"""Checkpoint inspection / reshaping helper.

Reference ``checkpoint/deepspeed_checkpoint.py`` (``DeepSpeedCheckpoint``) —
used by the universal converter and by migration tooling to enumerate a
checkpoint's parameters, topology, and iteration without a live engine.
"""

import json
import os

import numpy as np

from .constants import UNIVERSAL_META, ZERO_FILE_PREFIX


class DeepSpeedCheckpoint:
    """Read-only view over either an engine checkpoint or a universal one."""

    def __init__(self, ckpt_dir, tag=None):
        self.dir = ckpt_dir
        if tag is None:
            latest = os.path.join(ckpt_dir, "latest")
            if os.path.exists(latest):
                with open(latest) as f:
                    tag = f.read().strip()
        self.tag = tag
        self.root = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
        self._universal = os.path.exists(os.path.join(self.root, UNIVERSAL_META))
        self._meta = None
        self._state = None
        self._model_flat = None  # lazy cache: one orbax read serves all queries
        if self._universal:
            with open(os.path.join(self.root, UNIVERSAL_META)) as f:
                self._meta = json.load(f)
            self._state = self._meta.get("engine_state", {})
        else:
            es = os.path.join(self.root, "engine_state.json")
            if os.path.exists(es):
                with open(es) as f:
                    self._state = json.load(f)

    @property
    def is_universal(self):
        return self._universal

    def get_iteration(self):
        return (self._state or {}).get("global_steps", 0)

    @property
    def zero_stage(self):
        return (self._state or {}).get("zero_stage", 0)

    @property
    def dp_degree(self):
        return (self._state or {}).get("dp_world_size", 1)

    def _model(self):
        if self._model_flat is None:
            from .zero_to_fp32 import _restore_flat
            self._model_flat = _restore_flat(os.path.join(self.root, "model"))
        return self._model_flat

    def parameter_names(self):
        if self._universal:
            return sorted(self._meta.get("params", {}).keys())
        return sorted(self._model().keys())

    def parameter_shapes(self):
        if self._universal:
            return {k: tuple(v["shape"])
                    for k, v in self._meta.get("params", {}).items()}
        return {k: v.shape for k, v in self._model().items()}

    def get_parameter(self, name, key="fp32"):
        """Fetch one tensor. ``key`` ∈ {fp32, exp_avg, exp_avg_sq} for
        universal checkpoints."""
        if self._universal:
            path = os.path.join(self.root, ZERO_FILE_PREFIX, name, f"{key}.npy")
            if not os.path.exists(path):
                raise KeyError(f"{name}/{key} not in checkpoint")
            return np.load(path)
        flat = self._model()
        if name not in flat:
            raise KeyError(name)
        return np.asarray(flat[name])

    def show(self):
        names = self.parameter_names()
        print(f"checkpoint {self.root} (universal={self._universal}) "
              f"iteration={self.get_iteration()} params={len(names)}")
        for n in names:
            print(f"  {n}")
