"""Universal-checkpoint key names (reference ``deepspeed/checkpoint/constants.py``).

The per-parameter state files keep the reference's key vocabulary (``fp32``,
``exp_avg``, ``exp_avg_sq``, ``step``) so tooling written against DeepSpeed's
universal layout maps 1:1.
"""

FP32 = "fp32"
EXP_AVG = "exp_avg"
EXP_AVG_SQ = "exp_avg_sq"
STEP = "step"

# dir layout
ZERO_FILE_PREFIX = "zero"
UNIVERSAL_META = "universal_meta.json"
DS_VERSION = "ds_version"

# mapping from this framework's optimizer-state field names to the universal
# (torch-style) names the reference writes (ds_to_universal.py:232 merges
# "exp_avg"/"exp_avg_sq" slices).
STATE_FIELD_TO_UNIVERSAL = {
    "mu": EXP_AVG,
    "nu": EXP_AVG_SQ,
    "m": EXP_AVG,
    "v": EXP_AVG_SQ,
    "momentum": EXP_AVG,
    "exp_avg": EXP_AVG,
    "exp_avg_sq": EXP_AVG_SQ,
    "sum": EXP_AVG_SQ,   # adagrad squared-grad accumulator (torch key "sum")
}
UNIVERSAL_TO_STATE_FIELD = {EXP_AVG: "mu", EXP_AVG_SQ: "nu"}
