"""Consolidate a checkpoint into a single fp32 state dict.

Reference ``deepspeed/utils/zero_to_fp32.py`` — the offline recovery script
DeepSpeed copies into every checkpoint directory (``engine.py:3540``) so a
user can always extract full fp32 weights from ZeRO shards without the
training stack.  Here shards are orbax global arrays, so "merging" is a plain
host read; the public helpers keep the reference names.
"""

import argparse
import json
import os

import numpy as np


def _flatten(tree, prefix=""):
    """Nested dict/list → {'a/b/c': leaf}.  Shared with ds_to_universal
    (this file must stay standalone-copyable, so the helper lives here)."""
    out = {}
    if isinstance(tree, dict):
        items = tree.items()
    elif isinstance(tree, (list, tuple)):
        items = enumerate(tree)
    else:
        out[prefix.rstrip("/")] = tree
        return out
    for k, v in items:
        out.update(_flatten(v, f"{prefix}{k}/"))
    return out


def _restore_flat(path):
    import jax
    import orbax.checkpoint as ocp
    restored = ocp.PyTreeCheckpointer().restore(path)
    restored = jax.tree_util.tree_map(np.asarray, restored)
    return _flatten(restored)


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Return ``{param_name: np.float32 array}`` (reference function of the
    same name, zero_to_fp32.py)."""
    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
    root = os.path.join(checkpoint_dir, tag) if tag else checkpoint_dir
    if not os.path.isdir(root):
        raise FileNotFoundError(f"no checkpoint found at {root}")
    master = os.path.join(root, "master")
    model = os.path.join(root, "model")
    src = master if os.path.isdir(master) else model
    flat = _restore_flat(src)
    return {k: np.asarray(v, dtype=np.float32) for k, v in flat.items()}


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file,
                                               tag=None):
    """Write the consolidated fp32 state dict to ``output_file`` (.npz)."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=tag)
    out = output_file if output_file.endswith(".npz") else output_file + ".npz"
    np.savez(out, **{k.replace("/", "."): v for k, v in sd.items()})
    total = sum(v.size for v in sd.values())
    print(f"saved {len(sd)} tensors / {total:,} elements to {out}")
    return out


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Extract fp32 weights from a deepspeed_tpu checkpoint "
        "(reference zero_to_fp32.py)")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("-t", "--tag", default=None)
    args = p.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, tag=args.tag)


if __name__ == "__main__":
    main()
