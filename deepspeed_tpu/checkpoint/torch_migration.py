"""Migrate torch-DeepSpeed checkpoints → universal layout (round-1 review
item 7; reference format producers: ``runtime/engine.py:2723-2792`` name
scheme, ``runtime/zero/stage_1_and_2.py state_dict()`` contents; reference
consumer being mirrored: ``checkpoint/ds_to_universal.py:112
extract_zero_shards`` / ``:232 merge``).

Reads a ZeRO stage-0/1/2 **or stage-3** checkpoint directory written by
the torch DeepSpeed:

    {tag}/mp_rank_00_model_states.pt          "module": model state_dict
                                              (+ "param_shapes" at stage 3)
    {tag}/zero_pp_rank_{d}_mp_rank_00_optim_states.pt, one per dp rank:
      stage ≤2 — sd["optimizer_state_dict"]:
            "param_slice_mappings":  per group {name: fragment(start, numel)}
            "base_optimizer_state":  {"state": per group {"exp_avg": flat,
                                      "exp_avg_sq": flat[, "step": n]}}
            "single_partition_of_fp32_groups": per group flat fp32 partition
      stage 3 — sd["optimizer_state_dict"]:
            "fp32_flat_groups": [flat fp32 slice of EVERY param]
            "optimizer_state_dict": {"state": {0: {"exp_avg": flat, ...}}}

and reassembles full per-parameter fp32 weights + Adam moments (stage ≤2:
named fragments in dp order; stage 3: the per-param ceil(numel/dp) slice
walk of ``ds_to_universal.py:152``), then writes the universal layout
(``ds_to_universal.py`` output contract) under TORCH→FLAX renaming so
``load_universal_checkpoint`` can resume the run on a TPU mesh.

Unpickling note: those files reference ``deepspeed.utils.tensor_fragment.
fragment_address`` — a namedtuple from a package this environment doesn't
ship.  A shim module with a compatible namedtuple is registered before
``torch.load`` so the files open WITHOUT the torch DeepSpeed installed.
"""

import collections
import glob
import json
import os
import re
import sys
import types

import numpy as np

from ..utils.logging import logger
from .constants import DS_VERSION, UNIVERSAL_META, ZERO_FILE_PREFIX

# compatible stand-in for deepspeed.utils.tensor_fragment.fragment_address
fragment_address = collections.namedtuple("fragment_address",
                                          ["numel", "start"])


import contextlib


@contextlib.contextmanager
def _unpickle_shims():
    """Temporarily register shim modules so torch.load can resolve pickled
    references into the (absent) torch-DeepSpeed package.

    SCOPED: the shims are removed afterwards — a lingering fake ``deepspeed``
    in ``sys.modules`` (with ``__spec__`` None) breaks every later
    ``importlib.util.find_spec("deepspeed")`` (transformers probes exactly
    that)."""
    names = ("deepspeed", "deepspeed.utils", "deepspeed.utils.tensor_fragment",
             "deepspeed.runtime", "deepspeed.runtime.fp16",
             "deepspeed.runtime.fp16.loss_scaler",
             "deepspeed.runtime.zero", "deepspeed.runtime.zero.config")

    class _Anything:
        """Accept any pickled construction (LossScaler etc.) — migration
        only reads tensors and fragment maps."""

        def __init__(self, *a, **k):
            self.__dict__.update(k)

        def __setstate__(self, state):
            if isinstance(state, dict):
                self.__dict__.update(state)

    saved = {}
    try:
        for name in names:
            saved[name] = sys.modules.get(name)
            if saved[name] is None:
                mod = types.ModuleType(name)
                mod.__getattr__ = lambda attr, _c=_Anything: _c
                sys.modules[name] = mod
        # unconditional: hasattr would hit the _Anything __getattr__ fallback
        sys.modules["deepspeed.utils.tensor_fragment"].fragment_address = \
            fragment_address
        yield
    finally:
        for name in names:
            if saved.get(name) is None:
                sys.modules.pop(name, None)
            else:
                sys.modules[name] = saved[name]


def _torch_load(path):
    import torch
    with _unpickle_shims():
        return torch.load(path, map_location="cpu", weights_only=False)


def _to_numpy(t):
    import torch
    if isinstance(t, torch.Tensor):
        t = t.detach()
        if t.dtype == torch.bfloat16:
            t = t.float()
        return t.numpy()
    return np.asarray(t)


def default_torch_to_flax(name, arr):
    """Default torch-module → flax-module renaming:

    ``a.b.weight`` [out, in] → ``a/b/kernel`` transposed; 1-D ``weight``
    (norms) stays ``weight``; ``bias`` passes through; embeddings (≥2-D
    ``weight`` on a module whose name mentions embed) keep [V, D] as
    ``embedding``.  Return None to drop a key; supply a custom ``transform``
    for models with other conventions.
    """
    parts = name.split(".")
    leaf = parts[-1]
    prefix = "/".join(parts[:-1])
    if leaf == "weight":
        if arr.ndim >= 2 and "embed" in name.lower():
            return f"{prefix}/embedding", arr
        if arr.ndim == 2:
            return f"{prefix}/kernel", np.ascontiguousarray(arr.T)
        return f"{prefix}/weight", arr
    if leaf == "bias":
        return f"{prefix}/bias", arr
    return f"{prefix}/{leaf}", arr


def _resolve_tag(ckpt_dir, tag):
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
    return tag


def _assemble_stage2(module, shapes, optim_files, first_sd=None):
    """Stage ≤2: per-rank flat group partitions + named fragment maps
    (reference ``stage_1_and_2.py state_dict``; consumer
    ``ds_to_universal.py:112 extract_zero_shards``)."""
    state_parts = {"fp32": {}, "exp_avg": {}, "exp_avg_sq": {}}
    step = None
    for i, path in enumerate(optim_files):
        sd = first_sd if i == 0 and first_sd is not None else _torch_load(path)
        osd = sd.get("optimizer_state_dict", sd)
        if "param_slice_mappings" not in osd:
            raise ValueError(
                f"{os.path.basename(path)} is not a stage ≤2 optim file "
                "(no param_slice_mappings) — mixed-stage or truncated "
                "checkpoint?")
        slice_maps = osd["param_slice_mappings"]
        base_state = osd["base_optimizer_state"]["state"]
        fp32_groups = osd["single_partition_of_fp32_groups"]
        for gid, mapping in enumerate(slice_maps):
            flats = {"fp32": _to_numpy(fp32_groups[gid]),
                     "exp_avg": _to_numpy(base_state[gid]["exp_avg"]),
                     "exp_avg_sq": _to_numpy(base_state[gid]["exp_avg_sq"])}
            if step is None and "step" in base_state[gid]:
                step = int(_to_numpy(base_state[gid]["step"]))
            for name, frag in mapping.items():
                start, numel = int(frag.start), int(frag.numel)
                for key, flat in flats.items():
                    state_parts[key].setdefault(name, []).append(
                        flat[start:start + numel])

    assembled = {}
    for name, shape in shapes.items():
        if name not in state_parts["fp32"]:
            logger.warning(f"migration: no optimizer fragments for {name} "
                           "(frozen param?) — copying module weight")
            assembled[name] = (shape,
                               {"fp32": _to_numpy(module[name]).reshape(shape)})
            continue
        full = {}
        for key in state_parts:
            flat = np.concatenate(state_parts[key][name])
            numel = int(np.prod(shape))
            if flat.size < numel:
                raise ValueError(
                    f"{name}: fragments cover {flat.size} of {numel} "
                    "elements — checkpoint incomplete?")
            full[key] = flat[:numel].reshape(shape)
        assembled[name] = (shape, full)
    return assembled, step


def _assemble_stage3(model_sd, optim_files, zero_model_sds=(),
                     first_sd=None):
    """Stage 3: every param is split across ALL dp ranks; each rank's flat
    buffer concatenates its ceil(numel/dp)-sized slice of every param in
    ``param_shapes`` order (reference producer ``stage3.py state_dict``
    [fp32_flat_groups]; consumer ``ds_to_universal.py:152
    extract_zero_shards_stage3`` — this mirrors its offset walk).

    ``zero_model_sds``: the per-dp-rank ``zero_pp_rank_*_model_states.pt``
    dicts, used for frozen params (absent from fp32_flat_groups): each rank
    stores its ``ds_tensor`` partition in ``frozen_param_fragments``
    (reference merge: ``utils/zero_to_fp32.py _zero3_merge_frozen_params``)."""
    shapes_raw = model_sd.get("param_shapes")
    if shapes_raw is None:
        raise ValueError(
            "stage-3 optim files present but model_states carries no "
            "param_shapes — not a complete ZeRO-3 checkpoint")
    param_shapes = {}
    if isinstance(shapes_raw, (list, tuple)):
        for d in shapes_raw:
            param_shapes.update(d)
    else:
        param_shapes.update(shapes_raw)

    dp = len(optim_files)
    ranks = {"fp32": [], "exp_avg": [], "exp_avg_sq": []}
    step = None
    for i, path in enumerate(optim_files):
        sd = first_sd if i == 0 and first_sd is not None else _torch_load(path)
        osd = sd.get("optimizer_state_dict", sd)
        if "fp32_flat_groups" not in osd:
            raise ValueError(
                f"{os.path.basename(path)} is not a stage-3 optim file "
                "(no fp32_flat_groups) — mixed-stage or truncated "
                "checkpoint?")
        groups = osd["fp32_flat_groups"]
        inner = osd["optimizer_state_dict"]["state"]
        if len(groups) != 1 or len(inner) != 1:
            raise NotImplementedError(
                f"stage-3 migration supports a single param group; got "
                f"{len(groups)} flat groups / {len(inner)} state groups "
                "(reference ds_to_universal.py:158 reads group 0 only)")
        st = inner[0] if 0 in inner else next(iter(inner.values()))
        ranks["fp32"].append(_to_numpy(groups[0]))
        ranks["exp_avg"].append(_to_numpy(st["exp_avg"]))
        ranks["exp_avg_sq"].append(_to_numpy(st["exp_avg_sq"]))
        if step is None and "step" in st:
            step = int(_to_numpy(st["step"]))

    assembled = {}
    offset = 0
    for name, shape in param_shapes.items():
        shape = tuple(int(x) for x in shape)
        numel = int(np.prod(shape)) if shape else 1
        pn = -(-numel // dp)  # ceil: per-rank slice incl. tail padding
        full = {}
        for key, flats in ranks.items():
            segs = []
            for r in range(dp):
                # DELIBERATE deviation from the reference's
                # ds_to_universal.py:165 ``min(pn, abs(numel - r*pn))``: for
                # ranks past the data (numel=5, dp=4 → rank 3) abs() would
                # read padding bytes as parameters; the clamp at 0 is the
                # mathematically correct count.  Do not "fix" this back to
                # mirror the reference (ADVICE r3).
                valid = max(0, min(pn, numel - r * pn))
                if valid:
                    segs.append(flats[r][offset:offset + valid])
            flat = np.concatenate(segs) if segs else np.zeros(0, np.float32)
            if flat.size != numel:
                raise ValueError(
                    f"{name}: stage-3 slices cover {flat.size} of {numel} "
                    "elements — dp degree / param_shapes mismatch?")
            full[key] = flat.reshape(shape)
        assembled[name] = (shape, full)
        offset += pn

    # frozen params: per-rank ds_tensor fragments concatenated then
    # narrowed to numel (reference _zero3_merge_frozen_params)
    frozen_shapes = (zero_model_sds[0].get("frozen_param_shapes")
                     if zero_model_sds else
                     model_sd.get("frozen_param_shapes")) or {}
    for name, shape in frozen_shapes.items():
        shape = tuple(int(x) for x in shape)
        numel = int(np.prod(shape)) if shape else 1
        sds = zero_model_sds or (model_sd, )
        frags = []
        for sd in sds:
            fragments = sd.get("frozen_param_fragments") or {}
            if name in fragments:
                frags.append(_to_numpy(fragments[name]).reshape(-1))
        if not frags:
            raise ValueError(
                f"frozen param {name} listed in frozen_param_shapes but no "
                "rank carries its fragment — incomplete stage-3 checkpoint")
        flat = np.concatenate(frags)
        if flat.size < numel:
            raise ValueError(
                f"frozen param {name}: fragments cover {flat.size} of "
                f"{numel} elements — missing per-rank "
                "zero_pp_rank_*_model_states.pt files?")
        assembled[name] = (shape, {"fp32": flat[:numel].reshape(shape)})
    return assembled, step


def _write_universal(output_dir, assembled, transform, step, global_steps,
                     root):
    zero_root = os.path.join(output_dir, ZERO_FILE_PREFIX)
    os.makedirs(zero_root, exist_ok=True)
    param_meta = {}
    for name, (shape, full) in assembled.items():
        mapped = transform(name, full["fp32"])
        if mapped is None:
            continue
        new_name, _ = mapped
        pdir = os.path.join(zero_root, new_name)
        os.makedirs(pdir, exist_ok=True)
        for key, arr in full.items():
            _, out = transform(name, arr)
            np.save(os.path.join(pdir, f"{key}.npy"),
                    out.astype(np.float32))
        param_meta[new_name] = {"shape": list(mapped[1].shape),
                                "dtype": "float32",
                                "source": name}

    meta = {
        "engine_state": {"global_steps": global_steps},
        "step": step if step is not None else global_steps,
        "params": param_meta,
        "migrated_from": "torch-deepspeed",
    }
    with open(os.path.join(output_dir, UNIVERSAL_META), "w") as f:
        json.dump(meta, f, indent=2)
    from .. import __version__
    with open(os.path.join(output_dir, DS_VERSION), "w") as f:
        f.write(__version__)
    logger.info(f"migrated {len(param_meta)} params from torch checkpoint "
                f"{root} → {output_dir}")
    return output_dir


def migrate_torch_checkpoint(checkpoint_dir, output_dir, tag=None,
                             transform=default_torch_to_flax):
    """Convert a torch-DeepSpeed ZeRO (stage 0-3) checkpoint into the
    universal layout at ``output_dir``.  Returns ``output_dir``.

    Stage detection is by optim-file contents: stage ≤2 files carry
    ``single_partition_of_fp32_groups`` + ``param_slice_mappings``; stage-3
    files carry ``fp32_flat_groups`` with ``param_shapes`` in the model
    states (reference ``ds_to_universal.py:486 _check_for_required_state``)."""
    tag = _resolve_tag(checkpoint_dir, tag)
    root = os.path.join(checkpoint_dir, tag) if tag else checkpoint_dir
    if not os.path.isdir(root):
        raise FileNotFoundError(f"no checkpoint at {root}")

    model_files = sorted(glob.glob(os.path.join(root,
                                                "mp_rank_*_model_states.pt")))
    # stage-3 checkpoints also (or only) write per-dp-rank model states
    # carrying frozen-param fragments (zero_to_fp32.py:76 naming)
    zero_model_files = sorted(
        glob.glob(os.path.join(root, "zero_pp_rank_*_model_states.pt")),
        key=lambda p: [int(x) for x in re.findall(r"rank_(\d+)", p)])
    if not model_files and not zero_model_files:
        raise FileNotFoundError(f"no *_model_states.pt under {root}")
    if len(model_files) > 1:
        raise NotImplementedError(
            "TP-sharded torch checkpoints (mp>1) need merge_tp_slices — "
            "single-mp migration is supported")
    model_sd = _torch_load(model_files[0] if model_files
                           else zero_model_files[0])
    module = model_sd.get("module", model_sd) or {}
    shapes = {k: tuple(v.shape) for k, v in module.items()
              if hasattr(v, "shape")}

    optim_files = sorted(
        glob.glob(os.path.join(root, "*_optim_states.pt")),
        key=lambda p: [int(x) for x in re.findall(r"rank_(\d+)", p)])
    if not optim_files:
        # weights-only checkpoint: migrate module weights alone (each param
        # takes the copy-module-weight branch with a warning)
        assembled, step = _assemble_stage2(module, shapes, optim_files)
    else:
        first = _torch_load(optim_files[0])
        first_osd = first.get("optimizer_state_dict", first)
        if "single_partition_of_fp32_groups" in first_osd:
            assembled, step = _assemble_stage2(module, shapes, optim_files,
                                               first_sd=first)
        elif "fp32_flat_groups" in first_osd:
            # load the per-rank model states only when frozen params exist
            # (a dp=64 run would otherwise unpickle 64 files for nothing)
            zero_model_sds = ()
            if zero_model_files:
                rank0 = _torch_load(zero_model_files[0])
                if rank0.get("frozen_param_shapes"):
                    zero_model_sds = (rank0, ) + tuple(
                        _torch_load(p) for p in zero_model_files[1:])
            assembled, step = _assemble_stage3(model_sd, optim_files,
                                               zero_model_sds,
                                               first_sd=first)
        else:
            raise ValueError(
                f"{os.path.basename(optim_files[0])} is neither a stage ≤2 "
                "(single_partition_of_fp32_groups) nor a stage-3 "
                "(fp32_flat_groups) optim file")
    return _write_universal(output_dir, assembled, transform, step,
                            model_sd.get("global_steps", 0), root)


def load_torch_deepspeed_checkpoint(engine, checkpoint_dir, tag=None,
                                    transform=default_torch_to_flax):
    """One-call resume from a torch-DeepSpeed checkpoint: migrate into a
    scratch universal directory, then ``load_universal_checkpoint``."""
    import tempfile
    from .universal_checkpoint import load_universal_checkpoint
    with tempfile.TemporaryDirectory(prefix="ds_tpu_migrate_") as tmp:
        migrate_torch_checkpoint(checkpoint_dir, tmp, tag=tag,
                                 transform=transform)
        return load_universal_checkpoint(engine, tmp)


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        description="Migrate a torch-DeepSpeed ZeRO (stage 0-3) checkpoint "
        "to the universal layout")
    p.add_argument("--input_folder", required=True)
    p.add_argument("--output_folder", required=True)
    p.add_argument("--tag", default=None)
    args = p.parse_args(argv)
    migrate_torch_checkpoint(args.input_folder, args.output_folder,
                             tag=args.tag)
    print(f"universal checkpoint written to {args.output_folder}")


if __name__ == "__main__":
    main()
