"""Universal checkpointing (reference ``deepspeed/checkpoint/``).

DP/TP/PP-degree-independent resume: a converter turns an engine checkpoint
into per-parameter fp32 "hp" slices (reference ``checkpoint/ds_to_universal.py``),
and a loader repartitions them under a new mesh topology (reference
``checkpoint/universal_checkpoint.py:22``).
"""

from .constants import (EXP_AVG, EXP_AVG_SQ, FP32, STEP, UNIVERSAL_META,
                        ZERO_FILE_PREFIX)
from .deepspeed_checkpoint import DeepSpeedCheckpoint
from .ds_to_universal import convert_to_universal
from .universal_checkpoint import load_universal_checkpoint
from .zero_to_fp32 import (convert_zero_checkpoint_to_fp32_state_dict,
                           get_fp32_state_dict_from_zero_checkpoint)
