"""Serving config block (``ServingConfig``) — scheduler-side knobs layered
over the engine's :class:`RaggedInferenceEngineConfig` (which owns the
batching/KV geometry: token budget, block size, ``kv_cache_dtype``, decode
burst)."""

from typing import Optional

from ..runtime.config_utils import DeepSpeedConfigModel


class ServingConfig(DeepSpeedConfigModel):
    #: in-flight sequence cap; clamped to the engine's slot count
    #: (``max_ragged_sequence_count`` − 1 — slot 0 is the padding slot)
    max_concurrent: int = 64
    #: admission queue bound; 0 = unbounded.  A full queue makes ``submit``
    #: raise :class:`~deepspeed_tpu.serving.scheduler.AdmissionQueueFull` —
    #: the caller-visible backpressure signal
    max_queue_depth: int = 0
    #: KV-pressure admission gate: a request is admitted only when
    #: ``blocks_for(len(prompt) + reserve) + floor ≤ free_blocks``.  None →
    #: one block of decode headroom (the first decode block is the one a
    #: just-admitted request always grows into)
    kv_admit_reserve_tokens: Optional[int] = None
    #: free blocks the admission gate keeps in reserve for the sequences
    #: already running (decode growth) — raises the backpressure threshold
    kv_free_block_floor: int = 0
    #: cap on consecutive preemptions inside ONE scheduler step before the
    #: exhaustion is re-raised to the caller (a single request bigger than
    #: the whole pool must fail loudly, not evict the world)
    max_preemptions_per_step: int = 8

    # sampling (greedy by default; sampled serving keeps the per-step loop
    # unless the engine's decode_burst_sampling opts into the device PRNG)
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None

    #: replica-health heartbeats (elasticity watchdog): directory to beat
    #: into once per scheduler step; None → honor ``DS_TPU_HEARTBEAT_DIR``
    #: when the elastic agent exported it, else no heartbeat
    heartbeat_dir: Optional[str] = None
    #: rank stamped into the heartbeat file name
    heartbeat_rank: int = 0
