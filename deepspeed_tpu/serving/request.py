"""Typed request lifecycle for the serving engine.

A :class:`Request` is the unit the scheduler moves through the state
machine::

    QUEUED ──admit──▶ PREFILL ──first token──▶ DECODE ──EOS/max──▶ DONE
       ▲                 │                        │
       └──requeue── EVICTED ◀──────preempt────────┘

Transitions are validated (:meth:`Request.transition`): an illegal edge is
a scheduler bug and raises immediately instead of corrupting accounting.
``EVICTED`` is transient under the default preempt-and-requeue policy —
the scheduler re-queues the victim at the FRONT of the admission queue
(LIFO among victims) with its full token history, so re-admission
recomputes the KV prefix and greedy decoding continues token-identically.

Latency accounting lives here too: TTFT (submit → first generated token)
and the per-token gaps (TBT) the serve bench folds into p50/p99.
"""

import enum
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional


class RequestState(enum.Enum):
    QUEUED = "queued"      # in the admission queue, no KV held
    PREFILL = "prefill"    # admitted; prompt (or recovery) tokens streaming
    DECODE = "decode"      # producing tokens, one per engine iteration
    DONE = "done"          # completed (EOS or max_new_tokens); KV released
    EVICTED = "evicted"    # preempted under KV pressure; KV released


#: legal edges of the lifecycle (EVICTED → QUEUED is the requeue path;
#: QUEUED → DONE covers cancellation before admission)
_TRANSITIONS = {
    RequestState.QUEUED: (RequestState.PREFILL, RequestState.DONE),
    RequestState.PREFILL: (RequestState.DECODE, RequestState.EVICTED,
                           RequestState.DONE),
    RequestState.DECODE: (RequestState.DONE, RequestState.EVICTED),
    RequestState.EVICTED: (RequestState.QUEUED, ),
    RequestState.DONE: (),
}


class IllegalTransition(RuntimeError):
    """A lifecycle edge outside the state machine — a scheduler bug."""


@dataclass
class Request:
    """One serving request and its full accounting record."""

    uid: int
    prompt: List[int]
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    #: streaming callback ``on_token(token: int, done: bool)`` — invoked
    #: once per generated token, from the scheduler thread
    on_token: Optional[Callable[[int, bool], None]] = None

    state: RequestState = RequestState.QUEUED
    produced: List[int] = field(default_factory=list)
    preemptions: int = 0
    #: monotonically increasing admission ticket — the LIFO preemption key
    admit_order: int = -1

    # latency bookkeeping (scheduler clock timestamps, seconds)
    t_submit: float = field(default_factory=time.perf_counter)
    t_admit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_last_token: Optional[float] = None
    #: decode-phase inter-token gaps (seconds) — the TBT histogram feed
    token_gaps: List[float] = field(default_factory=list)

    def transition(self, new_state):
        if new_state not in _TRANSITIONS[self.state]:
            raise IllegalTransition(
                f"request {self.uid}: {self.state.name} → {new_state.name} "
                "is not a lifecycle edge")
        self.state = new_state

    # ------------------------------------------------------------- recording
    def record_token(self, tok, now, done):
        """Book one generated token: stream it, stamp TTFT on the first."""
        if self.t_first_token is None:
            self.t_first_token = now
        elif self.t_last_token is not None:
            self.token_gaps.append(now - self.t_last_token)
        self.t_last_token = now
        self.produced.append(int(tok))
        if self.on_token is not None:
            self.on_token(int(tok), done)

    @property
    def remaining_tokens(self):
        return max(0, self.max_new_tokens - len(self.produced))

    @property
    def resume_tokens(self):
        """Token history a preempted request re-enters the engine with:
        prompt + everything already produced (the KV prefix to recompute)."""
        return list(self.prompt) + list(self.produced)

    @property
    def ttft(self):
        """Submit → first token, seconds (None until the first token)."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def mean_tbt(self):
        if not self.token_gaps:
            return None
        return sum(self.token_gaps) / len(self.token_gaps)
