"""Continuous in-flight batching scheduler — the production request path
over :class:`~deepspeed_tpu.inference.v2.InferenceEngineV2`.

FastGen-class serving loop (reference ``mii``/DeepSpeed-FastGen): an
admission queue feeds a token-budget engine that keeps a mixed batch of
prefill chunks and decode tokens in flight every iteration.  What this
layer adds over the raw engine:

* **admission with KV-pressure backpressure** — a request is admitted only
  when the block pool can hold its prompt plus decode headroom
  (``ServingConfig.kv_admit_reserve_tokens`` / ``kv_free_block_floor``);
  a bounded queue turns overload into a typed
  :class:`AdmissionQueueFull` instead of unbounded memory growth;
* **LIFO preemption-and-requeue** — when the engine raises
  :class:`~deepspeed_tpu.inference.v2.KVCacheExhausted` (a *capacity*
  signal, typed precisely so bugs don't get preempted around), the most
  recently admitted request is evicted: its blocks are flushed and it
  re-enters the admission queue at the FRONT with its full token history,
  so re-admission recomputes the KV prefix and greedy decoding continues
  token-identically;
* **prefill/decode disaggregation** — the engine's two-layout atom
  machinery (``engine_v2._atom_layout``) packs the regions; the scheduler
  classifies each iteration (``prefill`` / ``decode`` / ``mixed``) and
  books it as a telemetry span, and fuses multi-token decode bursts when
  every in-flight sequence is in pure decode;
* **streaming** — per-token ``on_token(token, done)`` callbacks as tokens
  are produced, not when the request completes;
* **observability + health** — per-request TTFT/TBT histograms,
  queue-depth/KV-occupancy/preemption gauges on the PR 6 telemetry spine,
  and a PR 3 watchdog heartbeat per scheduler step for replica health.
"""

import os
import time
from collections import deque

from .. import telemetry
from ..elasticity.watchdog import HEARTBEAT_DIR_ENV, HeartbeatWriter
from ..inference.v2.ragged import KVCacheExhausted
from ..utils.logging import logger
from .config import ServingConfig
from .request import Request, RequestState


class AdmissionQueueFull(RuntimeError):
    """The bounded admission queue rejected a submit — caller-visible
    backpressure (shed load upstream or retry later)."""


class ServingScheduler:
    """Drives one :class:`InferenceEngineV2` as a continuously batched
    serving replica.  Single-threaded by design: ``submit`` enqueues,
    ``step`` runs one engine iteration, ``drain``/``serve`` loop for you —
    a thread or asyncio wrapper owns the loop in a real deployment (the
    engine is synchronous per step, see ``engine_v2.py`` module docstring).
    """

    def __init__(self, engine, config=None, clock=time.perf_counter):
        if config is None:
            config = ServingConfig()
        elif isinstance(config, dict):
            config = ServingConfig(**config)
        self.engine = engine
        self.config = config
        self._clock = clock
        self._queue = deque()          # Request admission queue (FIFO)
        self._running = {}             # uid -> Request (admitted, holds KV)
        self._all = {}                 # uid -> Request (every submit)
        self._next_uid = 0
        self._admit_ticket = 0         # LIFO preemption key source
        self._step_index = 0
        self.preemptions = 0
        self.completed = 0
        self.tokens_generated = 0
        self.peak_running = 0          # max concurrently admitted sequences
        # in-flight cap: the engine has max_seqs slots, slot 0 reserved
        self._max_concurrent = min(
            int(config.max_concurrent),
            engine.state_manager.max_seqs - 1)
        hb_dir = config.heartbeat_dir or os.environ.get(HEARTBEAT_DIR_ENV)
        self._heartbeat = HeartbeatWriter(
            hb_dir, rank=config.heartbeat_rank) if hb_dir else None

    # ---------------------------------------------------------------- submit
    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               on_token=None, uid=None):
        """Queue a request; returns its uid.  Raises
        :class:`AdmissionQueueFull` when the bounded queue is at depth."""
        depth = self.config.max_queue_depth
        if depth and len(self._queue) >= depth:
            raise AdmissionQueueFull(
                f"admission queue at max_queue_depth={depth} "
                f"({len(self._running)} running) — shed load or retry")
        if uid is None:
            uid = self._next_uid
        if isinstance(uid, int):
            # explicit uids may be any hashable the engine accepts; only
            # ints advance the auto-uid counter
            self._next_uid = max(self._next_uid, uid + 1)
        if uid in self._all and self._all[uid].state is not RequestState.DONE:
            raise ValueError(f"uid {uid!r} is already live "
                             f"({self._all[uid].state.name})")
        req = Request(uid=uid, prompt=[int(t) for t in prompt],
                      max_new_tokens=int(max_new_tokens),
                      eos_token_id=eos_token_id, on_token=on_token,
                      t_submit=self._clock())
        self._all[uid] = req
        self._queue.append(req)
        if telemetry.enabled:
            telemetry.counter("serving/requests_submitted",
                              help="requests accepted into the admission "
                              "queue").inc()
        return uid

    def query(self, uid):
        """The :class:`Request` record (live or finished) for ``uid``."""
        return self._all.get(uid)

    # ------------------------------------------------------------- admission
    def _admit_blocks_needed(self, req):
        """Blocks the admission gate charges a request for: its (resume)
        prompt plus decode headroom."""
        reserve = self.config.kv_admit_reserve_tokens
        if reserve is None:
            reserve = self.engine.kv_cache.block_size   # one decode block
        return self.engine.kv_cache.blocks_for(
            len(req.resume_tokens) + int(reserve))

    def _outstanding_claims(self):
        """Blocks the already-running sequences are still expected to take
        from the pool (their token history + decode reserve, minus what
        they physically hold) — the engine only materializes blocks at
        schedule time, so the admission gate must count claims, not just
        the instantaneous free list."""
        sm = self.engine.state_manager
        reserve = self.config.kv_admit_reserve_tokens
        if reserve is None:
            reserve = self.engine.kv_cache.block_size
        total = 0
        for uid in self._running:
            seq = sm.get_sequence(uid)
            total += max(0, self.engine.kv_cache.blocks_for(
                len(seq.tokens) + int(reserve)) - len(seq.blocks))
        return total

    def _admit(self):
        sm = self.engine.state_manager
        while self._queue and len(self._running) < self._max_concurrent:
            req = self._queue[0]
            need = self._admit_blocks_needed(req)
            free = (sm.free_blocks - int(self.config.kv_free_block_floor)
                    - self._outstanding_claims())
            if self._running and need > free:
                # KV pressure: hold admission until blocks free up.  With
                # NOTHING running the head request is admitted regardless —
                # chunked prefill + the engine's deferral can still serve a
                # prompt bigger than the instantaneous free pool, and an
                # impossible request must fail loudly, not deadlock quietly.
                break
            self._queue.popleft()
            self.engine.put([req.uid], [req.resume_tokens])
            req.transition(RequestState.PREFILL)
            req.t_admit = self._clock()
            req.admit_order = self._admit_ticket
            self._admit_ticket += 1
            self._running[req.uid] = req
            self.peak_running = max(self.peak_running, len(self._running))
            if telemetry.enabled:
                telemetry.counter("serving/requests_admitted",
                                  help="admission-queue → engine "
                                  "transitions (re-admissions included)"
                                  ).inc()

    # ------------------------------------------------------------ preemption
    def _preempt_one(self):
        """Evict the most recently admitted request (LIFO) and requeue it
        at the FRONT of the admission queue with its full token history.
        Returns False when there is nothing sensible to evict (≤1 running —
        evicting the only runner cannot free enough to run it)."""
        if len(self._running) <= 1:
            return False
        victim = max(self._running.values(), key=lambda r: r.admit_order)
        self.engine.flush([victim.uid])
        del self._running[victim.uid]
        victim.transition(RequestState.EVICTED)
        victim.preemptions += 1
        self.preemptions += 1
        victim.transition(RequestState.QUEUED)
        self._queue.appendleft(victim)
        logger.info(
            "serving: preempted uid %s (%d produced, %d prompt tokens) "
            "under KV pressure — requeued at front", victim.uid,
            len(victim.produced), len(victim.prompt))
        if telemetry.enabled:
            telemetry.counter("serving/preemptions",
                              help="LIFO evictions under KV pressure").inc()
        return True

    # ----------------------------------------------------------------- steps
    def _phase(self):
        """Step classification for span attribution: what work is pending
        across the in-flight batch right now."""
        n_prefill = n_decode = 0
        for uid in self._running:
            seq = self.engine.state_manager.get_sequence(uid)
            pending = len(seq.tokens) - seq.seen_tokens
            if pending > 1:
                n_prefill += 1
            elif pending == 1:
                n_decode += 1
        if n_prefill and n_decode:
            return "mixed"
        return "prefill" if n_prefill else "decode"

    def _try_burst(self):
        """Fused multi-token decode when EVERY in-flight sequence is in
        pure decode (same eligibility as ``generate``'s burst path).
        Returns {uid: [tokens]} or None (ineligible / pool too tight)."""
        cap = int(self.engine._config.decode_burst or 0)
        if cap < 2 or not self._running:
            return None
        cfg = self.config
        if cfg.do_sample and not (
                self.engine._config.decode_burst_sampling
                and cfg.seed is not None):
            return None   # host-RNG sampling keeps the per-step loop
        sm = self.engine.state_manager
        k = cap
        for req in self._running.values():
            seq = sm.get_sequence(req.uid)
            if len(seq.tokens) - seq.seen_tokens != 1:
                return None
            k = min(k, req.remaining_tokens)
        if k < 2:
            return None
        out = self.engine.burst_decode(
            list(self._running), max_tokens=k, do_sample=cfg.do_sample,
            temperature=cfg.temperature, top_k=cfg.top_k, top_p=cfg.top_p,
            rng=cfg.seed)
        return out or None

    def step(self):
        """One scheduler iteration: admit → run one engine step (preempting
        under KV exhaustion) → stream tokens.  Returns {uid: [tokens]}
        emitted this step (empty when idle)."""
        self._admit()
        self._step_index += 1
        if self._heartbeat is not None:
            self._heartbeat.beat(self._step_index)
        if not self._running:
            self._export_gauges()
            return {}
        if telemetry.enabled:
            telemetry.begin_step(self._step_index)
        phase = self._phase()
        t_launch = self._clock()     # before the engine call — _dispatch
        preempts = 0                 # amortizes burst wall time over tokens
        while True:
            try:
                if telemetry.enabled:
                    telemetry.begin_span(phase, cat="serve")
                try:
                    burst = self._try_burst()
                    if burst is not None:
                        results = burst
                    else:
                        cfg = self.config
                        results = self.engine.schedule_step(
                            do_sample=cfg.do_sample,
                            temperature=cfg.temperature, top_k=cfg.top_k,
                            top_p=cfg.top_p, rng=cfg.seed)
                finally:
                    if telemetry.enabled:
                        telemetry.end_span(phase)
                break
            except KVCacheExhausted as e:
                preempts += 1
                if preempts > int(self.config.max_preemptions_per_step) \
                        or not self._preempt_one():
                    raise KVCacheExhausted(
                        e.wanted_blocks, e.free_blocks,
                        detail="not recoverable by preemption — the "
                        "request needs more blocks than the pool holds "
                        "(raise state_manager.num_blocks or lower "
                        "max_context)") from e
        emitted = self._dispatch(results, t_launch)
        self._export_gauges(n_tokens=sum(len(v) for v in emitted.values()))
        return emitted

    def _dispatch(self, results, t_launch=None):
        """Book engine output into request records: streaming callbacks,
        lifecycle transitions, completion + immediate flush (blocks return
        to the pool the moment a request finishes).  Burst results arrive
        k-at-a-time from one engine call; their timestamps interpolate over
        [t_launch, now] so the TBT accounting reflects per-token cost, not
        k−1 fabricated zero gaps plus one burst-sized one."""
        now = self._clock()
        if t_launch is None:
            t_launch = now
        sm = self.engine.state_manager
        emitted = {}
        for uid, toks in results.items():
            req = self._running.get(uid)
            if req is None:      # flushed between schedule and dispatch
                continue
            if isinstance(toks, int):
                toks = [toks]
            burst = len(toks) > 1
            out = emitted.setdefault(uid, [])
            for i, tok in enumerate(toks):
                t_tok = (now if not burst else
                         t_launch + (i + 1) * (now - t_launch) / len(toks))
                done = ((req.eos_token_id is not None
                         and tok == req.eos_token_id)
                        or len(req.produced) + 1 >= req.max_new_tokens)
                if req.state is RequestState.PREFILL:
                    req.transition(RequestState.DECODE)
                req.record_token(tok, t_tok, done)
                out.append(int(tok))
                if telemetry.enabled:
                    telemetry.counter("serving/tokens_generated",
                                      help="tokens streamed to callers"
                                      ).inc()
                self.tokens_generated += 1
                if done:
                    # overshoot past EOS inside a burst window is garbage
                    # the flush drops; ``produced`` truncates exactly
                    req.transition(RequestState.DONE)
                    sm.get_sequence(uid).done = True
                    self.engine.flush([uid])
                    del self._running[uid]
                    self.completed += 1
                    if telemetry.enabled:
                        telemetry.counter("serving/requests_completed",
                                          help="requests finished (EOS or "
                                          "max_new_tokens)").inc()
                        if req.ttft is not None:
                            telemetry.observe("serving/ttft_seconds",
                                              req.ttft,
                                              help="submit → first token")
                        for gap in req.token_gaps:
                            telemetry.observe("serving/tbt_seconds", gap,
                                              help="decode inter-token gap")
                    break
                if not burst:
                    # per-step decode feedback (the burst path already
                    # extended the engine-side token history on device)
                    sm.get_sequence(uid).tokens.append(int(tok))
        return emitted

    def _export_gauges(self, n_tokens=0):
        if not telemetry.enabled:
            return
        sm = self.engine.state_manager
        total = self.engine.kv_cache.num_blocks - 1   # minus garbage block
        used = total - sm.free_blocks
        telemetry.gauge("serving/queue_depth",
                        help="requests waiting for admission"
                        ).set(len(self._queue))
        telemetry.gauge("serving/running_sequences",
                        help="requests holding KV blocks"
                        ).set(len(self._running))
        telemetry.gauge("serving/kv_free_blocks").set(sm.free_blocks)
        telemetry.gauge("serving/kv_occupancy_frac",
                        help="used / usable KV blocks"
                        ).set(used / total if total else 0.0)
        if telemetry.get_recorder() is not None:
            try:
                from ..runtime.utils import memory_usage_snapshot
                snap = memory_usage_snapshot()
                telemetry.record_hbm(
                    {k: snap[k] for k in ("live_bytes", "peak_bytes",
                                          "limit_bytes")})
            except Exception:
                pass   # telemetry must never kill a serving step
            telemetry.end_step(metrics={
                "tokens": n_tokens,
                "serve_running": len(self._running),
                "serve_queue_depth": len(self._queue),
            })

    # ----------------------------------------------------------- convenience
    @property
    def idle(self):
        """No queued and no running work."""
        return not self._queue and not self._running

    def drain(self, max_steps=100_000):
        """Step until every submitted request completes."""
        steps = 0
        while not self.idle:
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"serving drain did not converge in {max_steps} steps "
                    f"({len(self._queue)} queued, {len(self._running)} "
                    "running)")
        return steps

    def serve(self, prompts, max_new_tokens=32, eos_token_id=None):
        """Batch convenience (tests/bench): submit all, drain, return the
        produced tokens in submit order."""
        uids = [self.submit(p, max_new_tokens=max_new_tokens,
                            eos_token_id=eos_token_id) for p in prompts]
        self.drain()
        return [self._all[u].produced for u in uids]


def build_serving_engine(model, params=None, engine_config=None,
                         serving_config=None):
    """One-call replica: ``InferenceEngineV2`` + :class:`ServingScheduler`.
    ``engine_config`` may carry ``kv_cache_dtype: "int8"|"fp8"`` for the
    quantized paged-KV mode."""
    from ..inference.v2 import InferenceEngineV2
    engine = InferenceEngineV2(model, params=params, config=engine_config)
    return ServingScheduler(engine, config=serving_config)
