"""deepspeed_tpu.serving — the production serving engine.

Continuous in-flight batching over the FastGen-style ragged engine
(``inference/v2``): typed request lifecycle (QUEUED → PREFILL → DECODE →
DONE/EVICTED), token-budget admission with KV-pressure backpressure, LIFO
preemption-and-requeue on KV exhaustion, streaming per-token callbacks,
and the quantized paged-KV mode (``kv_cache_dtype: int8|fp8``).  See
docs/serving.md; ``tools/serve_bench.py`` is the traffic driver.
"""

from .config import ServingConfig                          # noqa: F401
from .request import (IllegalTransition, Request,           # noqa: F401
                      RequestState)
from .scheduler import (AdmissionQueueFull,                 # noqa: F401
                        ServingScheduler, build_serving_engine)
