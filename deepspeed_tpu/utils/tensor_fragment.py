"""Tensor-fragment API — safe access to sharded / high-precision state.

Reference ``deepspeed/utils/tensor_fragment.py:132-299``:
``safe_get_full_fp32_param`` etc. let user code read/modify the fp32 master
weights, gradients, and optimizer states regardless of ZeRO stage, because
under ZeRO the torch ``param.data`` is a shard or empty.  Here parameters are
jax global arrays, so "full" access is a host gather (``np.asarray`` of the
global array triggers the all-gather) and "local" access reads the
addressable shard; setters re-``device_put`` with the engine's sharding so
the partitioned layout is preserved.

All functions take ``(engine, name)`` where ``name`` is the ``path_str`` of
the parameter ('layer/kernel' style); pass ``engine.parameter_names()`` to
enumerate.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _flat_with_names(tree):
    from ..runtime.zero.partition import path_str
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {path_str(kp): leaf for kp, leaf in flat}


def _lookup(tree, name):
    if tree is None:
        return None
    return _flat_with_names(tree).get(name)


def _set_leaf(tree, name, value):
    from ..runtime.zero.partition import path_str
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = []
    found = False
    for kp, leaf in flat:
        if path_str(kp) == name:
            found = True
            leaves.append(value)
        else:
            leaves.append(leaf)
    if not found:
        raise KeyError(name)
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(tree), leaves)


def _resident(engine, *attrs):
    """Restore ONLY the named offloaded trees ("params"/"master"/
    "opt_state") to device before a fragment access — restoring everything
    would re-fill the HBM that offload_states() just freed.  The NVMe path
    stores master+opt_state as one unit, so either name triggers its
    swap-in."""
    off = getattr(engine, "_host_offloaded", None)
    if off:
        for attr in attrs:
            if attr in off:
                host, sh = off[attr]
                setattr(engine, attr, jax.tree_util.tree_map(
                    jax.device_put, host, sh))
                del off[attr]  # only after the puts succeeded — a failed
                # restore must not drop the sole (host) copy of the state
    if ({"master", "opt_state"} & set(attrs)
            and getattr(engine, "_state_on_nvme", False)):
        engine._ensure_state_resident()
    return engine


def _host_tree(engine, attr):
    """The host copy of an offloaded tree, if present (no device transfer)."""
    off = getattr(engine, "_host_offloaded", None) or {}
    return off[attr][0] if attr in off else None


def parameter_names(engine):
    # tree structure only — the host copy suffices, no residency needed
    params = engine.params if engine.params is not None \
        else _host_tree(engine, "params")
    return sorted(_flat_with_names(params).keys())


# ------------------------------------------------------------------ getters
def _resident_master_or_params(engine):
    """Restore the fp32 source of truth only: master when the engine keeps
    one, else params (stage-0: params ARE the master).  Never restores an
    offloaded params tree alongside a live master — that would re-fill the
    HBM offload_states() freed for a tree the caller won't touch."""
    _resident(engine, "master")
    if engine.master is None:
        _resident(engine, "params")


def safe_get_full_fp32_param(engine, name):
    """Full fp32 master weight (reference tensor_fragment.py:187)."""
    _resident_master_or_params(engine)
    src = engine.master if engine.master is not None else engine.params
    leaf = _lookup(src, name)
    if leaf is None:
        return None
    return np.asarray(leaf, dtype=np.float32)


def _live_scale(engine):
    return (float(engine.scale_state.scale)
            if engine.scale_state is not None else 1.0)


def safe_get_full_grad(engine, name):
    """Full accumulated gradient, unscaled (reference :158)."""
    _resident(engine, "grad_acc")
    leaf = _lookup(engine.grad_acc, name)
    if leaf is None:
        return None
    g = np.asarray(leaf, dtype=np.float32)
    return g / _live_scale(engine)


def safe_get_full_optimizer_state(engine, name, state_key):
    """Full optimizer state tensor, e.g. ``exp_avg`` (reference :214)."""
    _resident(engine, "opt_state")
    from ..checkpoint.constants import UNIVERSAL_TO_STATE_FIELD
    field = UNIVERSAL_TO_STATE_FIELD.get(state_key, state_key)
    sub = getattr(engine.opt_state, field, None)
    if sub is None and isinstance(engine.opt_state, dict):
        sub = engine.opt_state.get(field)
    leaf = _lookup(sub, name)
    if leaf is None:
        return None
    return np.asarray(leaf, dtype=np.float32)


# ------------------------------------------------------------------ setters
def safe_set_full_fp32_param(engine, name, value):
    """Overwrite the fp32 master weight (and refresh the compute-dtype copy)
    preserving sharding (reference :241)."""
    _resident(engine, "master", "params")  # writes both copies
    plan = engine.plan
    if engine.master is not None:
        old = _lookup(engine.master, name)
        sh = _flat_with_names(plan.master_shardings(engine.master))[name]
        new = jax.device_put(jnp.asarray(value, dtype=old.dtype), sh)
        engine.master = _set_leaf(engine.master, name, new)
    # refresh compute copy
    oldp = _lookup(engine.params, name)
    shp = _flat_with_names(plan.param_shardings(engine.params))[name]
    newp = jax.device_put(jnp.asarray(value, dtype=oldp.dtype), shp)
    engine.params = _set_leaf(engine.params, name, newp)


def safe_set_full_optimizer_state(engine, name, state_key, value):
    """Overwrite one optimizer-state tensor (reference :262)."""
    _resident(engine, "opt_state")
    from ..checkpoint.constants import UNIVERSAL_TO_STATE_FIELD
    field = UNIVERSAL_TO_STATE_FIELD.get(state_key, state_key)
    sub = getattr(engine.opt_state, field, None)
    if sub is None:
        raise KeyError(state_key)
    old = _lookup(sub, name)
    if old is None:
        raise KeyError(name)
    new = jax.device_put(jnp.asarray(value, dtype=old.dtype), old.sharding)
    new_sub = _set_leaf(sub, name, new)
    engine.opt_state = engine.opt_state._replace(**{field: new_sub})


# ------------------------------------------------------- local (shard) view
def _shard_block_slices(leaf, shards):
    """(block_shape, [(shard, slice-within-block)]) for this host's shards'
    union bounding box — the ONE place get/set shard geometry lives."""
    nd = leaf.ndim
    starts = [min((s.index[d].start or 0) for s in shards)
              for d in range(nd)]
    stops = [max((s.index[d].stop if s.index[d].stop is not None
                  else leaf.shape[d]) for s in shards) for d in range(nd)]
    out_shape = [hi - lo for lo, hi in zip(starts, stops)]
    pairs = []
    for s in shards:
        sl = tuple(
            slice((ix.start or 0) - lo,
                  (ix.stop if ix.stop is not None else dim) - lo)
            for ix, lo, dim in zip(s.index, starts, leaf.shape))
        pairs.append((s, sl))
    return out_shape, pairs


def _local_block(leaf, dtype=np.float32):
    """Stitch this host's addressable shards into one array covering their
    union bounding box (a host driving several chips owns several shards)."""
    shards = list(leaf.addressable_shards)
    if not shards:
        return None
    if len(shards) == 1:
        return np.asarray(shards[0].data, dtype=dtype)
    # Dedup replicated shards (several local devices may hold the same slice).
    by_index = {}
    for s in shards:
        key = tuple((ix.start or 0, ix.stop if ix.stop is not None else dim)
                    for ix, dim in zip(s.index, leaf.shape))
        by_index.setdefault(key, s)
    shards = list(by_index.values())
    out_shape, pairs = _shard_block_slices(leaf, shards)
    out = np.zeros(out_shape, dtype=dtype)
    covered = 0
    for s, sl in pairs:
        out[sl] = np.asarray(s.data, dtype=dtype)
        covered += int(np.prod([x.stop - x.start for x in sl]))
    if covered != out.size:
        # Non-contiguous local shards (e.g. a 2D mesh ordering giving this
        # host slices 0 and 2 of 4): the bounding box would contain fabricated
        # zeros — refuse rather than return garbage.
        raise ValueError(
            "local shards do not tile a contiguous block "
            f"(covered {covered} of {out.size} elements); read the full "
            "tensor via safe_get_full_fp32_param instead")
    return out


def _set_local_block(leaf, value):
    """Inverse of :func:`_local_block`: scatter ``value`` (this host's
    contiguous block) back into the host's addressable shards, returning a
    new global array with every other host's data untouched."""
    value = np.asarray(value)
    shards = list(leaf.addressable_shards)
    if not shards:
        raise ValueError(
            "no addressable shards of this array on this host — local "
            "set/get only touch locally-owned data")
    _, pairs = _shard_block_slices(leaf, shards)
    arrays = []
    for s, sl in pairs:
        blk = np.ascontiguousarray(value[sl]).astype(leaf.dtype)
        if blk.shape != tuple(x.stop - x.start for x in sl):
            raise ValueError(
                f"local value shape {value.shape} does not cover this "
                f"host's shard block")
        arrays.append(jax.device_put(blk, s.device))
    return jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, arrays)


def safe_set_full_grad(engine, name, value):
    """Overwrite the full accumulated gradient (reference :171).  ``value``
    is UNSCALED; it is stored re-multiplied by the live loss scale so
    :func:`safe_get_full_grad` round-trips."""
    _resident(engine, "grad_acc")
    leaf = _lookup(engine.grad_acc, name)
    if leaf is None:
        raise KeyError(f"no accumulated grad for {name!r} (call backward "
                       "before setting grads)")
    new = jax.device_put(
        jnp.asarray(value, dtype=leaf.dtype) * _live_scale(engine),
        leaf.sharding)
    engine.grad_acc = _set_leaf(engine.grad_acc, name, new)


def safe_set_local_fp32_param(engine, name, value):
    """Overwrite THIS host's shard of the fp32 master (reference ZeRO-3
    local API :300).  The compute-dtype copy refreshes at the next
    boundary apply (master is the source of truth there); with no master
    (pure fp32 stage-0) the params leaf IS the master and is written
    directly.  NOTE the master and compute copies may be sharded
    differently, so only the master's local geometry is meaningful here —
    use :func:`safe_set_full_fp32_param` to update both views at once."""
    _resident_master_or_params(engine)
    if engine.master is not None:
        old = _lookup(engine.master, name)
        engine.master = _set_leaf(engine.master, name,
                                  _set_local_block(old, value))
    else:
        oldp = _lookup(engine.params, name)
        engine.params = _set_leaf(engine.params, name,
                                  _set_local_block(oldp, value))


def safe_set_local_grad(engine, name, value):
    """Overwrite this host's shard of the accumulated grad (unscaled in,
    scaled storage — reference :190)."""
    _resident(engine, "grad_acc")
    leaf = _lookup(engine.grad_acc, name)
    if leaf is None:
        raise KeyError(f"no accumulated grad for {name!r}")
    engine.grad_acc = _set_leaf(
        engine.grad_acc, name,
        _set_local_block(leaf, np.asarray(value) * _live_scale(engine)))


def safe_set_local_optimizer_state(engine, name, state_key, value):
    """Overwrite this host's shard of one optimizer-state tensor
    (reference :320)."""
    _resident(engine, "opt_state")
    from ..checkpoint.constants import UNIVERSAL_TO_STATE_FIELD
    field = UNIVERSAL_TO_STATE_FIELD.get(state_key, state_key)
    sub = getattr(engine.opt_state, field, None)
    if sub is None:
        raise KeyError(state_key)
    leaf = _lookup(sub, name)
    if leaf is None:
        raise KeyError(name)
    new_sub = _set_leaf(sub, name, _set_local_block(leaf, value))
    engine.opt_state = engine.opt_state._replace(**{field: new_sub})


def safe_get_local_fp32_param(engine, name):
    """This host's shard of the fp32 master (reference ZeRO-3 local API :280)."""
    _resident_master_or_params(engine)
    src = engine.master if engine.master is not None else engine.params
    leaf = _lookup(src, name)
    if leaf is None:
        return None
    return _local_block(leaf)


def safe_get_local_grad(engine, name):
    _resident(engine, "grad_acc")
    leaf = _lookup(engine.grad_acc, name)
    if leaf is None:
        return None
    blk = _local_block(leaf)
    if blk is None:
        return None
    return blk / _live_scale(engine)


def safe_get_local_optimizer_state(engine, name, state_key):
    _resident(engine, "opt_state")
    from ..checkpoint.constants import UNIVERSAL_TO_STATE_FIELD
    field = UNIVERSAL_TO_STATE_FIELD.get(state_key, state_key)
    sub = getattr(engine.opt_state, field, None)
    leaf = _lookup(sub, name)
    if leaf is None:
        return None
    return _local_block(leaf)
