"""jax version-compat shims.

This codebase targets the modern ``jax.shard_map`` API
(``shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...,
axis_names=...)``).  On the pinned 0.4.x jaxlib that entry point does not
exist — the same machinery lives at ``jax.experimental.shard_map.shard_map``
with the older kwarg spelling (``check_rep`` instead of ``check_vma``,
``auto`` = the *complement* of ``axis_names``).  Without a shim, every
eager collective in ``comm/backend.py`` (and the pipeline/sequence
shard_map programs) dies with ``AttributeError: module 'jax' has no
attribute 'shard_map'``.

:func:`install` bridges the gap by publishing an adapter at
``jax.shard_map`` when (and only when) the attribute is missing — on a
modern jax it is a no-op, so the shim ages out automatically.
"""

import jax

_installed = False


def is_legacy_shard_map():
    """True when the adapter (not a native ``jax.shard_map``) is serving.
    Legacy jaxes also ship an SPMD partitioner that CHECK-fails
    (``hlo_sharding_util.cc IsManualSubgroup``) on *partial*-manual programs
    with collectives inside — callers that would emit one must refuse
    cleanly instead of letting XLA abort the process."""
    return _installed


def inside_axis_context():
    """True when called under an active named-axis trace (inside a
    shard_map/pmap region).  Legacy jax has no ``get_abstract_mesh`` to
    resolve the context mesh, so nested-region callers use this to refuse
    cleanly instead of building a nested program the old partitioner
    aborts on."""
    try:
        from jax._src import core as _core
        return bool(_core.get_axis_env().axis_names())
    except Exception:
        return False


def _adapt_shard_map(experimental_shard_map):

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, axis_names=None, check_rep=None,
                  auto=None):
        if auto is None:
            if axis_names:
                # modern axis_names = the MANUAL axes; legacy auto = the rest
                auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            else:
                auto = frozenset()
        if check_rep is None:
            check_rep = bool(check_vma) if check_vma is not None else True
        return experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                      out_specs=out_specs,
                                      check_rep=check_rep, auto=auto)

    return shard_map


def install():
    """Publish ``jax.shard_map`` on jaxes that predate it.  Returns True
    when the adapter was installed, False when jax already has the API (or
    has neither spelling)."""
    try:
        from jax.experimental.pallas import tpu as _pltpu
        if not hasattr(_pltpu, "CompilerParams") and \
                hasattr(_pltpu, "TPUCompilerParams"):
            # the pinned jaxlib spells it TPUCompilerParams; the kernels use
            # the modern name
            _pltpu.CompilerParams = _pltpu.TPUCompilerParams
    except ImportError:
        pass
    try:
        getattr(jax, "shard_map")
        return False
    except AttributeError:
        pass
    try:
        from jax.experimental.shard_map import shard_map as _exp
    except ImportError:
        return False
    global _installed
    jax.shard_map = _adapt_shard_map(_exp)
    _installed = True
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        # legacy jax cannot introspect "am I inside a manual shard_map
        # region"; answer "no" (manual_axes=()) so callers fall back to the
        # concrete global mesh — correct for every non-nested use
        sentinel = type("_NoAbstractMesh", (), {"manual_axes": ()})()
        jax.sharding.get_abstract_mesh = lambda: sentinel
    if not hasattr(jax.lax, "axis_size"):
        # pre-axis_size idiom: psum of a concrete 1 folds to the axis size
        # at trace time
        jax.lax.axis_size = lambda name: jax.lax.psum(1, name)
    return True
