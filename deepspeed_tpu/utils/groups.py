"""Process-group topology (L3) — mesh-axis factorization.

TPU-native re-derivation of reference ``deepspeed/utils/groups.py:55-588`` +
``runtime/pipe/topology.py``: instead of materializing rank lists and creating
NCCL communicators per group, we build ONE global 5-axis
``jax.sharding.Mesh``

    (pp, dp, ep, sp, tp)   — pipeline / expert-data / expert / sequence /
                             tensor axes

where the FULL data-parallel degree is the product of ("dp", "ep") — see the
axis-name comment below.  ZeRO secondary-partition (hpZ) groups live on a
separate reshaped mesh.  Any communication "group" is then just a tuple of
axis names (see ``deepspeed_tpu.comm.backend.ProcessGroup``), and XLA lays the
collectives onto ICI along those axes.

Axis order: the *rightmost* mesh axes are most-minor (fastest-varying device
index) and therefore map to physically-closest chips; we order
(pp, dp, ep, sp, tp) so tensor-parallel collectives (latency-bound, per-layer)
ride the shortest ICI hops, matching how Megatron orders NCCL groups.
"""

import os
from dataclasses import dataclass, field

import numpy as np

import jax
from jax.sharding import Mesh

from .logging import logger

# Canonical axis names, most-major → most-minor.  The global mesh is ALWAYS
# 5-axis (pp, dp, ep, sp, tp): "dp" is the expert-data-parallel part and the
# full data-parallel degree is the product of ("dp", "ep") — when ep=1 they
# coincide.  Keeping expert parallelism as a first-class axis of the ONE
# global mesh (instead of the reference's separate expert process groups,
# utils/groups.py:117-310) lets a single jitted step shard experts over "ep"
# while ZeRO shards state over ("dp","ep").
PP_AXIS = "pp"
DP_AXIS = "dp"
SP_AXIS = "sp"
TP_AXIS = "tp"
EP_AXIS = "ep"
EDP_AXIS = DP_AXIS  # expert-data-parallel IS the dp axis
# hpZ (ZeRO++ secondary partition) axes: dp = zp_outer × zp
ZP_AXIS = "zp"
ZP_OUTER_AXIS = "zp_outer"

_mesh_state = None


@dataclass
class MeshState:
    mesh: Mesh
    pp: int
    dp: int  # TOTAL data-parallel degree (= mesh dp × ep)
    sp: int
    tp: int
    ep: int = 1
    # hpZ mesh reshapes dp → (zp_outer, zp); params secondarily replicated
    # within the (intra-host) zp axis
    hpz_mesh: Mesh = None
    zero_partition_size: int = None  # hpZ secondary partition (ranks per shard group)


def _check_sizes(total, pp, dp, sp, tp):
    if pp * dp * sp * tp != total:
        raise ValueError(
            f"pp({pp}) * dp({dp}) * sp({sp}) * tp({tp}) = {pp*dp*sp*tp} "
            f"!= device count {total}")


def _physical_device_grid(shape, devices, strict=False):
    """Physically-aware device layout (round-1 review item 6: plain reshape
    ignores ICI topology — hpZ's intra-host promise and multi-slice DCN both
    need real placement):

    * multi-slice pods: ``create_hybrid_device_mesh`` puts the slice (DCN)
      factor outermost on the dp axis, so ZeRO reduce-scatter segments ride
      ICI within a slice and only the final combine crosses DCN;
    * single slice: ``create_device_mesh`` orders devices so most-minor mesh
      axes (tp, sp) map to nearest ICI neighbors — and the hpZ ``zp`` inner
      factor of dp (derived by reshape of this grid) stays on adjacent
      chips.

    CPU/virtual platforms fall back to the plain reshape (topology-free).

    ``strict``: the caller explicitly configured a locality property (hpZ
    secondary partition, MiCS) — a silent fallback would hand back a run
    without the property the config promised, so construction failure
    raises instead of warning (round-2 review weak #9).
    """
    if jax.default_backend() != "tpu" or devices.size == 1:
        return devices.reshape(shape)
    from jax.experimental import mesh_utils
    try:
        slices = {getattr(d, "slice_index", 0) for d in devices.flat}
        n_slices = len(slices)
        if n_slices > 1 and shape[1] % n_slices == 0:
            per_slice = list(shape)
            per_slice[1] //= n_slices
            dcn = [1] * len(shape)
            dcn[1] = n_slices  # DCN axis folded into dp, slice-major
            return mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn, devices=list(devices.flat))
        return mesh_utils.create_device_mesh(
            shape, devices=list(devices.flat),
            allow_split_physical_axes=True)
    except Exception as e:
        if strict:
            raise RuntimeError(
                "physical device-mesh construction failed but the config "
                "explicitly requests a locality property (hpZ "
                "zero_partition_size / MiCS shard groups) that depends on "
                "it; refusing to fall back to linear device order. "
                f"Underlying error: {type(e).__name__}: {e}") from e
        logger.warning(
            f"physical mesh construction failed ({type(e).__name__}: {e}) — "
            "falling back to linear device order; hpZ/DCN locality NOT "
            "guaranteed")
        return devices.reshape(shape)


def initialize_mesh(dp=None, pp=1, sp=1, tp=1, ep=1, devices=None,
                    zero_partition_size=None):
    """Build the global mesh. ``dp=None`` → use all remaining devices.

    Analog of reference ``deepspeed.initialize``'s mesh_device creation
    (``deepspeed/__init__.py:153-162``) plus ``PipelineParallelGrid``
    (``runtime/pipe/topology.py:251``) in one step.
    """
    global _mesh_state
    explicit_devices = devices is not None
    if devices is None:
        devices = np.array(jax.devices())
    else:
        devices = np.asarray(devices)
    total = devices.size
    if dp is None:
        rem = pp * sp * tp
        if total % rem != 0:
            raise ValueError(f"device count {total} not divisible by pp*sp*tp={rem}")
        dp = total // rem
    _check_sizes(total, pp, dp, sp, tp)
    if ep < 1:
        raise ValueError(f"expert parallel size ep={ep} must be >= 1")
    if dp % ep != 0:
        # loud, BEFORE the grid reshape: a bad factorization used to be
        # reachable as a cryptic numpy "cannot reshape array" error from
        # mesh construction paths that skipped this function
        raise ValueError(
            f"expert parallel size (ep_size) ep={ep} must divide the "
            f"data-parallel world size dp={dp} — the mesh factors dp into "
            f"(dp/ep, ep) = ({dp}/{ep}, {ep}) (reference moe/layer.py:89 "
            "semantics); pick ep from the divisors of dp")

    shape = (pp, dp // ep, ep, sp, tp)
    if explicit_devices:
        grid = devices.reshape(shape)
    else:
        grid = _physical_device_grid(
            shape, devices,
            strict=bool(zero_partition_size and zero_partition_size > 1))
        devices = grid  # hpZ factoring below reuses the optimized order
    mesh = Mesh(grid, axis_names=(PP_AXIS, DP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS))

    # hpZ secondary-partition mesh: dp factored into (outer, inner) where the
    # inner axis groups physically-adjacent chips (intra-host) — reference
    # groups.py:531 _create_zero_param_parallel_group.
    hpz_mesh = None
    if zero_partition_size and zero_partition_size > 1:
        if dp % zero_partition_size != 0:
            raise ValueError(
                f"zero_partition_size={zero_partition_size} must divide dp={dp}")
        zgrid = devices.reshape(pp, dp // zero_partition_size,
                                zero_partition_size, sp, tp)
        hpz_mesh = Mesh(zgrid, axis_names=(PP_AXIS, ZP_OUTER_AXIS, ZP_AXIS,
                                           SP_AXIS, TP_AXIS))

    _mesh_state = MeshState(mesh=mesh, pp=pp, dp=dp, sp=sp, tp=tp, ep=ep,
                            hpz_mesh=hpz_mesh,
                            zero_partition_size=zero_partition_size)
    logger.debug(f"initialized mesh pp={pp} dp={dp} sp={sp} tp={tp} ep={ep}")
    # Keep an already-created comm backend in sync so facade collectives and
    # groups-module accessors always agree on the topology.
    from ..comm import comm as _comm
    if _comm.cdb is not None:
        from ..comm.backend import ProcessGroup
        _comm.cdb.mesh = mesh
        _comm.cdb.world_group = ProcessGroup(mesh, mesh.axis_names)
    return _mesh_state


def mesh_is_initialized():
    return _mesh_state is not None


def get_mesh_state() -> MeshState:
    if _mesh_state is None:
        initialize_mesh()
    return _mesh_state


def reset_mesh():
    global _mesh_state
    _mesh_state = None


def get_global_mesh() -> Mesh:
    return get_mesh_state().mesh


def dp_axes():
    """Mesh axes whose product is the full data-parallel degree."""
    return (DP_AXIS, EP_AXIS)


# ----------------------------------------------------------------- group API
# Accessor names mirror reference utils/groups.py so engine code reads the same.

def _pg(axes, mesh=None):
    from ..comm.backend import ProcessGroup
    return ProcessGroup(mesh or get_global_mesh(), axes)


def _get_data_parallel_group():
    return _pg(dp_axes())


def _get_sequence_parallel_group():
    return _pg((SP_AXIS, ))


def _get_sequence_data_parallel_group():
    """ZeRO shards over the combined seq×dp group when SP is on (reference
    ``engine.py:1580,1651`` seq_data_parallel_group)."""
    return _pg(dp_axes() + (SP_AXIS, ))


def _get_model_parallel_group():
    return _pg((TP_AXIS, ))


def _get_pipe_parallel_group():
    return _pg((PP_AXIS, ))


def _get_expert_parallel_group():
    return _pg((EP_AXIS, ))


def _get_expert_data_parallel_group():
    """Grads of expert params reduce over this group only (reference
    engine.py:2510 _reduce_expert_gradients)."""
    return _pg((DP_AXIS, ))


def _get_zero_param_partition_group():
    """hpZ secondary partition group (reference ``groups.py:531``): params are
    secondarily replicated within this group so allgather rides intra-host ICI."""
    st = get_mesh_state()
    if st.hpz_mesh is None:
        return None
    return _pg((ZP_AXIS, ), mesh=st.hpz_mesh)


def _get_data_parallel_world_size():
    return get_mesh_state().dp


def _get_sequence_parallel_world_size():
    return get_mesh_state().sp


def _get_model_parallel_world_size():
    return get_mesh_state().tp


def _get_pipe_parallel_world_size():
    return get_mesh_state().pp


def _get_expert_parallel_world_size():
    return get_mesh_state().ep


def _get_data_parallel_rank():
    """Host-level dp rank for per-process data loading (reference
    ``groups.py`` dp rank feeding ``DistributedSampler``): the dp-axis
    coordinate block of this process's addressable devices.  Per-device ranks
    only exist inside shard_map; this is the IO-level notion — processes with
    the same value must feed identical data, processes with different values
    feed different dp shards (see ``engine.shard_batch``)."""
    if jax.process_count() == 1:
        return 0
    st = get_mesh_state()
    devs = st.mesh.devices
    names = st.mesh.axis_names
    pi = jax.process_index()
    dp_i = names.index(DP_AXIS)
    ep_i = names.index(EP_AXIS)
    ep = devs.shape[ep_i]
    for coords in np.ndindex(devs.shape):
        if devs[coords].process_index == pi:
            # full-dp coordinate = dp coord × ep + ep coord (dp_axes order)
            return int(coords[dp_i]) * ep + int(coords[ep_i])
    raise RuntimeError(
        f"process {pi} owns no device in the mesh — mesh built from a "
        "device subset?")




def zero_sharding_axes(sequence_parallel=False):
    """Mesh axes over which ZeRO partitions optimizer/grad/param state."""
    return dp_axes() + ((SP_AXIS, ) if sequence_parallel else ())
