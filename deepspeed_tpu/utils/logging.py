"""Rank-aware logging.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (rank-aware
``log_dist`` / ``logger``).  Process identity comes from JAX's distributed runtime
rather than torch.distributed.
"""

import logging
import os
import sys
from functools import lru_cache

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@lru_cache(None)
def _create_logger(name="DeepSpeedTPU", level=logging.INFO):
    logger_ = logging.getLogger(name)
    logger_.setLevel(level)
    logger_.propagate = False
    if not logger_.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
        logger_.addHandler(handler)
    return logger_


logger = _create_logger(
    level=LOG_LEVELS.get(os.environ.get("DS_TPU_LOG_LEVEL", "info").lower(), logging.INFO))


def _get_rank():
    # Avoid importing jax at module import time; the launcher sets RANK before
    # child processes import this package (launcher/launch.py analog).
    rank = os.environ.get("RANK")
    if rank is not None:
        return int(rank)
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the given ranks (None or [-1] = all ranks).

    Mirrors the behavior of the reference's ``log_dist``
    (``deepspeed/utils/logging.py``).
    """
    my_rank = _get_rank()
    if ranks is None or len(ranks) == 0 or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message):
    _warn_once_cache = getattr(warning_once, "_cache", None)
    if _warn_once_cache is None:
        _warn_once_cache = set()
        warning_once._cache = _warn_once_cache
    if message not in _warn_once_cache:
        _warn_once_cache.add(message)
        logger.warning(message)


def print_json_dist(message, ranks=None, path=None):
    """Print/append a json message on selected ranks (autotuning metric dump)."""
    import json
    my_rank = _get_rank()
    if ranks is None or len(ranks) == 0 or -1 in ranks or my_rank in ranks:
        message["rank"] = my_rank
        if path is None:
            print(json.dumps(message))
        else:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            with open(path, "a") as f:
                f.write(json.dumps(message) + "\n")
