"""Back-compat import path (reference ships the recovery script as
``deepspeed/utils/zero_to_fp32.py``) — implementation lives in
``deepspeed_tpu/checkpoint/zero_to_fp32.py`` (it is also copied into every
checkpoint dir by the save path, reference engine.py:3540)."""

from ..checkpoint.zero_to_fp32 import (  # noqa: F401
    convert_zero_checkpoint_to_fp32_state_dict,
    get_fp32_state_dict_from_zero_checkpoint, main)
