"""Timers — analog of reference ``deepspeed/utils/timer.py``.

``SynchronizedWallClockTimer`` (reference ``timer.py:44``) with the
accelerator abstraction's ``synchronize()`` in place of CUDA events;
``ThroughputTimer`` (reference ``timer.py:199``) reports samples/sec with
an optional smoothing window.
"""

import time
from collections import deque

from .logging import log_dist


def _device_synchronize():
    """Device sync via the accelerator abstraction — the ONE place timers
    touch the device, so non-jax accelerators (or tests stubbing the
    accelerator) get correct synchronized timing for free."""
    from ..accelerator import get_accelerator
    get_accelerator().synchronize()

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"

TRAIN_BATCH_TIMER = "train_batch"


class SynchronizedWallClockTimer:

    class Timer:

        def __init__(self, name):
            self.name_ = name
            self.started_ = False
            self.start_time = 0.0
            self.elapsed_ = 0.0
            self.records = []

        def start(self, sync=False):
            assert not self.started_, f"{self.name_} timer already started"
            if sync:
                self._sync()
            self.start_time = time.perf_counter()
            self.started_ = True

        def stop(self, reset=False, record=True, sync=False):
            assert self.started_, f"{self.name_} timer not started"
            if sync:
                self._sync()
            elapsed = time.perf_counter() - self.start_time
            if reset:
                self.elapsed_ = elapsed
            else:
                self.elapsed_ += elapsed
            if record:
                self.records.append(elapsed * 1000.0)
            self.started_ = False

        _sync = staticmethod(_device_synchronize)

        def elapsed(self, reset=True):
            """Accumulated seconds.  ``reset=False`` is a pure READ: a
            running timer keeps running and nothing is folded or restarted
            (previously the running segment was stopped into ``elapsed_``
            and the timer restarted, so back-to-back reads mutated state
            and dropped the sync/record options of the original start).
            ``reset=True`` zeroes the accumulation; a running timer restarts
            its segment at now."""
            elapsed = self.elapsed_
            if self.started_:
                elapsed += time.perf_counter() - self.start_time
            if reset:
                self.elapsed_ = 0.0
                if self.started_:
                    self.start_time = time.perf_counter()
            return elapsed

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def mean(self):
            return (sum(self.records) / len(self.records)) if self.records else 0.0


    def __init__(self):
        self.timers = {}

    #: reference ``SynchronizedWallClockTimer.synchronize`` — device sync
    #: through the accelerator abstraction
    synchronize = staticmethod(_device_synchronize)

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def get_timers(self):
        return self.timers

    @staticmethod
    def memory_usage():
        from ..accelerator import get_accelerator
        acc = get_accelerator()
        alloc = acc.memory_allocated() / (1024**3)
        peak = acc.max_memory_allocated() / (1024**3)
        return f"mem_alloc={alloc:.2f}GB peak={peak:.2f}GB"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        log_dist(string, ranks=ranks or [0])


class NoopTimer:
    """Reference ``timer.py:164`` — disabled-timer stand-in."""

    class Timer:

        def start(self, **kwargs):
            ...

        def stop(self, **kwargs):
            ...

        def reset(self):
            ...

        def elapsed(self, **kwargs):
            return 0.0

        def mean(self):
            return 0.0

    def __init__(self):
        self.timer = self.Timer()

    def __call__(self, name):
        return self.timer

    def get_timers(self):
        return {}

    def log(self, names, **kwargs):
        ...


class ThroughputTimer:
    """Samples/sec + TFLOPS reporting (reference ``timer.py:199``).

    ``smoothing_window``: with N > 0, :meth:`avg_samples_per_sec` averages
    over the last N steps instead of the whole run — the number a live
    dashboard wants (a data-loader hiccup 10k steps ago should not haunt
    the reported throughput forever)."""

    def __init__(self, config, batch_size, start_step=2, steps_per_output=None,
                 monitor_memory=False, logging_fn=None, smoothing_window=None):
        self.config = config
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda m: log_dist(m, ranks=[0]))
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.started = False
        self.start_time = 0.0
        self.smoothing_window = smoothing_window
        self._recent = (deque(maxlen=int(smoothing_window))
                        if smoothing_window and smoothing_window > 0
                        else None)

    @property
    def enabled(self):
        return getattr(self.config, "enabled", True)

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self):
        if not self.enabled:
            return
        self.start_time = time.perf_counter()
        self.started = True

    def stop(self, global_step=False, report_speed=True):
        if not self.enabled or not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        duration = time.perf_counter() - self.start_time
        if global_step:
            self.global_step_count += 1
            if self.global_step_count >= self.start_step:
                self.total_elapsed_time += duration
                self.step_elapsed_time += duration
                if self._recent is not None:
                    self._recent.append(duration)
                if report_speed and self.steps_per_output and \
                        self.global_step_count % self.steps_per_output == 0:
                    self.logging(
                        f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                        f"global_step={self.global_step_count}, RunningAvgSamplesPerSec="
                        f"{self.avg_samples_per_sec():.2f}, CurrSamplesPerSec="
                        f"{self.batch_size / self.step_elapsed_time:.2f}")
                # Reset every global step so CurrSamplesPerSec reflects the
                # latest step only (reference timer.py behavior).
                self.step_elapsed_time = 0.0
            else:
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self):
        if self._recent:
            return self.batch_size * len(self._recent) / sum(self._recent)
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step + 1)
            return samples / self.total_elapsed_time
        return 0.0
