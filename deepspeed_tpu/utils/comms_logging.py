"""Comms logger — analog of reference ``deepspeed/utils/comms_logging.py:67``.

Tracks per-op counts/sizes/latencies and computes algorithmic/bus bandwidth
(``get_bw`` logic mirrors the reference's msg-size → busbw factors).

Bandwidth accounting is **wire-truthful**: when the collectives engine
(``comm/collectives/``) runs a quantized or hierarchical variant, the op
records the bytes that actually crossed the bottleneck (inter-node) link —
quantized payload + per-group scales — not the logical fp tensor size, and
the variant name is carried into the ``log_summary()`` rows as
``op[variant]``.  Flat ops report wire == message size, as before.
"""

import math

from .logging import log_dist, logger


def get_msg_size_from_args(x):
    import numpy as np
    try:
        return int(np.prod(x.shape)) * x.dtype.itemsize
    except Exception:
        return 0


def calc_bw_log(comm_op, size, duration, n):
    """Return (algbw, busbw) in Gbps for ``size`` transported bytes.
    Factors follow nccl-tests conventions, as the reference does
    (``comms_logging.py`` ``get_bw``); a variant suffix (``all_reduce[hier]``)
    keys off the base op name."""
    if duration <= 0:
        return 0.0, 0.0
    comm_op = comm_op.split("[", 1)[0]
    tput = size / duration  # bytes/sec
    if comm_op in ("all_to_all", "all_to_all_single"):
        busbw = tput * ((n - 1) / n)
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor"):
        busbw = tput * ((n - 1) / n)
    elif comm_op in ("all_reduce", "inference_all_reduce"):
        busbw = tput * (2 * (n - 1) / n)
    else:  # send/recv/broadcast/reduce/barrier
        busbw = tput
    # bytes/sec → Gbits/sec
    return tput * 8 / 1e9, busbw * 8 / 1e9


class CommsLogger:

    def __init__(self, enabled=False, verbose=False, prof_all=True, debug=False,
                 prof_ops=None, sync_timing=False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        self.prof_ops = prof_ops or []
        # round-1 review: forcing block_until_ready on every logged
        # collective serializes the async pipeline; sync timing is opt-in
        self.sync_timing = sync_timing
        self.comms_dict = {}

    def configure(self, comms_config):
        self.enabled = comms_config.comms_logger_enabled
        if self.enabled:
            self.verbose = comms_config.comms_logger.verbose
            self.prof_all = comms_config.comms_logger.prof_all
            self.debug = comms_config.comms_logger.debug
            self.prof_ops = comms_config.comms_logger.prof_ops
            self.sync_timing = getattr(comms_config.comms_logger,
                                       "sync_timing", False)

    def start_profiling_op(self, op_name_list):
        self.prof_ops = list(set(self.prof_ops) | set(op_name_list))

    def stop_profiling_op(self, op_name_list):
        self.prof_ops = [op for op in self.prof_ops if op not in op_name_list]

    def start_profiling_comms(self):
        self.prof_all = True

    def stop_profiling_comms(self):
        self.prof_all = False

    def append(self, raw_name, record_name, latency, msg_size, world_size,
               wire_size=None, variant=None):
        """Record one collective.  ``msg_size`` is the logical tensor bytes;
        ``wire_size`` the transported bytes (defaults to msg_size for flat
        ops) — bandwidth is computed from the wire, because that is what the
        links carried.  Entry slot 4 holds the TOTAL transported bytes for
        the row (it used to be overwritten with the latest call's wire —
        which double-counted quantized bytes into flat totals when an op
        fell back from a quantized variant to flat mid-run and a stale wire
        was re-attributed; totals now sum each call exactly once)."""
        wire = wire_size if wire_size is not None else msg_size
        name = f"{record_name}[{variant}]" if variant else record_name
        raw = f"{raw_name}[{variant}]" if variant else raw_name
        algbw, busbw = calc_bw_log(raw, wire, latency, world_size)
        if name in self.comms_dict:
            if msg_size in self.comms_dict[name]:
                entry = self.comms_dict[name][msg_size]
                entry[0] += 1
                entry[1].append(latency)
                entry[2].append(algbw)
                entry[3].append(busbw)
                entry[4] += wire
            else:
                self.comms_dict[name][msg_size] = [1, [latency], [algbw],
                                                   [busbw], wire]
        else:
            self.comms_dict[name] = {msg_size: [1, [latency], [algbw],
                                                [busbw], wire]}
        if self.verbose:
            log_dist(
                f"rank=? | comm op: {name} | time(ms): {latency*1000:.2f} | "
                f"msg size: {msg_size} | wire size: {wire} | "
                f"algbw(Gbps): {algbw:.2f} | busbw(Gbps): {busbw:.2f}",
                ranks=[0])

    def log_all(self, print_log=True, show_straggler=False):
        from ..utils.logging import logger
        lines = [f"{'Comm. Op (variant)':<28}{'Message Size':<16}"
                 f"{'Wire Size':<14}{'Count':<8}"
                 f"{'Total Latency(ms)':<20}{'Avg Latency(ms)':<18}"
                 f"{'tput_avg (Gbps)':<18}{'busbw_avg (Gbps)':<18}"]
        for record_name, sizes in sorted(self.comms_dict.items()):
            lines.append(record_name)
            for msg_size, (count, latencies, algbws, busbws,
                           wire_total) in sorted(sizes.items()):
                total = sum(latencies) * 1000
                avg = total / count
                avg_alg = sum(algbws) / len(algbws)
                avg_bus = sum(busbws) / len(busbws)
                wire = wire_total // count  # per-call transported bytes
                lines.append(f"{'':<28}{msg_size:<16}{wire:<14}{count:<8}"
                             f"{total:<20.2f}{avg:<18.2f}{avg_alg:<18.2f}"
                             f"{avg_bus:<18.2f}")
        out = "\n".join(lines)
        if print_log:
            logger.info(out)
        return self.comms_dict

    def get_summary_dict(self):
        """Machine-readable counterpart of :meth:`log_all` — what the
        telemetry tooling (``tools/trace_report.py``) and the future comm
        autotuner ingest instead of scraping the printed table.

        Returns::

            {"ops": {"all_reduce[q_int8]": {"base_op", "variant",
                 "count", "total_latency_ms", "avg_latency_ms",
                 "total_msg_bytes", "total_wire_bytes",
                 "algbw_gbps_avg", "busbw_gbps_avg",
                 "msg_sizes": {bytes: {...per-size row...}}}, ...},
             "totals": {"all_reduce": {"count", "total_latency_ms",
                 "total_wire_bytes", "variants": [...]}, ...}}

        ``totals`` aggregates across variants by base op, each recorded
        call counted exactly once — an op that fell back from a quantized
        variant to flat mid-run contributes each call to exactly one
        variant row and once to its base-op total (no double-counting)."""
        ops = {}
        totals = {}
        for name, sizes in sorted(self.comms_dict.items()):
            if "[" in name and name.endswith("]"):
                base, variant = name[:-1].split("[", 1)
            else:
                base, variant = name, None
            op = {"base_op": base, "variant": variant, "count": 0,
                  "total_latency_ms": 0.0, "total_msg_bytes": 0,
                  "total_wire_bytes": 0, "algbw_gbps_avg": 0.0,
                  "busbw_gbps_avg": 0.0, "msg_sizes": {}}
            alg_all, bus_all = [], []
            for msg_size, (count, latencies, algbws, busbws,
                           wire_total) in sorted(sizes.items()):
                total_ms = sum(latencies) * 1000
                op["msg_sizes"][int(msg_size)] = {
                    "count": count,
                    "total_latency_ms": total_ms,
                    "avg_latency_ms": total_ms / count,
                    "wire_bytes_per_call": wire_total // count,
                    "algbw_gbps_avg": sum(algbws) / len(algbws),
                    "busbw_gbps_avg": sum(busbws) / len(busbws),
                }
                op["count"] += count
                op["total_latency_ms"] += total_ms
                op["total_msg_bytes"] += int(msg_size) * count
                op["total_wire_bytes"] += int(wire_total)
                alg_all += algbws
                bus_all += busbws
            if alg_all:
                op["algbw_gbps_avg"] = sum(alg_all) / len(alg_all)
                op["busbw_gbps_avg"] = sum(bus_all) / len(bus_all)
            ops[name] = op
            t = totals.setdefault(base, {"count": 0, "total_latency_ms": 0.0,
                                         "total_msg_bytes": 0,
                                         "total_wire_bytes": 0,
                                         "variants": []})
            t["count"] += op["count"]
            t["total_latency_ms"] += op["total_latency_ms"]
            t["total_msg_bytes"] += op["total_msg_bytes"]
            t["total_wire_bytes"] += op["total_wire_bytes"]
            t["variants"].append(variant or "flat")
        return {"ops": ops, "totals": totals}
