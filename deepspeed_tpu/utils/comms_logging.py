"""Comms logger — analog of reference ``deepspeed/utils/comms_logging.py:67``.

Tracks per-op counts/sizes/latencies and computes algorithmic/bus bandwidth
(``get_bw`` logic mirrors the reference's msg-size → busbw factors).
"""

import math

from .logging import log_dist, logger


def get_msg_size_from_args(x):
    import numpy as np
    try:
        return int(np.prod(x.shape)) * x.dtype.itemsize
    except Exception:
        return 0


def calc_bw_log(comm_op, size, duration, n):
    """Return (algbw, busbw) in Gbps. Factors follow nccl-tests conventions,
    as the reference does (``comms_logging.py`` ``get_bw``)."""
    if duration <= 0:
        return 0.0, 0.0
    tput = size / duration  # bytes/sec
    if comm_op in ("all_to_all", "all_to_all_single"):
        busbw = tput * ((n - 1) / n)
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor"):
        busbw = tput * ((n - 1) / n)
    elif comm_op in ("all_reduce", "inference_all_reduce"):
        busbw = tput * (2 * (n - 1) / n)
    else:  # send/recv/broadcast/reduce/barrier
        busbw = tput
    # bytes/sec → Gbits/sec
    return tput * 8 / 1e9, busbw * 8 / 1e9


class CommsLogger:

    def __init__(self, enabled=False, verbose=False, prof_all=True, debug=False,
                 prof_ops=None, sync_timing=False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        self.prof_ops = prof_ops or []
        # round-1 review: forcing block_until_ready on every logged
        # collective serializes the async pipeline; sync timing is opt-in
        self.sync_timing = sync_timing
        self.comms_dict = {}

    def configure(self, comms_config):
        self.enabled = comms_config.comms_logger_enabled
        if self.enabled:
            self.verbose = comms_config.comms_logger.verbose
            self.prof_all = comms_config.comms_logger.prof_all
            self.debug = comms_config.comms_logger.debug
            self.prof_ops = comms_config.comms_logger.prof_ops
            self.sync_timing = getattr(comms_config.comms_logger,
                                       "sync_timing", False)

    def start_profiling_op(self, op_name_list):
        self.prof_ops = list(set(self.prof_ops) | set(op_name_list))

    def stop_profiling_op(self, op_name_list):
        self.prof_ops = [op for op in self.prof_ops if op not in op_name_list]

    def start_profiling_comms(self):
        self.prof_all = True

    def stop_profiling_comms(self):
        self.prof_all = False

    def append(self, raw_name, record_name, latency, msg_size, world_size):
        algbw, busbw = calc_bw_log(raw_name, msg_size, latency, world_size)
        if record_name in self.comms_dict:
            if msg_size in self.comms_dict[record_name]:
                entry = self.comms_dict[record_name][msg_size]
                entry[0] += 1
                entry[1].append(latency)
                entry[2].append(algbw)
                entry[3].append(busbw)
            else:
                self.comms_dict[record_name][msg_size] = [1, [latency], [algbw], [busbw]]
        else:
            self.comms_dict[record_name] = {msg_size: [1, [latency], [algbw], [busbw]]}
        if self.verbose:
            log_dist(
                f"rank=? | comm op: {record_name} | time(ms): {latency*1000:.2f} | "
                f"msg size: {msg_size} | algbw(Gbps): {algbw:.2f} | busbw(Gbps): {busbw:.2f}",
                ranks=[0])

    def log_all(self, print_log=True, show_straggler=False):
        from ..utils.logging import logger
        lines = [f"{'Comm. Op':<20}{'Message Size':<20}{'Count':<10}"
                 f"{'Total Latency(ms)':<20}{'Avg Latency(ms)':<20}"
                 f"{'tput_avg (Gbps)':<20}{'busbw_avg (Gbps)':<20}"]
        for record_name, sizes in sorted(self.comms_dict.items()):
            lines.append(record_name)
            for msg_size, (count, latencies, algbws, busbws) in sorted(sizes.items()):
                total = sum(latencies) * 1000
                avg = total / count
                avg_alg = sum(algbws) / len(algbws)
                avg_bus = sum(busbws) / len(busbws)
                lines.append(f"{'':<20}{msg_size:<20}{count:<10}{total:<20.2f}"
                             f"{avg:<20.2f}{avg_alg:<20.2f}{avg_bus:<20.2f}")
        out = "\n".join(lines)
        if print_log:
            logger.info(out)
        return self.comms_dict
