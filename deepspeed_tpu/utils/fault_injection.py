"""Deterministic fault injection for resilience testing.

Production code is instrumented with named *fault points* — cheap no-ops
unless a fault is armed — and tests (or the ``tools/fault_smoke.py`` script,
via env var) arm handlers that kill a save mid-write, truncate a checkpoint
file, poison a loss, fail an FS write transiently, or stall a heartbeat.
This is how every recovery path in the resilience subsystem is proven
end-to-end instead of hoped-for.

Instrumented sites (grep for ``fault_point(`` to audit):

====================  =====================================================
site                  fires
====================  =====================================================
``ckpt.save_tree``    before each orbax tree write (inside the retry loop —
                      a handler that raises tests retry-with-backoff)
``ckpt.mid_write``    after each tree of a tag is written, before the next
                      (kill here → partial tag, no manifest, stale latest)
``ckpt.committed``    after manifest + ``latest`` are durable (truncate here
                      → post-commit corruption the manifest check must catch)
``engine.poison``     per micro-step in ``forward`` — a truthy return
                      poisons that step's loss and gradients with NaN
``heartbeat.beat``    before a heartbeat write — a truthy return suppresses
                      it (simulates a hung worker for the watchdog)
====================  =====================================================

Programmatic use (in-process tests)::

    from deepspeed_tpu.utils import fault_injection as fi
    fi.inject("engine.poison", lambda ctx: ctx["step"] == 3)
    ...
    fi.clear()

Cross-process use (subprocess workers, the smoke script) via
``DS_TPU_FAULT_INJECT`` — ``;``-separated fault specs, each
``name:key=val,key=val``::

    DS_TPU_FAULT_INJECT="kill_save_mid_write:after=1"
    DS_TPU_FAULT_INJECT="fail_save:times=2;poison_loss:step=3"
    DS_TPU_FAULT_INJECT="truncate_ckpt:file=engine_state.json"
    DS_TPU_FAULT_INJECT="stall_heartbeat:after=2"

``kill_save_mid_write`` calls ``os._exit(17)`` — an un-catchable death that
leaves whatever bytes happen to be on disk, exactly like a preempted host.
"""

import os
import threading

from .logging import logger

#: exit code used by ``kill_save_mid_write`` so harnesses can tell an
#: injected death from an organic crash
KILLED_EXIT_CODE = 17


class FaultError(OSError):
    """Raised by injected transient failures (``fail_save``)."""


class FaultInjector:
    """Registry of site → handlers.  ``fire`` is the hot path: one dict
    lookup when nothing is armed."""

    def __init__(self):
        self._handlers = {}
        self._counts = {}
        self._lock = threading.Lock()
        self._env_spec_loaded = None

    # ------------------------------------------------------------- arming
    def inject(self, site, handler):
        """Arm ``handler(ctx: dict) -> result`` at ``site``.  A handler may
        raise, kill the process, mutate files named in ``ctx``, or return a
        value the instrumented site acts on (see module docstring)."""
        self._handlers.setdefault(site, []).append(handler)
        return handler

    def clear(self):
        """Disarm everything and reset per-site fire counters."""
        self._handlers.clear()
        self._counts.clear()
        self._env_spec_loaded = None

    def count(self, site):
        """How many times ``site`` fired since the last ``clear``."""
        return self._counts.get(site, 0)

    # ------------------------------------------------------------- firing
    def fire(self, site, **ctx):
        """Called from instrumented production code.  Returns the first
        non-None handler result (or None when nothing is armed)."""
        self._maybe_load_env()
        handlers = self._handlers.get(site)
        if not handlers:
            return None
        with self._lock:
            n = self._counts[site] = self._counts.get(site, 0) + 1
        ctx["call"] = n
        out = None
        for h in list(handlers):
            r = h(ctx)
            if out is None and r is not None:
                out = r
        return out

    # ------------------------------------------------------- env-var specs
    def _maybe_load_env(self):
        spec = os.environ.get("DS_TPU_FAULT_INJECT", "")
        if spec == self._env_spec_loaded:
            return
        # spec changed (or first fire): rebuild env-armed handlers; keep
        # programmatic ones (env handlers are tagged)
        for site, hs in list(self._handlers.items()):
            self._handlers[site] = [h for h in hs
                                    if not getattr(h, "_from_env", False)]
        self._env_spec_loaded = spec
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            name, _, argstr = part.partition(":")
            args = {}
            for kv in filter(None, (a.strip() for a in argstr.split(","))):
                k, _, v = kv.partition("=")
                args[k] = v
            try:
                self._install_env_fault(name.strip(), args)
            except KeyError:
                raise ValueError(
                    f"unknown fault {name!r} in DS_TPU_FAULT_INJECT "
                    f"(have: kill_save_mid_write, fail_save, truncate_ckpt, "
                    f"poison_loss, stall_heartbeat)") from None

    def _install_env_fault(self, name, args):
        def env(site, handler):
            handler._from_env = True
            self._handlers.setdefault(site, []).append(handler)

        if name == "kill_save_mid_write":
            after = int(args.get("after", 1))
            tag = args.get("tag")   # None = any tag

            def kill(ctx):
                if tag is not None and str(ctx.get("tag")) != tag:
                    return
                if ctx["call"] >= after:
                    logger.error(
                        "fault injection: dying mid checkpoint write "
                        "(tag=%s sub=%s)", ctx.get("tag"), ctx.get("sub"))
                    os._exit(KILLED_EXIT_CODE)
            env("ckpt.mid_write", kill)
        elif name == "fail_save":
            times = int(args.get("times", 1))

            def fail(ctx):
                if ctx["call"] <= times:
                    raise FaultError(
                        f"injected transient save failure "
                        f"{ctx['call']}/{times}")
            env("ckpt.save_tree", fail)
        elif name == "truncate_ckpt":
            fname = args.get("file", "engine_state.json")

            def truncate(ctx):
                truncate_file_in_tag(ctx["root"], fname)
            env("ckpt.committed", truncate)
        elif name == "poison_loss":
            step = int(args.get("step", 0))
            env("engine.poison", lambda ctx: ctx["step"] == step)
        elif name == "stall_heartbeat":
            after = int(args.get("after", 0))
            env("heartbeat.beat", lambda ctx: ctx["step"] >= after)
        else:
            raise KeyError(name)


def truncate_file_in_tag(root, name):
    """Chop the named checkpoint file (path relative to the tag root, or a
    bare filename searched for recursively) to half its size — the
    post-commit corruption shape (preempted flush, bit rot) manifest
    verification exists to catch."""
    path = os.path.join(root, name)
    if not os.path.exists(path):
        for dirpath, _, files in os.walk(root):
            if name in files:
                path = os.path.join(dirpath, name)
                break
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    logger.error("fault injection: truncated %s (%d → %d bytes)",
                 path, size, size // 2)
    return path


#: process-global injector — production fault points and tests share it
_INJECTOR = FaultInjector()


def fault_point(site, **ctx):
    """The production-side hook.  No-op (one dict lookup + env check) unless
    a fault is armed at ``site``."""
    return _INJECTOR.fire(site, **ctx)


def inject(site, handler):
    return _INJECTOR.inject(site, handler)


def clear():
    _INJECTOR.clear()


def fire_count(site):
    return _INJECTOR.count(site)
