"""NUMA/core binding for host-side workers — analog of reference
``deepspeed/utils/numa.py`` (``get_numactl_cmd``).

On a TPU host the heavy host-side consumers are the C++ optimizer sweep
(OpenMP) and the aio engines; binding each launched process to its own core
slice (and, when the slice sits inside one NUMA node, membinding there)
keeps the host optimizer's memory traffic NUMA-local.  Used by
``launcher/launch.py`` when ``--bind_cores_to_rank`` is set.
"""

import os
import shutil
import subprocess

from .logging import logger


def parse_range_list(spec):
    """'0-3,8,10-11' → [0, 1, 2, 3, 8, 10, 11] (sorted, deduped)."""
    cores = set()
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-")
            lo, hi = int(lo), int(hi)
            if hi < lo:
                raise ValueError(f"invalid core range {part!r}")
            cores.update(range(lo, hi + 1))
        else:
            cores.add(int(part))
    return sorted(cores)


def get_numa_cores():
    """[[cores of node 0], [cores of node 1], ...] via ``numactl
    --hardware``; [] when numactl is unavailable."""
    if shutil.which("numactl") is None:
        return []
    try:
        out = subprocess.check_output(["numactl", "--hardware"],
                                      text=True, stderr=subprocess.DEVNULL)
    except (OSError, subprocess.CalledProcessError):
        return []
    nodes = []
    for line in out.splitlines():
        # 'node 0 cpus: 0 1 2 3 ...'
        parts = line.split()
        if len(parts) >= 3 and parts[0] == "node" and parts[2] == "cpus:":
            nodes.append([int(c) for c in parts[3:]])
    return nodes


def _cpu_count():
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:
        return os.cpu_count() or 1


def get_numactl_cmd(bind_core_list, num_local_procs, local_rank):
    """numactl prefix binding ``local_rank`` (of ``num_local_procs``) to its
    core slice; membind to the covering NUMA node(s) when determinable.

    Returns (cmd_prefix: list[str], cores_per_rank: int) — the caller
    should also set OMP_NUM_THREADS=cores_per_rank for the child."""
    if bind_core_list:
        cores = parse_range_list(bind_core_list)
    else:
        cores = list(range(_cpu_count()))
    per_rank = len(cores) // num_local_procs
    if per_rank < 1:
        raise ValueError(
            f"{len(cores)} cores cannot bind {num_local_procs} local "
            "processes (need ≥1 core per rank)")
    mine = cores[per_rank * local_rank:per_rank * (local_rank + 1)]
    if shutil.which("numactl") is None:
        # no numactl → no numactl/KMP conflict either; degrade, don't abort
        logger.warning("numactl not installed — skipping core binding")
        return [], per_rank
    if "KMP_AFFINITY" in os.environ:
        raise ValueError(
            "KMP_AFFINITY conflicts with numactl core binding; unset it "
            "before launching with --bind_cores_to_rank")
    cmd = ["numactl", "-C", ",".join(map(str, mine))]
    # membind when the slice is covered by identifiable NUMA node(s)
    nodes = [i for i, nc in enumerate(get_numa_cores())
             if nc and set(nc) & set(mine)]
    if nodes:
        cmd += ["-m", ",".join(map(str, nodes))]
    return cmd, per_rank
