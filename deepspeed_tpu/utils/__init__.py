from .logging import log_dist, logger, print_json_dist, warning_once
from .timer import NoopTimer, SynchronizedWallClockTimer, ThroughputTimer
from . import groups


def __getattr__(name):
    # reference surface: ``deepspeed.utils.RepeatingLoader`` (utils/__init__
    # re-exports it from runtime.dataloader); lazy here to avoid a
    # utils ↔ runtime import cycle.  PrefetchLoader is the TPU extension.
    if name in ("RepeatingLoader", "PrefetchLoader"):
        from ..runtime import dataloader
        return getattr(dataloader, name)
    raise AttributeError(name)
from .tensor_fragment import (safe_get_full_fp32_param, safe_get_full_grad,
                              safe_get_full_optimizer_state,
                              safe_get_local_fp32_param, safe_get_local_grad,
                              safe_get_local_optimizer_state,
                              safe_set_full_fp32_param,
                              safe_set_full_optimizer_state)
