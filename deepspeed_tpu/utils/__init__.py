from .logging import log_dist, logger, print_json_dist, warning_once
from .timer import NoopTimer, SynchronizedWallClockTimer, ThroughputTimer
from . import groups
from . import tensor_fragment
from .tensor_fragment import (  # reference deepspeed.utils surface
    safe_get_full_fp32_param, safe_get_full_grad,
    safe_get_full_optimizer_state, safe_get_local_fp32_param,
    safe_get_local_grad, safe_get_local_optimizer_state,
    safe_set_full_fp32_param, safe_set_full_grad,
    safe_set_full_optimizer_state, safe_set_local_fp32_param,
    safe_set_local_grad, safe_set_local_optimizer_state)
from .numa import get_numactl_cmd


def instrument_w_nvtx(func):
    """Reference ``deepspeed.utils.instrument_w_nvtx`` — wraps a function in
    an NVTX range for nsys traces.  NVTX is CUDA tooling; the TPU analog is
    ``jax.profiler.TraceAnnotation`` feeding the xplane trace the flops
    profiler captures."""
    import functools

    import jax

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(getattr(func, "__qualname__",
                                                  func.__name__)):
            return func(*args, **kwargs)

    return wrapped


# ---- z3 ("ZeRO-3 leaf module") API — designed away, kept for imports.
# Reference ``deepspeed/utils/z3_leaf_module.py`` marks modules whose params
# must fetch as ONE unit so the hook-driven prefetcher doesn't thrash (MoE
# blocks).  Under GSPMD there are no hooks: the whole step is one compiled
# program and XLA's latency-hiding scheduler owns gather placement, so leaf
# marking has nothing to steer.  The markers record intent and return
# sensible values so reference-shaped code runs unchanged.
def set_z3_leaf_modules(model, leaf_module_classes):
    for cls in leaf_module_classes:
        setattr(cls, "_z3_leaf", True)
    return list(leaf_module_classes)


def unset_z3_leaf_modules(model, leaf_module_classes):
    for cls in leaf_module_classes:
        if getattr(cls, "_z3_leaf", False):
            cls._z3_leaf = False
    return list(leaf_module_classes)


def set_z3_leaf_module(model, flag=True):
    type(model)._z3_leaf = flag


def z3_leaf_module(model) -> bool:
    return bool(getattr(type(model), "_z3_leaf", False))


def z3_leaf_parameter(param) -> bool:
    # params are plain arrays here; leaf-ness is a module property
    return False


def get_z3_leaf_modules(model):
    return [type(model)] if z3_leaf_module(model) else []


def __getattr__(name):
    # reference surface: ``deepspeed.utils.RepeatingLoader`` (utils/__init__
    # re-exports it from runtime.dataloader); lazy here to avoid a
    # utils ↔ runtime import cycle.  PrefetchLoader is the TPU extension.
    if name in ("RepeatingLoader", "PrefetchLoader"):
        from ..runtime import dataloader
        return getattr(dataloader, name)
    raise AttributeError(name)
