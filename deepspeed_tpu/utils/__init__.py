from .logging import log_dist, logger, print_json_dist, warning_once
from .timer import NoopTimer, SynchronizedWallClockTimer, ThroughputTimer
from . import groups
from .tensor_fragment import (safe_get_full_fp32_param, safe_get_full_grad,
                              safe_get_full_optimizer_state,
                              safe_get_local_fp32_param, safe_get_local_grad,
                              safe_get_local_optimizer_state,
                              safe_set_full_fp32_param,
                              safe_set_full_optimizer_state)
