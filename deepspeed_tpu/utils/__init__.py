from .logging import log_dist, logger, print_json_dist, warning_once
from .timer import NoopTimer, SynchronizedWallClockTimer, ThroughputTimer
from . import groups
