"""OnDevice — construct models without materializing weights.

Reference ``deepspeed/utils/init_on_device.py`` (``OnDevice`` meta-device
context): patches torch tensor constructors so huge models build with no
storage.  The JAX analog is ``jax.eval_shape``; inside ``OnDevice(
device="meta")`` every flax ``Module.init`` returns a tree of
``jax.ShapeDtypeStruct`` — shapes and dtypes, zero bytes — which is exactly
what ``engine.initialize_parameters`` / checkpoint restore consume to
materialize directly into the sharded layout.

With a real ``device``, ``init`` simply runs under ``jax.default_device``.

    with OnDevice(dtype=jnp.bfloat16, device="meta"):
        abstract = model.init(rng, sample)     # ShapeDtypeStructs
"""

import contextlib
import contextvars

import jax

# The meta-device patch necessarily rebinds ``nn.Module.init`` process-wide,
# but the *effect* is scoped per-context: the wrapper abstracts only inits
# initiated from a thread/context that is inside an OnDevice("meta") block;
# concurrent unrelated inits on other threads run the original (round-2
# advisor finding).
_meta_active = contextvars.ContextVar("ds_on_device_meta", default=False)


class OnDevice:
    """Context manager: abstract (meta) or device-targeted flax init."""

    def __init__(self, dtype=None, device="meta", enabled=True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._stack = None

    def __enter__(self):
        if not self.enabled:
            return self
        self._stack = contextlib.ExitStack()
        if self.device == "meta":
            import flax.linen as nn
            orig_init = nn.Module.init
            me = self

            def abstract_init(module, rngs, *args, **kwargs):
                if not _meta_active.get():
                    return orig_init(module, rngs, *args, **kwargs)
                out = jax.eval_shape(
                    lambda r, *a: orig_init(module, r, *a, **kwargs),
                    rngs, *args)
                if me.dtype is not None:
                    out = jax.tree_util.tree_map(
                        lambda s: jax.ShapeDtypeStruct(s.shape, me.dtype)
                        if jax.numpy.issubdtype(s.dtype,
                                                jax.numpy.floating) else s,
                        out)
                return out

            nn.Module.init = abstract_init
            self._stack.callback(setattr, nn.Module, "init", orig_init)
            token = _meta_active.set(True)
            self._stack.callback(_meta_active.reset, token)
        else:
            dev = (self.device if not isinstance(self.device, str)
                   else jax.devices(self.device)[0])
            self._stack.enter_context(jax.default_device(dev))
        return self

    def __exit__(self, *exc):
        stack, self._stack = self._stack, None
        if stack is not None:
            stack.close()
        return False
