// ds_aio — threaded async block I/O library backing NVMe/disk offload.
//
// TPU-native rebuild of the reference's csrc/aio (libaio-based
// deepspeed_aio_thread.cpp / deepspeed_py_io_handle.cpp): a pool of I/O
// threads services read/write requests; each request is split into
// block_size chunks fanned out across the pool (the reference's
// queue-depth×block-size parallel submission), completion is tracked
// per-request so Python can overlap compute with swap traffic and wait()
// only when the tensor is needed.
//
// Exposed as a plain C API for ctypes (no pybind11 in this image).
// Alignment: buffers are caller-owned (numpy); we use plain pread/pwrite on
// a per-thread fd (O_DIRECT needs aligned userland buffers — opt-in flag).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
    std::atomic<int64_t> pending_chunks{0};
    std::atomic<int64_t> errors{0};
    bool write = false;
    std::mutex mu;
    std::condition_variable cv;
};

struct Chunk {
    std::shared_ptr<Request> req;
    std::string path;
    char* buf;
    int64_t count;
    int64_t offset;
    bool write;
};

class AioHandle {
  public:
    AioHandle(int64_t block_size, int queue_depth, int n_threads,
              bool o_direct)
        : block_size_(block_size > 0 ? block_size : (1 << 20)),
          queue_depth_(queue_depth > 0 ? queue_depth : 32),
          o_direct_(o_direct), stop_(false) {
        if (n_threads <= 0) n_threads = 4;
        for (int i = 0; i < n_threads; ++i)
            workers_.emplace_back([this] { worker_loop(); });
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
    }

    int64_t submit(const char* path, void* buf, int64_t count, int64_t offset,
                   bool write) {
        auto req = std::make_shared<Request>();
        req->write = write;
        int64_t n_chunks = (count + block_size_ - 1) / block_size_;
        if (n_chunks == 0) n_chunks = 1;
        req->pending_chunks.store(n_chunks);
        int64_t id;
        {
            std::unique_lock<std::mutex> lk(mu_);
            id = next_id_++;
            requests_[id] = req;
            for (int64_t c = 0; c < n_chunks; ++c) {
                // backpressure: queue_depth bounds in-flight chunks; workers
                // notify as they drain
                cv_.wait(lk, [&] {
                    return stop_ ||
                           static_cast<int>(queue_.size()) < queue_depth_;
                });
                if (stop_) {  // shutting down: unqueued chunks won't run
                    req->pending_chunks.fetch_sub(n_chunks - c);
                    break;
                }
                int64_t chunk_off = c * block_size_;
                int64_t chunk_len = std::min(block_size_, count - chunk_off);
                if (chunk_len <= 0) chunk_len = 0;
                queue_.push_back(Chunk{req, path,
                                       static_cast<char*>(buf) + chunk_off,
                                       chunk_len, offset + chunk_off, write});
                cv_.notify_one();
            }
        }
        cv_.notify_all();
        return id;
    }

    // returns 0 on success, -1 on I/O error
    int wait(int64_t id) {
        std::shared_ptr<Request> req;
        {
            std::lock_guard<std::mutex> lk(mu_);
            auto it = requests_.find(id);
            if (it == requests_.end()) return -2;
            req = it->second;
        }
        {
            std::unique_lock<std::mutex> lk(req->mu);
            req->cv.wait(lk, [&] { return req->pending_chunks.load() == 0; });
        }
        int rc = req->errors.load() ? -1 : 0;
        {
            std::lock_guard<std::mutex> lk(mu_);
            requests_.erase(id);
        }
        return rc;
    }

    int64_t pending() {
        std::lock_guard<std::mutex> lk(mu_);
        return static_cast<int64_t>(requests_.size());
    }

    int64_t block_size() const { return block_size_; }
    int queue_depth() const { return queue_depth_; }
    int n_threads() const { return static_cast<int>(workers_.size()); }

  private:
    void worker_loop() {
        for (;;) {
            Chunk chunk;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [&] { return stop_ || !queue_.empty(); });
                if (stop_ && queue_.empty()) return;
                chunk = std::move(queue_.front());
                queue_.pop_front();
                cv_.notify_all();  // wake submitters waiting for queue space
            }
            run_chunk(chunk);
        }
    }

    void run_chunk(Chunk& chunk) {
        int flags = chunk.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
#ifdef O_DIRECT
        // same per-request contract as the io_uring engine: O_DIRECT only
        // when (buffer, offset, length) are 4KiB-aligned, silent buffered
        // fallback otherwise — an unaligned request must not EINVAL just
        // because this engine was selected
        constexpr int64_t kAlign = 4096;
        if (o_direct_ && chunk.count > 0 &&
            reinterpret_cast<uintptr_t>(chunk.buf) % kAlign == 0 &&
            chunk.offset % kAlign == 0 && chunk.count % kAlign == 0)
            flags |= O_DIRECT;
#endif
        bool failed = false;
        int fd = ::open(chunk.path.c_str(), flags, 0644);
        if (fd < 0) {
            failed = true;
        } else {
            int64_t done = 0;
            while (done < chunk.count) {
                ssize_t n =
                    chunk.write
                        ? ::pwrite(fd, chunk.buf + done, chunk.count - done,
                                   chunk.offset + done)
                        : ::pread(fd, chunk.buf + done, chunk.count - done,
                                  chunk.offset + done);
                if (n <= 0) {
                    failed = true;
                    break;
                }
                done += n;
            }
            ::close(fd);
        }
        if (failed) chunk.req->errors.fetch_add(1);
        if (chunk.req->pending_chunks.fetch_sub(1) == 1) {
            std::lock_guard<std::mutex> lk(chunk.req->mu);
            chunk.req->cv.notify_all();
        }
    }

    int64_t block_size_;
    int queue_depth_;
    bool o_direct_;
    bool stop_;
    std::mutex mu_;
    std::condition_variable cv_;
    std::deque<Chunk> queue_;
    std::map<int64_t, std::shared_ptr<Request>> requests_;
    int64_t next_id_ = 1;
    std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int64_t block_size, int queue_depth, int n_threads,
                        int o_direct) {
    return new AioHandle(block_size, queue_depth, n_threads, o_direct != 0);
}

void ds_aio_handle_free(void* h) { delete static_cast<AioHandle*>(h); }

int64_t ds_aio_submit_read(void* h, const char* path, void* buf,
                           int64_t count, int64_t offset) {
    return static_cast<AioHandle*>(h)->submit(path, buf, count, offset,
                                              false);
}

int64_t ds_aio_submit_write(void* h, const char* path, void* buf,
                            int64_t count, int64_t offset) {
    return static_cast<AioHandle*>(h)->submit(path, buf, count, offset, true);
}

int ds_aio_wait(void* h, int64_t req_id) {
    return static_cast<AioHandle*>(h)->wait(req_id);
}

int64_t ds_aio_pending(void* h) {
    return static_cast<AioHandle*>(h)->pending();
}

// synchronous convenience (submit+wait)
int ds_aio_pread(void* h, const char* path, void* buf, int64_t count,
                 int64_t offset) {
    auto* handle = static_cast<AioHandle*>(h);
    return handle->wait(handle->submit(path, buf, count, offset, false));
}

int ds_aio_pwrite(void* h, const char* path, void* buf, int64_t count,
                  int64_t offset) {
    auto* handle = static_cast<AioHandle*>(h);
    return handle->wait(handle->submit(path, buf, count, offset, true));
}

int64_t ds_aio_block_size(void* h) {
    return static_cast<AioHandle*>(h)->block_size();
}
int ds_aio_queue_depth(void* h) {
    return static_cast<AioHandle*>(h)->queue_depth();
}
int ds_aio_thread_count(void* h) {
    return static_cast<AioHandle*>(h)->n_threads();
}

}  // extern "C"
