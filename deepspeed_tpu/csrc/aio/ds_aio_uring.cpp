// ds_aio_uring — io_uring-backed async block I/O engine.
//
// TPU-native rebuild of the reference's libaio queue-depth engine
// (csrc/aio/py_lib/deepspeed_aio_thread.cpp + deepspeed_py_io_handle.cpp):
// instead of a pool of threads each doing synchronous pread/pwrite (the
// fallback engine in ds_aio.cpp), ONE driver thread keeps `queue_depth`
// chunk-sized operations in flight inside a single io_uring — the kernel's
// async submission path is what saturates NVMe queue pairs, which is the
// property ZeRO-Infinity swap throughput depends on.
//
// Raw ABI (no liburing in this image): io_uring_setup/enter via syscall(2),
// SQ/CQ rings mmap'd per <linux/io_uring.h>.  O_DIRECT is applied
// per-request when the (buffer, offset, length) triple is 4KiB-aligned —
// misaligned requests silently fall back to page-cache I/O, so callers can
// opt in without alignment bookkeeping (aio_aligned_empty in ops/aio.py
// produces qualifying buffers).
//
// Exposed as a plain C API for ctypes, mirroring ds_aio.cpp's exports with
// a ds_uring_ prefix; ops/aio.py's AIOHandle picks the engine at runtime.

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <linux/io_uring.h>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr int64_t kDirectAlign = 4096;

int io_uring_setup(unsigned entries, io_uring_params* p) {
    return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}

int io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                   unsigned flags) {
    return static_cast<int>(syscall(__NR_io_uring_enter, fd, to_submit,
                                    min_complete, flags, nullptr, 0));
}

struct Request {
    std::atomic<int64_t> pending_chunks{0};
    std::atomic<int64_t> errors{0};
    int fd = -1;
    std::mutex mu;
    std::condition_variable cv;
};

struct Chunk {
    std::shared_ptr<Request> req;
    char* buf;
    int64_t count;
    int64_t offset;
    bool write;
};

class UringEngine {
  public:
    static bool available() {
        io_uring_params p{};
        int fd = io_uring_setup(2, &p);
        if (fd < 0) return false;
        ::close(fd);
        return true;
    }

    UringEngine(int64_t block_size, int queue_depth, bool o_direct)
        : block_size_(block_size > 0 ? block_size : (1 << 20)),
          queue_depth_(queue_depth > 0 ? queue_depth : 32),
          o_direct_(o_direct) {
        io_uring_params p{};
        ring_fd_ = io_uring_setup(queue_depth_, &p);
        if (ring_fd_ < 0) throw std::runtime_error("io_uring_setup failed");
        sq_entries_ = p.sq_entries;
        cq_entries_ = p.cq_entries;

        size_t sq_sz = p.sq_off.array + p.sq_entries * sizeof(uint32_t);
        size_t cq_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
        if (p.features & IORING_FEAT_SINGLE_MMAP) {
            sq_map_sz_ = cq_map_sz_ = std::max(sq_sz, cq_sz);
            sq_ring_ = mmap_ring(sq_map_sz_, IORING_OFF_SQ_RING);
            cq_ring_ = sq_ring_;
        } else {
            sq_map_sz_ = sq_sz;
            cq_map_sz_ = cq_sz;
            sq_ring_ = mmap_ring(sq_sz, IORING_OFF_SQ_RING);
            cq_ring_ = mmap_ring(cq_sz, IORING_OFF_CQ_RING);
        }
        sqes_sz_ = p.sq_entries * sizeof(io_uring_sqe);
        sqes_ = static_cast<io_uring_sqe*>(
            mmap_ring(sqes_sz_, IORING_OFF_SQES));

        sq_head_ = ring_u32(sq_ring_, p.sq_off.head);
        sq_tail_ = ring_u32(sq_ring_, p.sq_off.tail);
        sq_mask_ = *ring_u32(sq_ring_, p.sq_off.ring_mask);
        sq_array_ = ring_u32(sq_ring_, p.sq_off.array);
        cq_head_ = ring_u32(cq_ring_, p.cq_off.head);
        cq_tail_ = ring_u32(cq_ring_, p.cq_off.tail);
        cq_mask_ = *ring_u32(cq_ring_, p.cq_off.ring_mask);
        cqes_ = reinterpret_cast<io_uring_cqe*>(
            static_cast<char*>(cq_ring_) + p.cq_off.cqes);

        driver_ = std::thread([this] { drive(); });
    }

    ~UringEngine() {
        {
            std::lock_guard<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        if (driver_.joinable()) driver_.join();
        if (sqes_) munmap(sqes_, sqes_sz_);
        if (cq_ring_ && cq_ring_ != sq_ring_) munmap(cq_ring_, cq_map_sz_);
        if (sq_ring_) munmap(sq_ring_, sq_map_sz_);
        if (ring_fd_ >= 0) ::close(ring_fd_);
    }

    int64_t submit(const char* path, void* buf, int64_t count, int64_t offset,
                   bool write) {
        auto req = std::make_shared<Request>();
        int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
        bool aligned = o_direct_ && count > 0 &&
                       (reinterpret_cast<uintptr_t>(buf) % kDirectAlign) == 0 &&
                       (offset % kDirectAlign) == 0 &&
                       (count % kDirectAlign) == 0;
#ifdef O_DIRECT
        if (aligned) flags |= O_DIRECT;
#endif
        req->fd = ::open(path, flags, 0644);
        int64_t n_chunks =
            count > 0 ? (count + block_size_ - 1) / block_size_ : 1;
        req->pending_chunks.store(n_chunks);
        int64_t id;
        {
            std::lock_guard<std::mutex> lk(mu_);
            id = next_id_++;
            requests_[id] = req;
            if (req->fd < 0) {
                req->errors.fetch_add(1);
                req->pending_chunks.store(0);
            } else {
                for (int64_t c = 0; c < n_chunks; ++c) {
                    int64_t off = c * block_size_;
                    int64_t len = std::min(block_size_, count - off);
                    if (len < 0) len = 0;
                    chunks_.push_back(Chunk{req, static_cast<char*>(buf) + off,
                                            len, offset + off, write});
                }
            }
        }
        cv_.notify_all();
        return id;
    }

    int wait(int64_t id) {
        std::shared_ptr<Request> req;
        {
            std::lock_guard<std::mutex> lk(mu_);
            auto it = requests_.find(id);
            if (it == requests_.end()) return -2;
            req = it->second;
        }
        {
            std::unique_lock<std::mutex> lk(req->mu);
            req->cv.wait(lk, [&] { return req->pending_chunks.load() == 0; });
        }
        int rc = req->errors.load() ? -1 : 0;
        {
            std::lock_guard<std::mutex> lk(mu_);
            requests_.erase(id);
        }
        return rc;
    }

    int64_t pending() {
        std::lock_guard<std::mutex> lk(mu_);
        return static_cast<int64_t>(requests_.size());
    }

    int64_t block_size() const { return block_size_; }
    int queue_depth() const { return queue_depth_; }

  private:
    void* mmap_ring(size_t sz, uint64_t off) {
        void* p = mmap(nullptr, sz, PROT_READ | PROT_WRITE,
                       MAP_SHARED | MAP_POPULATE, ring_fd_, off);
        if (p == MAP_FAILED) throw std::runtime_error("io_uring mmap failed");
        return p;
    }

    static uint32_t* ring_u32(void* base, uint32_t off) {
        return reinterpret_cast<uint32_t*>(static_cast<char*>(base) + off);
    }

    // Driver loop: keep up to sq_entries_ chunk ops in flight; block in
    // io_uring_enter(GETEVENTS) only while something is in flight, else on
    // the condition variable.  Short reads/writes are re-queued with the
    // remainder adjusted — required for O_DIRECT tails and EINTR.
    void drive() {
        for (;;) {
            unsigned to_submit = 0;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [&] {
                    return stop_ || !chunks_.empty() || inflight_ > 0;
                });
                if (stop_ && chunks_.empty() && inflight_ == 0) return;
                // fill SQEs from the chunk queue
                uint32_t tail = load_acquire(sq_tail_);
                while (!chunks_.empty() &&
                       inflight_ + to_submit < static_cast<unsigned>(
                                                   sq_entries_)) {
                    Chunk* c = new Chunk(std::move(chunks_.front()));
                    chunks_.pop_front();
                    if (c->count == 0) {  // zero-length: complete immediately
                        complete_chunk(c, /*err=*/false);
                        continue;
                    }
                    uint32_t idx = tail & sq_mask_;
                    io_uring_sqe* sqe = &sqes_[idx];
                    std::memset(sqe, 0, sizeof(*sqe));
                    sqe->opcode = c->write ? IORING_OP_WRITE : IORING_OP_READ;
                    sqe->fd = c->req->fd;
                    sqe->addr = reinterpret_cast<uint64_t>(c->buf);
                    sqe->len = static_cast<uint32_t>(c->count);
                    sqe->off = static_cast<uint64_t>(c->offset);
                    sqe->user_data = reinterpret_cast<uint64_t>(c);
                    sq_array_[idx] = idx;
                    ++tail;
                    ++to_submit;
                }
                store_release(sq_tail_, tail);
                inflight_ += to_submit;
            }
            // Derive to_submit from the ring itself: entries the kernel has
            // not consumed yet (sq head..tail) — a previous partial/failed
            // enter leaves them queued and this naturally resubmits them.
            uint32_t pending_sq =
                load_acquire(sq_tail_) - load_acquire(sq_head_);
            if (pending_sq > 0 || inflight_load() > 0) {
                int rc = io_uring_enter(ring_fd_, pending_sq,
                                        /*min_complete=*/1,
                                        IORING_ENTER_GETEVENTS);
                if (rc < 0 && errno != EINTR && errno != EAGAIN &&
                    errno != EBUSY) {
                    fail_unsubmitted();
                    continue;
                }
            }
            reap();
        }
    }

    uint32_t inflight_load() {
        std::lock_guard<std::mutex> lk(mu_);
        return inflight_;
    }

    void reap() {
        uint32_t head = load_acquire(cq_head_);
        for (;;) {
            uint32_t tail = load_acquire(cq_tail_);
            if (head == tail) break;
            io_uring_cqe* cqe = &cqes_[head & cq_mask_];
            Chunk* c = reinterpret_cast<Chunk*>(cqe->user_data);
            int32_t res = cqe->res;
            ++head;
            store_release(cq_head_, head);
            if (res == -EINTR || res == -EAGAIN) {
                requeue(c);  // retry whole chunk
            } else if (res <= 0) {
                complete_chunk(c, /*err=*/true);
            } else if (res < c->count) {
                c->buf += res;
                c->offset += res;
                c->count -= res;
                requeue(c);  // short I/O: finish the remainder
            } else {
                complete_chunk(c, /*err=*/false);
            }
            {
                std::lock_guard<std::mutex> lk(mu_);
                if (inflight_ > 0) --inflight_;
            }
        }
    }

    void requeue(Chunk* c) {
        {
            std::lock_guard<std::mutex> lk(mu_);
            chunks_.push_front(std::move(*c));
        }
        delete c;
    }

    void complete_chunk(Chunk* c, bool err) {
        auto req = c->req;
        if (err) req->errors.fetch_add(1);
        int64_t prev = req->pending_chunks.fetch_sub(1);
        if (prev <= 0) {
            // already force-completed by the failure path; a late CQE for a
            // kernel-accepted op must not re-run completion bookkeeping
            req->pending_chunks.fetch_add(1);
        } else if (prev == 1) {
            if (req->fd >= 0) ::close(req->fd);
            req->fd = -1;
            std::lock_guard<std::mutex> lk(req->mu);
            req->cv.notify_all();
        }
        delete c;
    }

    // io_uring_enter failed non-retryably: ops the kernel ALREADY accepted
    // will still post CQEs (reap handles them normally), but SQEs it never
    // consumed and chunks never staged would wait forever — fail those so
    // waiters unblock with an error instead of hanging (matches reference
    // aio error propagation).
    void fail_unsubmitted() {
        reap();  // drain whatever did complete first
        std::lock_guard<std::mutex> lk(mu_);
        // drop ring entries the kernel never consumed: rewind our tail to
        // the kernel's head and fail their chunks (user_data owns them)
        uint32_t khead = load_acquire(sq_head_);
        uint32_t tail = load_acquire(sq_tail_);
        for (uint32_t i = khead; i != tail; ++i) {
            io_uring_sqe* sqe = &sqes_[sq_array_[i & sq_mask_]];
            complete_chunk(reinterpret_cast<Chunk*>(sqe->user_data),
                           /*err=*/true);
            if (inflight_ > 0) --inflight_;
        }
        store_release(sq_tail_, khead);
        // fail everything still queued host-side
        for (auto& c : chunks_)
            complete_chunk(new Chunk(std::move(c)), /*err=*/true);
        chunks_.clear();
    }

    static uint32_t load_acquire(uint32_t* p) {
        return __atomic_load_n(p, __ATOMIC_ACQUIRE);
    }
    static void store_release(uint32_t* p, uint32_t v) {
        __atomic_store_n(p, v, __ATOMIC_RELEASE);
    }

    int64_t block_size_;
    int queue_depth_;
    bool o_direct_;
    int ring_fd_ = -1;
    unsigned sq_entries_ = 0, cq_entries_ = 0;
    void* sq_ring_ = nullptr;
    void* cq_ring_ = nullptr;
    io_uring_sqe* sqes_ = nullptr;
    size_t sq_map_sz_ = 0, cq_map_sz_ = 0, sqes_sz_ = 0;
    uint32_t *sq_head_ = nullptr, *sq_tail_ = nullptr, *sq_array_ = nullptr;
    uint32_t *cq_head_ = nullptr, *cq_tail_ = nullptr;
    uint32_t sq_mask_ = 0, cq_mask_ = 0;
    io_uring_cqe* cqes_ = nullptr;

    bool stop_ = false;
    std::mutex mu_;
    std::condition_variable cv_;
    std::deque<Chunk> chunks_;
    std::map<int64_t, std::shared_ptr<Request>> requests_;
    int64_t next_id_ = 1;
    uint32_t inflight_ = 0;
    std::thread driver_;
};

}  // namespace

extern "C" {

int ds_uring_available() { return UringEngine::available() ? 1 : 0; }

void* ds_uring_handle_new(int64_t block_size, int queue_depth, int o_direct) {
    try {
        return new UringEngine(block_size, queue_depth, o_direct != 0);
    } catch (...) {
        return nullptr;
    }
}

void ds_uring_handle_free(void* h) { delete static_cast<UringEngine*>(h); }

int64_t ds_uring_submit_read(void* h, const char* path, void* buf,
                             int64_t count, int64_t offset) {
    return static_cast<UringEngine*>(h)->submit(path, buf, count, offset,
                                                false);
}

int64_t ds_uring_submit_write(void* h, const char* path, void* buf,
                              int64_t count, int64_t offset) {
    return static_cast<UringEngine*>(h)->submit(path, buf, count, offset,
                                                true);
}

int ds_uring_wait(void* h, int64_t req_id) {
    return static_cast<UringEngine*>(h)->wait(req_id);
}

int64_t ds_uring_pending(void* h) {
    return static_cast<UringEngine*>(h)->pending();
}

int ds_uring_pread(void* h, const char* path, void* buf, int64_t count,
                   int64_t offset) {
    auto* e = static_cast<UringEngine*>(h);
    return e->wait(e->submit(path, buf, count, offset, false));
}

int ds_uring_pwrite(void* h, const char* path, void* buf, int64_t count,
                    int64_t offset) {
    auto* e = static_cast<UringEngine*>(h);
    return e->wait(e->submit(path, buf, count, offset, true));
}

}  // extern "C"
