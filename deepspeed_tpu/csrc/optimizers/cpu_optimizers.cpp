// SIMD host optimizers for ZeRO-Offload — rebuild of the reference's
// csrc/adam/cpu_adam_impl.cpp, csrc/adagrad/cpu_adagrad.cpp and
// csrc/lion/cpu_lion.cpp (AVX via csrc/includes/simd.h).
//
// The offloaded fp32 master partition + optimizer moments live in host RAM
// (numpy); the engine calls these kernels instead of shipping the update to
// the TPU.  Vectorization comes from OpenMP `parallel for simd` + -O3
// -march=native (the compiler emits AVX/AVX-512 — same effect as the
// reference's hand-written SIMD wrappers, portable across hosts).
//
// All kernels also accept a bf16 (uint16) shadow "compute param" output so
// the updated weights can be sent back to device without a host-side fp32
// copy pass.

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

inline uint16_t fp32_to_bf16(float f) {
    uint32_t x;
    std::memcpy(&x, &f, 4);
    // NaN must not round into inf: quiet it before the bias addition
    if ((x & 0x7FFFFFFFu) > 0x7F800000u) {
        return static_cast<uint16_t>((x >> 16) | 0x0040u);
    }
    // round-to-nearest-even
    uint32_t rounding_bias = 0x7FFF + ((x >> 16) & 1);
    return static_cast<uint16_t>((x + rounding_bias) >> 16);
}

}  // namespace

extern "C" {

// Adam / AdamW (adamw != 0 → decoupled weight decay).
// step is 1-based; bias correction matches torch.optim.Adam.
void ds_cpu_adam_step(float* param, const float* grad, float* exp_avg,
                      float* exp_avg_sq, int64_t n, float lr, float beta1,
                      float beta2, float eps, float weight_decay, int step,
                      int adamw, uint16_t* bf16_out) {
    const float bc1 = 1.0f - std::pow(beta1, step);
    const float bc2 = 1.0f - std::pow(beta2, step);
    const float step_size = lr / bc1;
    const float bc2_sqrt = std::sqrt(bc2);

#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i];
        float p = param[i];
        if (weight_decay != 0.0f) {
            if (adamw) {
                p -= lr * weight_decay * p;
            } else {
                g += weight_decay * p;
            }
        }
        float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        p -= step_size * m / (std::sqrt(v) / bc2_sqrt + eps);
        param[i] = p;
        if (bf16_out) bf16_out[i] = fp32_to_bf16(p);
    }
}

// Adagrad (reference csrc/adagrad/cpu_adagrad.cpp).
void ds_cpu_adagrad_step(float* param, const float* grad, float* state_sum,
                         int64_t n, float lr, float eps, float weight_decay,
                         uint16_t* bf16_out) {
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i];
        float p = param[i];
        if (weight_decay != 0.0f) g += weight_decay * p;
        float s = state_sum[i] + g * g;
        state_sum[i] = s;
        p -= lr * g / (std::sqrt(s) + eps);
        param[i] = p;
        if (bf16_out) bf16_out[i] = fp32_to_bf16(p);
    }
}

// Lion (reference csrc/lion/cpu_lion.cpp): sign-of-interpolation update.
void ds_cpu_lion_step(float* param, const float* grad, float* exp_avg,
                      int64_t n, float lr, float beta1, float beta2,
                      float weight_decay, uint16_t* bf16_out) {
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i];
        float p = param[i];
        float m = exp_avg[i];
        float c = beta1 * m + (1.0f - beta1) * g;
        p -= lr * weight_decay * p;  // lion uses decoupled decay
        p -= lr * (c > 0.0f ? 1.0f : (c < 0.0f ? -1.0f : 0.0f));
        exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
        param[i] = p;
        if (bf16_out) bf16_out[i] = fp32_to_bf16(p);
    }
}

// fused grad-norm-squared over a flat buffer (used by host-side clipping)
double ds_cpu_sq_norm(const float* grad, int64_t n) {
    double acc = 0.0;
#pragma omp parallel for simd reduction(+ : acc) schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        acc += static_cast<double>(grad[i]) * static_cast<double>(grad[i]);
    }
    return acc;
}

}  // extern "C"
