"""Dataflow tensor-parallel parser (reference ``module_inject/auto_tp.py:273
tp_parser`` + ``:330 _replace``).

The reference walks the torch module graph to find "linears followed by an
all-reduce point".  The TPU-native equivalent walks the model's **jaxpr**: a
taint analysis tracks which kernel parameters each activation derives from,
and a residual ``add`` merging two differently-tainted branches is the
all-reduce point —

* the kernel that *produced* the merged operand (the last matmul on that
  branch) is ROW-parallel (shard its contracting/input dim; XLA inserts the
  psum the reference codes as ``LinearAllreduce``);
* every other kernel in the branch's taint is COLUMN-parallel (shard its
  output dim);
* params consumed by gathers (embeddings) are vocab-sharded;
* anything the analysis can't reach falls back to the name heuristics in
  ``auto_tp.AutoTP`` (the reference keeps per-arch policy lists for the same
  reason).

Works on any traceable ``apply(params, *inputs)`` — no per-arch containers
needed for the zoo models (bert/gpt2/llama/mixtral traced in tests).
"""

from typing import Dict, NamedTuple, Optional

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from ..runtime.zero.partition import path_str
from ..utils.logging import logger


class _State(NamedTuple):
    """Dataflow fact for one jaxpr var."""
    taint: frozenset          # kernel param ids since the last residual merge
    last_kernel: Optional[int]  # id of the matmul kernel that produced it
    param: Optional[int]        # id if var is a pure transform of ONE param

_EMPTY = _State(frozenset(), None, None)

_ELEMENTWISE_PASS = {
    "convert_element_type", "reshape", "transpose", "broadcast_in_dim",
    "squeeze", "slice", "dynamic_slice", "rev", "exp", "log", "tanh",
    "logistic", "rsqrt", "sqrt", "neg", "sign", "abs", "max", "min", "pow",
    "integer_pow", "erf", "cbrt", "concatenate", "pad", "stop_gradient",
    "reduce_max", "reduce_sum", "reduce_min", "div", "sub", "select_n",
    "exp2", "copy", "cumsum", "cumlogsumexp", "custom_jvp_call",
    "dynamic_update_slice", "iota", "gather", "clamp", "and", "or", "not",
    "eq", "ne", "lt", "le", "gt", "ge", "argmax", "argmin", "reduce_and",
    "reduce_or",
}


class TpParser:
    """One-shot parser: ``TpParser().parse(apply_fn, params, *inputs)`` →
    {"column": [paths], "row": [paths], "embed": [paths]}."""

    def __init__(self):
        self.kernel_class: Dict[int, str] = {}   # param id → column|row|embed
        self.param_paths: Dict[int, str] = {}

    # ------------------------------------------------------------ plumbing
    def parse(self, apply_fn, params, *inputs):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        paths = [path_str(kp) for kp, _ in
                 jax.tree_util.tree_leaves_with_path(params)]
        self.param_paths = dict(enumerate(paths))

        def flat_fn(flat_params, *ins):
            p = jax.tree_util.tree_unflatten(treedef, flat_params)
            return apply_fn(p, *ins)

        closed = jax.make_jaxpr(flat_fn)(leaves, *inputs)
        jaxpr = closed.jaxpr
        env: Dict = {}
        for i, v in enumerate(jaxpr.invars[:len(leaves)]):
            env[v] = _State(frozenset(), None, i)
        for v in jaxpr.invars[len(leaves):]:
            env[v] = _EMPTY
        self._walk(jaxpr, env)
        out = {"column": [], "row": [], "embed": [], "router": [],
               "expert_column": [], "expert_row": []}
        for pid, cls in self.kernel_class.items():
            out[cls].append(self.param_paths[pid])
        return out

    def _read(self, env, atom):
        if hasattr(atom, "val"):  # Literal
            return _EMPTY
        return env.get(atom, _EMPTY)

    def _walk(self, jaxpr, env):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            sub = self._subjaxpr(eqn)
            if sub is not None:
                self._recurse(eqn, sub, env)
                continue
            states = [self._read(env, a) for a in eqn.invars]
            if name == "dot_general":
                out = self._dot(states)
            elif name == "ragged_dot" or name == "ragged_dot_general":
                out = self._ragged_dot(states)
            elif name in ("add", "add_any"):
                out = self._add(states)
            elif name == "mul":
                out = self._mul(states)
            elif name == "gather" or name == "take":
                out = self._gather(states)
            elif name in ("sort", "top_k", "argsort"):
                # routers: a kernel whose output drives token routing is
                # gating logic, not a shardable linear — keep it replicated
                for s in states:
                    for k in s.taint:
                        if self.kernel_class.get(k) == "column":
                            self.kernel_class[k] = "router"
                out = self._passthrough(states, keep_last=False)
            else:
                keep = name in _ELEMENTWISE_PASS
                out = self._passthrough(states, keep_last=keep)
            for ov in eqn.outvars:
                env[ov] = out

    def _subjaxpr(self, eqn):
        for key in ("jaxpr", "call_jaxpr"):
            if key in eqn.params:
                j = eqn.params[key]
                return getattr(j, "jaxpr", j)
        if eqn.primitive.name == "scan":
            return None  # handled as passthrough (stacked-layer scan: the
            # block is uniform; callers parse the unstacked block instead)
        return None

    def _recurse(self, eqn, sub, env):
        inner_env = {}
        n = min(len(sub.invars), len(eqn.invars))
        # align trailing invars (leading invars may be consts)
        for iv, at in zip(sub.invars[len(sub.invars) - n:],
                          eqn.invars[len(eqn.invars) - n:]):
            inner_env[iv] = self._read(env, at)
        self._walk(sub, inner_env)
        for ov, sov in zip(eqn.outvars, sub.outvars):
            env[ov] = self._read(inner_env, sov)

    # ------------------------------------------------------------ transfer
    def _passthrough(self, states, keep_last=True):
        taint = frozenset().union(*[s.taint for s in states]) \
            if states else frozenset()
        params = {s.param for s in states if s.param is not None}
        lasts = {s.last_kernel for s in states if s.last_kernel is not None}
        # a pure-param transform stays param-pure only when nothing else
        # contributes taint
        param = params.pop() if len(params) == 1 and not taint else None
        last = lasts.pop() if keep_last and len(lasts) == 1 else None
        return _State(taint, last, param)

    def _dot(self, states):
        a, b = states[0], states[1]
        if b.param is not None and a.param is None:
            act, kernel = a, b.param
        elif a.param is not None and b.param is None:
            act, kernel = b, a.param
        else:
            # activation×activation (attention scores etc.): merge taints,
            # no owning kernel
            return _State(a.taint | b.taint, None, None)
        self.kernel_class.setdefault(kernel, "column")
        return _State(act.taint | {kernel}, kernel, None)

    def _ragged_dot(self, states):
        """Grouped expert matmul (``jax.lax.ragged_dot``): stacked expert
        kernels [E, in, out].  The first expert matmuls on a branch are
        expert-column; one consuming already-expert-tainted activations is
        the down-projection — expert-row."""
        a, b = states[0], states[1]
        if b.param is not None:
            act, kernel = a, b.param
        elif a.param is not None:
            act, kernel = b, a.param
        else:
            return _State(a.taint | b.taint, None, None)
        expert_ids = {k for k in act.taint
                      if self.kernel_class.get(k, "").startswith("expert")}
        cls = "expert_row" if expert_ids else "expert_column"
        self.kernel_class.setdefault(kernel, cls)
        return _State(act.taint | {kernel}, kernel, None)

    def _add(self, states):
        a, b = states[0], states[1]
        # bias add (one side param-pure) → passthrough
        if a.param is not None and not a.taint:
            return b
        if b.param is not None and not b.taint:
            return a
        if a.taint != b.taint and (a.last_kernel is not None
                                   or b.last_kernel is not None):
            # residual merge = the all-reduce point: the matmul that produced
            # a merged branch is the reference's "LinearAllreduce" linear
            # (covers the first block too, where the residual stream is a
            # taint-free embedding)
            for s in (a, b):
                if s.last_kernel is not None:
                    self.kernel_class[s.last_kernel] = "row"
            return _State(frozenset(), None, None)
        return self._passthrough(states)

    def _mul(self, states):
        a, b = states[0], states[1]
        # scale-by-param (norm weights) → passthrough of the activation
        if a.param is not None and not a.taint:
            return b
        if b.param is not None and not b.taint:
            return a
        # gating (silu(gate)·up): union, no single producer
        return _State(a.taint | b.taint, None, None)

    def _gather(self, states):
        src = states[0]
        if src.param is not None:
            self.kernel_class.setdefault(src.param, "embed")
            return _State(frozenset(), None, None)
        return self._passthrough(states)


def derive_tp_rules_from_dataflow(apply_fn, params, *inputs, tp_axis="tp",
                                  with_zero_pin=True):
    """Rule table (param-path suffix → PartitionSpec) from the dataflow
    classification; unclassified linears fall back to name heuristics
    (``AutoTP.derive_rules``).

    ``with_zero_pin`` appends the ``"zero"`` placeholder the way hand-written
    model rules do (``models/llama.py tp_rules``) so ZeRO never lands on a
    contracting dim.
    """
    classes = TpParser().parse(apply_fn, params, *inputs)
    shapes = {path_str(kp): getattr(leaf, "shape", ())
              for kp, leaf in jax.tree_util.tree_leaves_with_path(params)}
    z = ("zero", ) if with_zero_pin else ()
    rules = {}

    def spec_for(path, cls):
        nd = len(shapes[path])
        if cls == "embed":
            return P((tp_axis, ) + z, *([None] * (nd - 1)))
        if cls == "router":
            return P(*([None] * nd))  # gating logits: keep replicated
        if cls == "expert_column":   # stacked [E, in, out]
            return P("ep", None, (tp_axis, ) + z)
        if cls == "expert_row":      # stacked [E, in, out] (in=contracting)
            return P("ep", (tp_axis, ) + z, None)
        if cls == "column":
            if nd == 3:      # DenseGeneral [D, H, Dh]: shard heads
                return P(None, tp_axis, *z) if z else P(None, tp_axis, None)
            return P(*([None] * (nd - 1)), (tp_axis, ) + z)
        # row: contracting is the leading dim; pin zero on the output dim
        rest = z + (None, ) * max(nd - 1 - len(z), 0)
        return P(tp_axis, *rest)

    for cls in ("embed", "column", "row", "router", "expert_column",
                "expert_row"):
        for path in classes[cls]:
            parts = path.split("/")
            suffix = "/".join(parts[-2:]) if len(parts) >= 2 else path
            spec = spec_for(path, cls)
            prev = rules.get(suffix)
            if prev is not None and prev != spec:
                logger.warning("tp_parser: conflicting specs for %s (%s vs "
                               "%s) — keeping first", suffix, prev, spec)
                continue
            rules[suffix] = spec
    # biases of column-parallel layers follow the kernel's output shard
    # (zero pin stripped — biases are too small to zero-shard usefully)
    def _strip_zero(ax):
        names = tuple(a for a in (ax if isinstance(ax, tuple) else (ax, ))
                      if a not in (None, "zero"))
        return names if len(names) > 1 else (names[0] if names else None)

    for path, shape in shapes.items():
        if path.endswith("/bias"):
            suffix = "/".join(path.split("/")[-2:])
            kspec = rules.get(suffix[:-5] + "/kernel")
            if kspec and len(shape) + 1 == len(tuple(kspec)):
                rules[suffix[:-5] + "/bias"] = P(
                    *[_strip_zero(a) for a in tuple(kspec)[1:]])
    return rules
