"""Back-compat import path (reference ``deepspeed/module_inject/
replace_module.py:183``) — kernel-injection entry points live in the
package root modules (containers.py / diffusers_injection.py)."""

from . import replace_transformer_layer  # noqa: F401
from .diffusers_injection import generic_injection  # noqa: F401
