"""AutoTP — automatic tensor-parallel sharding of a parameter tree.

Reference: ``module_inject/auto_tp.py:273`` (``AutoTP.tp_parser``) walks the
torch module graph to find linears followed by an all-reduce point, then
slices weights with ``ReplaceWithTensorSlicing`` (``auto_tp.py:30``).

TPU-native redesign: no graph surgery and no manual slicing — we derive a
**rule table** (param-path suffix → ``PartitionSpec``) and hand it to GSPMD.
XLA then inserts the row-parallel all-reduces the reference codes by hand
(``LinearAllreduce``, ``module_inject/layers.py:78``).  Placement is one
``jax.device_put`` per leaf with a ``NamedSharding``; resharding an already
placed tree is the same call (XLA emits the collective-permute).

Rule derivation is by name heuristics over the flax param tree — the same
information the reference extracts from its per-arch policies
(``module_inject/containers/``) — with a shape-divisibility guard so
non-divisible tensors fall back to replication instead of erroring.
"""

import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..runtime.zero.partition import match_tp_rule, path_str
from ..utils.logging import logger

# column-parallel (shard output features, the LAST kernel dim): layers whose
# outputs stay sharded until a row-parallel layer reduces them
_COLUMN_PAT = re.compile(
    r"(q_proj|k_proj|v_proj|qkv|query|key|value|gate_proj|up_proj|c_fc|fc1"
    r"|wi_0|wi_1|wi|dense_h_to_4h|w1|w3|intermediate)$")
# row-parallel (shard input features, the FIRST kernel dim): the reduce point
_ROW_PAT = re.compile(
    r"(o_proj|out_proj|down_proj|c_proj|mlp_proj|fc2|wo|dense_4h_to_h|w2"
    r"|attention_output|output)$")
# vocab-sharded embeddings
_EMBED_PAT = re.compile(r"(embed_tokens|wte|word_embeddings|embedding)$")


class AutoTP:
    """Derive TP sharding rules from a parameter tree (reference
    ``AutoTP.tp_parser``, ``module_inject/auto_tp.py:273``)."""

    @staticmethod
    def derive_rules(params, tp_axis="tp"):
        rules = {}
        for kp, leaf in jax.tree_util.tree_leaves_with_path(params):
            path = path_str(kp)
            parts = path.split("/")
            if len(parts) < 2 or parts[-1] not in ("kernel", "embedding"):
                continue
            owner = parts[-2]
            ndim = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))
            if parts[-1] == "embedding" or _EMBED_PAT.search(owner):
                rules[f"{owner}/{parts[-1]}"] = P(tp_axis, None)
            elif _COLUMN_PAT.search(owner):
                # DenseGeneral kernels may be [D, H, Dh] (3D): shard the
                # first output dim (heads); plain Dense [D, F]: shard F.
                spec = ((None, tp_axis, None) if ndim == 3 else
                        (None, ) * (ndim - 1) + (tp_axis, ))
                rules[f"{owner}/kernel"] = P(*spec)
            elif _ROW_PAT.search(owner):
                # reduce dim is the leading input dim(s)
                spec = (tp_axis, ) + (None, ) * (ndim - 1)
                rules[f"{owner}/kernel"] = P(*spec)
        return rules

    # reference kept these as separate lists on the parser object
    @staticmethod
    def is_column_parallel(name):
        return bool(_COLUMN_PAT.search(name))

    @staticmethod
    def is_row_parallel(name):
        return bool(_ROW_PAT.search(name))


def _divisible(shape, spec, mesh):
    for dim, axis in zip(shape, tuple(spec) + (None, ) * len(shape)):
        if axis is None:
            continue
        axes = axis if isinstance(axis, tuple) else (axis, )
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        if dim % size != 0:
            return False
    return True


def _restrict_spec_to_mesh(spec, mesh):
    """Drop axes the target mesh doesn't have: the 'zero' pseudo-axis (a
    ZeRO-placement pin interpreted only by ZeroPartitionPlan) and any
    training-mesh axis absent at inference (e.g. mixtral's 'ep' on a
    tp-only mesh) — P('ep', None, ('tp','zero')) → P(None, None, 'tp')."""
    have = set(mesh.axis_names)
    out = []
    for ax in spec:
        names = tuple(a for a in (ax if isinstance(ax, tuple) else (ax, ))
                      if a is not None and a in have)
        out.append(names if len(names) > 1 else (names[0] if names else None))
    return P(*out)


def shard_params_for_tp(params, mesh, rules=None, tp_axis="tp"):
    """Place ``params`` on ``mesh`` with TP shardings from ``rules``
    (``ReplaceWithTensorSlicing`` analog — reference ``auto_tp.py:30`` — but
    a single device_put per leaf instead of manual narrow+copy)."""
    if rules is None:
        rules = AutoTP.derive_rules(params, tp_axis=tp_axis)

    def place(kp, leaf):
        spec = match_tp_rule(rules, path_str(kp))
        if spec is not None:
            spec = _restrict_spec_to_mesh(spec, mesh)
        if spec is None or not _divisible(leaf.shape, spec, mesh):
            if spec is not None:
                logger.warning(
                    "AutoTP: %s shape %s not divisible by %s — replicating",
                    path_str(kp), leaf.shape, spec)
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)
