from .auto_tp import AutoTP, shard_params_for_tp
from .layers import ColumnParallelLinear, RowParallelLinear, LinearAllreduce, LinearLayer
