from .auto_tp import AutoTP, shard_params_for_tp
from .containers import (InjectionPolicy, POLICIES, policy_for,
                         replace_transformer_layer,
                         revert_transformer_layer)
from .layers import ColumnParallelLinear, RowParallelLinear, LinearAllreduce, LinearLayer
from .tp_parser import TpParser, derive_tp_rules_from_dataflow
from .diffusers_injection import (fused_attention, generic_injection,
                                  make_interceptor)
