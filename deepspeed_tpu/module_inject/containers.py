"""Per-architecture injection policies (reference
``module_inject/containers/`` — bert…llama2, and
``replace_module.py:183 replace_transformer_layer``).

The reference's containers map a HuggingFace module tree onto fused CUDA
kernel modules, arch by arch.  The TPU equivalent replaces *modules* rather
than kernels: each policy names

* the in-repo TPU-optimized model class (Pallas flash-attention, fused XLA
  blocks) serving that architecture,
* the HF checkpoint ingestion that fills it
  (``inference/v2/model_implementations/hf_builders``),
* the TP sharding rules (dataflow parser or hand rules).

``replace_transformer_layer(orig_cls_or_name, checkpoint_dir, ...)`` is the
reference-shaped entry: given an HF arch name + local checkpoint, it returns
a ready (model, params) pair — the whole "kernel injection" in one step,
because on TPU the fused kernels live inside the model definition and XLA.
"""

from typing import Callable, NamedTuple, Optional

from ..utils.logging import logger


class InjectionPolicy(NamedTuple):
    model_type: str             # HF config.json model_type
    model_factory: Callable     # config dict → flax module
    supports_training: bool = True


def _llama_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _llama_config_from_hf)
    from ..models.llama import LlamaModel
    return LlamaModel(_llama_config_from_hf(hf_cfg, dtype))


def _mixtral_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _mixtral_config_from_hf)
    from ..models.mixtral import MixtralModel
    return MixtralModel(_mixtral_config_from_hf(hf_cfg, dtype))


def _falcon_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _falcon_config_from_hf)
    from ..models.falcon import FalconModel
    return FalconModel(_falcon_config_from_hf(hf_cfg, dtype))


def _opt_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _opt_config_from_hf)
    from ..models.opt import OPTModel
    return OPTModel(_opt_config_from_hf(hf_cfg, dtype))


def _gpt_neo_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _gpt_neo_config_from_hf)
    from ..models.gpt_neo import GPTNeoModel
    return GPTNeoModel(_gpt_neo_config_from_hf(hf_cfg, dtype))


def _bert_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _bert_config_from_hf)
    from ..models.bert import BertModel
    return BertModel(_bert_config_from_hf(hf_cfg, dtype))


def _gptj_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _gptj_config_from_hf)
    from ..models.gptj import GPTJModel
    return GPTJModel(_gptj_config_from_hf(hf_cfg, dtype))


def _gpt_neox_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _gpt_neox_config_from_hf)
    from ..models.gpt_neox import GPTNeoXModel
    return GPTNeoXModel(_gpt_neox_config_from_hf(hf_cfg, dtype))


def _bloom_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _bloom_config_from_hf)
    from ..models.bloom import BloomModel
    return BloomModel(_bloom_config_from_hf(hf_cfg, dtype))


def _phi_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _phi_config_from_hf)
    from ..models.phi import PhiModel
    return PhiModel(_phi_config_from_hf(hf_cfg, dtype))


def _qwen_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _qwen_config_from_hf)
    from ..models.llama import LlamaModel
    return LlamaModel(_qwen_config_from_hf(hf_cfg, dtype))


def _qwen2_moe_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _qwen2_moe_config_from_hf)
    from ..models.mixtral import MixtralModel
    return MixtralModel(_qwen2_moe_config_from_hf(hf_cfg, dtype))


def _gpt2_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _gpt2_config_from_hf)
    from ..models.gpt2 import GPT2Model
    return GPT2Model(_gpt2_config_from_hf(hf_cfg, dtype))


def _distilbert_factory(hf_cfg, dtype="bfloat16"):
    from ..inference.v2.model_implementations.hf_builders import (
        _distilbert_config_from_hf)
    from ..models.bert import BertModel
    return BertModel(_distilbert_config_from_hf(hf_cfg, dtype))


# arch aliases the reference keeps one container file per entry for
# (containers/llama.py, llama2, distil_llama, …): here one policy serves a
# family because the flax model is config-parametrized.
POLICIES = {
    "llama": InjectionPolicy("llama", _llama_factory),
    "llama2": InjectionPolicy("llama", _llama_factory),
    "mistral": InjectionPolicy("mistral", _llama_factory),
    "qwen": InjectionPolicy("qwen", _qwen_factory),
    "qwen2": InjectionPolicy("qwen2", _llama_factory),
    "phi3": InjectionPolicy("phi3", _llama_factory),
    "mixtral": InjectionPolicy("mixtral", _mixtral_factory),
    "qwen2_moe": InjectionPolicy("qwen2_moe", _qwen2_moe_factory),
    "bloom": InjectionPolicy("bloom", _bloom_factory),
    "gpt_neox": InjectionPolicy("gpt_neox", _gpt_neox_factory),
    "gpt_neo": InjectionPolicy("gpt_neo", _gpt_neo_factory),
    "gptj": InjectionPolicy("gptj", _gptj_factory),
    "bert": InjectionPolicy("bert", _bert_factory),
    "falcon": InjectionPolicy("falcon", _falcon_factory),
    "opt": InjectionPolicy("opt", _opt_factory),
    "phi": InjectionPolicy("phi", _phi_factory),
    "gpt2": InjectionPolicy("gpt2", _gpt2_factory),
    "distilbert": InjectionPolicy("distilbert", _distilbert_factory),
    # llama-architecture aliases (reference ships a dedicated internlm
    # container, module_inject/containers/internlm.py — same block layout)
    "internlm": InjectionPolicy("internlm", _llama_factory),
    "internlm2": InjectionPolicy("internlm2", _llama_factory),
}


def policy_for(arch_or_model) -> Optional[InjectionPolicy]:
    """Resolve a policy from an arch name, HF config, or torch/flax module
    class name (reference ``replace_module.py`` policy lookup)."""
    if isinstance(arch_or_model, str):
        key = arch_or_model.lower()
    elif isinstance(arch_or_model, dict):
        key = arch_or_model.get("model_type", "").lower()
    else:
        key = type(arch_or_model).__name__.lower()
        # longest-match first and underscore-insensitive: a Qwen2Moe class
        # name must hit "qwen2_moe", not "qwen2" (nor "qwen")
        for name in sorted(POLICIES, key=len, reverse=True):
            if name.replace("_", "") in key.replace("_", ""):
                key = name
                break
    return POLICIES.get(key)


def replace_transformer_layer(arch_or_model, checkpoint_dir=None,
                              dtype="bfloat16", config=None):
    """Reference-shaped injection entry (``replace_module.py:183``): swap an
    architecture for its TPU-optimized implementation, loading weights from
    a local HF checkpoint when given.  Returns ``(model, params)`` (params
    None when no checkpoint)."""
    policy = policy_for(arch_or_model if config is None else config)
    if policy is None:
        raise ValueError(
            f"no injection policy for {arch_or_model!r} "
            f"(have: {sorted(POLICIES)}); pass the model through unchanged "
            "or add a policy")
    if checkpoint_dir is not None:
        from ..inference.v2.checkpoint import HuggingFaceCheckpointEngine
        from ..inference.v2.model_implementations import build_model_and_params
        engine = HuggingFaceCheckpointEngine(checkpoint_dir)
        return build_model_and_params(engine, dtype=dtype)
    if config is None:
        raise ValueError("need either checkpoint_dir or an HF config dict")
    model = policy.model_factory(config, dtype=dtype)
    logger.info(f"injected TPU-optimized {policy.model_type} implementation")
    return model, None


def revert_transformer_layer(orig_model, replaced=None, config=None):
    """Reference ``module_inject/__init__`` ``revert_transformer_layer``:
    swap fused inference modules back to the original implementation.

    Here injection returns a NEW (model, params) pair and never mutates the
    user's module, so reverting is returning the original object — there is
    no fused-module state to unwind (XLA fusion is a compiler artifact of
    the replaced model's jit, not a module swap)."""
    logger.info("revert_transformer_layer: injection is non-mutating on "
                "TPU; returning the original model")
    return orig_model
