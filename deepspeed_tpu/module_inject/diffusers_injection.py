"""Diffusers/CLIP attention injection — TPU analog of the reference's
``generic_injection`` (``module_inject/replace_module.py:88``).

The reference swaps torch-diffusers ``CrossAttention`` /
``BasicTransformerBlock`` instances for fused CUDA modules
(``DeepSpeedDiffusersAttention``) and wraps the CLIP text encoder
(``DSClipEncoder``) for stable-diffusion inference.  Flax modules are
immutable, so the TPU mechanism is an **interceptor** instead of a module
swap: ``flax.linen.intercept_methods`` redirects matching modules'
``__call__`` to a fused path that runs q/k/v/out through the module's own
Dense submodules and the attention math through ``ops.attention_core``
(Pallas flash on TPU) — same weights, fused kernel, no tree surgery.

Matched out of the box (by class name + submodule layout):

* ``FlaxAttention`` / ``FlaxCrossAttention`` — flax-diffusers UNet/VAE
  attention (``query``/``key``/``value``/``proj_attn``);
* ``FlaxCLIPAttention`` — transformers' Flax CLIP text/vision encoder
  (``q_proj``/``k_proj``/``v_proj``/``out_proj``, causal for text).

Out-of-scope and deliberately NOT faked: the torch-diffusers pipeline
path (torch in this stack is CPU-only — a torch module swap would not
touch the TPU), and CUDA-graph wrapping (XLA jit covers whole-program
capture).  See PARITY.md.

Usage::

    with generic_injection():              # or fused_attention()
        out = flax_pipe(...)               # matching attentions run fused
"""

import contextlib

import numpy as np

import jax.numpy as jnp

from ..ops.attention import attention_core
from ..utils.logging import logger

# class name → submodule layout of the attention to fuse.  ``arg1`` names
# the meaning of the second POSITIONAL argument (diffusers passes the
# cross-attention ``context`` there; transformers passes the padding mask).
DEFAULT_POLICIES = {
    # "scale": attribute names to probe for the softmax scale, part of the
    # per-class policy (ADVICE r3) — a class whose scale lives under another
    # name must say so here rather than silently computing with D**-0.5
    "FlaxAttention": dict(q="query", k="key", v="value", out="proj_attn",
                          heads=("heads", ), returns_tuple=False,
                          arg1="context", scale=("scale", )),
    "FlaxCrossAttention": dict(q="query", k="key", v="value",
                               out="proj_attn", heads=("heads", ),
                               returns_tuple=False, arg1="context",
                               scale=("scale", )),
    "FlaxCLIPAttention": dict(q="q_proj", k="k_proj", v="v_proj",
                              out="out_proj",
                              heads=("num_heads", "heads"),
                              returns_tuple=True, arg1="attention_mask",
                              scale=("scale", )),
}

# any of these kwargs being non-None means cross-attention / kv-from-
# elsewhere — always the module's own implementation
_CROSS_KWARGS = ("context", "encoder_hidden_states", "key_value_states")


def _fused_call(mod, pol, hidden, counter):
    B, S, _ = hidden.shape
    heads = None
    for attr in pol["heads"]:
        heads = getattr(mod, attr, None)
        if heads is not None:
            break
    q = getattr(mod, pol["q"])(hidden)
    k = getattr(mod, pol["k"])(hidden)
    v = getattr(mod, pol["v"])(hidden)
    Dh = q.shape[-1] // heads
    q = q.reshape(B, S, heads, Dh)
    k = k.reshape(B, S, heads, Dh)
    v = v.reshape(B, S, heads, Dh)
    causal = bool(getattr(mod, "causal", False))
    scale = None
    for attr in pol.get("scale", ("scale", )):
        scale = getattr(mod, attr, None)
        if scale is not None:
            break
    out = attention_core(q, k, v, causal=causal, softmax_scale=scale)
    out = out.reshape(B, S, heads * Dh)
    out = getattr(mod, pol["out"])(out)
    if counter is not None:
        counter[0] += 1
    return (out, ) if pol["returns_tuple"] else out


def make_interceptor(policies=None, counter=None, assume_full_mask=False):
    """A flax method interceptor routing matching attention modules through
    the fused path.  Falls back to the original implementation when the
    call is cross-attention (``context``/``encoder_hidden_states`` present,
    positionally or by kwarg), asks for attention weights (flash never
    materializes them), or carries a padding mask that is not provably a
    no-op.

    ``assume_full_mask``: treat ANY provided padding mask as all-ones.
    Under ``jax.jit`` the mask is a tracer whose values can't be inspected,
    so the safe default falls back — callers who know their batches carry
    no padding set this to keep the fused path inside jit."""
    policies = dict(DEFAULT_POLICIES if policies is None else policies)

    def _mask_blocks_fusion(mask):
        """True → fall back.  A concrete all-ones padding mask is a no-op
        (the transformers default); anything else — real padding, a traced
        mask whose values we can't inspect, an additive bias — keeps the
        module's own implementation (unless assume_full_mask)."""
        if mask is None:
            return False
        if assume_full_mask:
            return False
        try:
            return not bool((np.asarray(mask) == 1).all())
        except Exception:  # traced / non-concrete
            return True

    def interceptor(next_fun, args, kwargs, context):
        pol = policies.get(type(context.module).__name__)
        if pol is None or context.method_name != "__call__":
            return next_fun(*args, **kwargs)
        if any(kwargs.get(kw) is not None for kw in _CROSS_KWARGS):
            return next_fun(*args, **kwargs)  # cross-attention
        arg1 = args[1] if len(args) > 1 else None
        if pol["arg1"] == "context":
            if arg1 is not None:
                return next_fun(*args, **kwargs)  # positional context
            mask = None
        else:
            mask = arg1 if arg1 is not None else kwargs.get("attention_mask")
        if kwargs.get("output_attentions") or _mask_blocks_fusion(mask):
            return next_fun(*args, **kwargs)
        # training-mode attention dropout lives in the module's own path —
        # the fused kernel has none, so non-deterministic calls with a
        # nonzero rate keep the original implementation
        rate = getattr(context.module, "dropout", 0.0)
        det = args[2] if len(args) > 2 else kwargs.get("deterministic", True)
        if isinstance(rate, (int, float)) and rate > 0 and not det:
            return next_fun(*args, **kwargs)
        hidden = args[0] if args else kwargs.get("hidden_states")
        if hidden is None:
            return next_fun(*args, **kwargs)
        try:
            return _fused_call(context.module, pol, hidden, counter)
        except Exception as e:  # unexpected layout → original path, loudly
            logger.warning(
                "fused attention injection failed for %s (%s: %s) — "
                "running the module's own implementation",
                type(context.module).__name__, type(e).__name__, e)
            return next_fun(*args, **kwargs)

    return interceptor


@contextlib.contextmanager
def fused_attention(policies=None, counter=None, assume_full_mask=False):
    """Context manager: flax applies inside run matching attentions fused.
    Set ``assume_full_mask=True`` to keep the fused path under ``jax.jit``
    when batches carry no padding (traced masks can't be inspected)."""
    import flax.linen as nn
    with nn.intercept_methods(
            make_interceptor(policies, counter, assume_full_mask)):
        yield


def generic_injection(module=None, dtype=None, enable_cuda_graph=None,
                      policies=None, assume_full_mask=False):
    """Reference-parity entry (``replace_module.py:88``).  Returns the
    :func:`fused_attention` context manager — flax pipelines are applied
    *inside* it (immutability forbids the reference's in-place swap).
    ``module``/``enable_cuda_graph`` are accepted for signature parity;
    whole-program capture is XLA jit's job on TPU."""
    if dtype is not None and jnp.dtype(dtype) not in (jnp.dtype(jnp.float16),
                                                      jnp.dtype(jnp.bfloat16),
                                                      jnp.dtype(jnp.float32)):
        raise ValueError(f"unsupported dtype {dtype}")
    return fused_attention(policies, assume_full_mask=assume_full_mask)
