"""Tensor-parallel linear layers — analogs of reference
``module_inject/layers.py`` (``LinearLayer`` :124, ``LinearAllreduce`` :78,
``LmHeadLinearAllreduce`` :95).

The reference implements row-parallel linears by computing a partial matmul
per rank then calling ``dist.inference_all_reduce``.  On TPU the same
structure is expressed declaratively: the kernel carries a sharding
constraint and XLA GSPMD inserts the all-reduce (over the ``tp`` mesh axis)
at the reduce point.  These modules exist so hand-written inference models
can opt into TP without AutoTP rule derivation.
"""

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from ..runtime.zero.partition import shard_spec  # noqa: F401  (re-export)


def _constrain(x, spec):
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except ValueError:
        return x  # no mesh context — single-device path


class ColumnParallelLinear(nn.Module):
    """Output-feature-sharded linear: y[..., f] with f split over ``tp``.
    Reference ``LinearLayer`` (module_inject/layers.py:124)."""
    features: int
    use_bias: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.with_partitioning(
                nn.initializers.lecun_normal(), (None, self.tp_axis)),
            (x.shape[-1], self.features), jnp.float32)
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param(
                "bias", nn.with_partitioning(nn.initializers.zeros,
                                             (self.tp_axis, )),
                (self.features, ), jnp.float32)
            y = y + bias.astype(self.dtype)
        return _constrain(y, P(*(None, ) * (x.ndim - 1), self.tp_axis))


class RowParallelLinear(nn.Module):
    """Input-feature-sharded linear; the contraction over the sharded dim is
    the all-reduce point (XLA inserts it).  Reference ``LinearAllreduce``
    (module_inject/layers.py:78)."""
    features: int
    use_bias: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.with_partitioning(
                nn.initializers.lecun_normal(), (self.tp_axis, None)),
            (x.shape[-1], self.features), jnp.float32)
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        y = _constrain(y, P(*(None, ) * y.ndim))  # replicated after reduce
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features, ), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


# reference-compatible names
LinearLayer = ColumnParallelLinear
LinearAllreduce = RowParallelLinear
