"""Falcon-family model (TPU-first flax implementation).

Covers the reference's Falcon support (FastGen impl
``inference/v2/model_implementations/falcon/``): the architecture differs
from Llama in load-bearing ways —

* **parallel block** (falcon-7b ``parallel_attn``): attention and MLP both
  read the SAME layernormed input and their outputs add into the residual
  together (one LN per block; the 40b "new decoder architecture" uses two
  parallel LNs ``ln_attn``/``ln_mlp``);
* LayerNorm (with bias), not RMSNorm;
* fused ``query_key_value`` projection with three layouts (interleaved
  per-head / multi-query / grouped) — handled at checkpoint ingest;
* MLP is a plain GELU 4× expansion (no gating).

Rotary is NeoX-style (same convention as :mod:`deepspeed_tpu.models.llama`);
alibi variants are not supported (rejected at ingest).
"""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from .llama import _rope_freqs, apply_rotary


@dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_hidden_layers: int = 32
    num_attention_heads: int = 71
    num_kv_heads: int = 1          # multi-query default (falcon-7b)
    ffn_hidden_size: int = None    # None → 4*hidden
    max_position_embeddings: int = 2048
    layer_norm_epsilon: float = 1e-5
    rope_theta: float = 10000.0
    new_decoder_architecture: bool = False  # 40b: parallel ln_attn/ln_mlp
    parallel_attn: bool = True
    bias: bool = False             # linear-layer biases (older variants)
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    remat: bool = True
    remat_policy: str = "nothing_saveable"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def num_key_value_heads(self):
        """Llama-family naming alias (the v2 engine sizes the paged KV cache
        through this)."""
        return self.num_kv_heads

    @property
    def ffn_size(self):
        return self.ffn_hidden_size or 4 * self.hidden_size


def falcon_tiny(**overrides):
    return FalconConfig(**{**dict(vocab_size=256, hidden_size=64,
                                  num_hidden_layers=2,
                                  num_attention_heads=4, num_kv_heads=1,
                                  max_position_embeddings=128),
                           **overrides})


class FalconBlock(nn.Module):
    config: FalconConfig

    @nn.compact
    def __call__(self, x, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_kv_heads,
                      cfg.head_dim)
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_epsilon,
                     dtype=dtype, param_dtype=jnp.float32)
        dense = partial(nn.DenseGeneral, use_bias=cfg.bias, dtype=dtype,
                        param_dtype=jnp.float32)

        if cfg.new_decoder_architecture:
            h_attn = ln(name="ln_attn")(x)
            h_mlp = ln(name="ln_mlp")(x)
        else:
            h_attn = h_mlp = ln(name="input_layernorm")(x)

        # ---- attention (NeoX rotary, GQA/MQA)
        q = dense(features=(H, Dh), name="q_proj")(h_attn)
        k = dense(features=(Hkv, Dh), name="k_proj")(h_attn)
        v = dense(features=(Hkv, Dh), name="v_proj")(h_attn)
        cos, sin = _rope_freqs(Dh, cfg.max_position_embeddings,
                               cfg.rope_theta)
        cos, sin = jnp.asarray(cos, jnp.float32), jnp.asarray(sin, jnp.float32)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)
        if Hkv != H:
            k = jnp.repeat(k, H // Hkv, axis=2)
            v = jnp.repeat(v, H // Hkv, axis=2)
        from ..ops.attention import attention_core
        attn = attention_core(q, k, v, causal=True)
        attn = dense(features=D, axis=-1,
                     name="dense")(attn.reshape(B, S, H * Dh))

        # ---- MLP (plain GELU 4x)
        mlp_in = h_mlp if cfg.parallel_attn else ln(name="post_attention_layernorm")(
            x + attn)
        h4 = nn.gelu(dense(features=cfg.ffn_size,
                           name="dense_h_to_4h")(mlp_in))
        mlp = dense(features=D, name="dense_4h_to_h")(h4)

        # sequential vs parallel differ only in mlp_in above; the residual
        # sum is the same either way
        return x + attn + mlp


class FalconModel(nn.Module):
    """Causal-LM.  ``__call__(input_ids, labels=None)`` → loss if labels
    given else logits."""
    config: FalconConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=dtype,
                         name="word_embeddings")
        x = embed(input_ids)
        block = FalconBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(FalconBlock, policy=policy, static_argnums=(2, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"h_{i}")(x, decode)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        if cfg.tie_word_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=jnp.float32, param_dtype=jnp.float32,
                              name="lm_head")(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)


def tp_rules(config: FalconConfig):
    """Column-parallel q/k/v and h_to_4h, row-parallel dense/4h_to_h,
    vocab-sharded embeddings (same scheme the dataflow parser derives)."""
    return {
        "q_proj/kernel": P(None, "tp", "zero"),
        "k_proj/kernel": P(None, "tp", "zero"),
        "v_proj/kernel": P(None, "tp", "zero"),
        "dense/kernel": P("tp", "zero"),
        "dense_h_to_4h/kernel": P(None, ("tp", "zero")),
        "dense_4h_to_h/kernel": P("tp", "zero"),
        "word_embeddings/embedding": P(("tp", "zero"), None),
        "lm_head/kernel": P(None, ("tp", "zero")),
    }
