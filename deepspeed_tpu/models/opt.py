"""OPT-family model (TPU-first flax implementation).

Covers the reference's OPT support (FastGen impl
``inference/v2/model_implementations/opt/``).  Architecturally distinct from
the Llama family:

* learned positional embeddings with the OPT quirk of a +2 offset
  (``embed_positions`` row i serves position i-2);
* LayerNorm (with bias) in pre-norm placement (``do_layer_norm_before``);
* plain ReLU 4× MLP; every linear carries a bias;
* no rotary — positions enter only through the embedding.
"""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

OPT_POSITION_OFFSET = 2


@dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    do_layer_norm_before: bool = True
    tie_word_embeddings: bool = True
    dtype: str = "bfloat16"
    remat: bool = True
    remat_policy: str = "nothing_saveable"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def num_key_value_heads(self):
        return self.num_attention_heads


def opt_tiny(**overrides):
    return OPTConfig(**{**dict(vocab_size=256, hidden_size=64, ffn_dim=128,
                               num_hidden_layers=2, num_attention_heads=4,
                               max_position_embeddings=128),
                        **overrides})


class OPTBlock(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, x, positions=None, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_eps, dtype=dtype,
                     param_dtype=jnp.float32)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=dtype,
                        param_dtype=jnp.float32)

        res = x
        h = ln(name="self_attn_layer_norm")(x) if cfg.do_layer_norm_before \
            else x
        q = dense(features=(H, Dh), name="q_proj")(h)
        k = dense(features=(H, Dh), name="k_proj")(h)
        v = dense(features=(H, Dh), name="v_proj")(h)
        from ..ops.attention import attention_core
        out = attention_core(q, k, v, causal=True)
        x = res + dense(features=D, axis=-1,
                        name="out_proj")(out.reshape(B, S, H * Dh))
        if not cfg.do_layer_norm_before:
            x = ln(name="self_attn_layer_norm")(x)

        res = x
        h = ln(name="final_layer_norm")(x) if cfg.do_layer_norm_before else x
        h = nn.relu(dense(features=cfg.ffn_dim, name="fc1")(h))
        x = res + dense(features=D, name="fc2")(h)
        if not cfg.do_layer_norm_before:
            x = ln(name="final_layer_norm")(x)
        return x


class OPTModel(nn.Module):
    """Causal-LM.  ``__call__(input_ids, labels=None)`` → loss if labels
    given else logits."""
    config: OPTConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S = input_ids.shape
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=dtype,
                         name="embed_tokens")
        pos_embed = nn.Embed(
            cfg.max_position_embeddings + OPT_POSITION_OFFSET,
            cfg.hidden_size, param_dtype=jnp.float32, dtype=dtype,
            name="embed_positions")
        x = embed(input_ids) + pos_embed(
            jnp.arange(S)[None, :] + OPT_POSITION_OFFSET)

        block = OPTBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(OPTBlock, policy=policy, static_argnums=(3, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"layers_{i}")(x, None, decode)

        if cfg.do_layer_norm_before:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                             param_dtype=jnp.float32,
                             name="final_layer_norm")(x)
        if cfg.tie_word_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=jnp.float32, param_dtype=jnp.float32,
                              name="lm_head")(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)


def tp_rules(config: OPTConfig):
    return {
        "q_proj/kernel": P(None, "tp", "zero"),
        "k_proj/kernel": P(None, "tp", "zero"),
        "v_proj/kernel": P(None, "tp", "zero"),
        "out_proj/kernel": P("tp", "zero"),
        "fc1/kernel": P(None, ("tp", "zero")),
        "fc2/kernel": P("tp", "zero"),
        "embed_tokens/embedding": P(("tp", "zero"), None),
        "lm_head/kernel": P(None, ("tp", "zero")),
    }
