"""Mixtral-family model (sparse-MoE Llama) — TPU-first flax implementation.

Covers the reference's Mixtral support (FastGen impl
``inference/v2/model_implementations/mixtral/`` and the MoE containers) as a
*training-capable* module:

* attention/norm/rope identical to :mod:`deepspeed_tpu.models.llama` (Mixtral
  is a Llama arch with the MLP replaced by a top-2 router over E experts);
* expert weights are STACKED arrays ``w1/w3: [E, D, I]``, ``w2: [E, I, D]``
  — one array per projection, so expert-parallel sharding is a single
  ``P("ep", ...)`` spec and the grouped matmul maps onto the MXU;
* the expert compute is ``jax.lax.ragged_dot`` over tokens sorted by expert
  (megablocks-style, no token dropping — exact Mixtral semantics), which XLA
  lowers to the TPU grouped-matmul path;
* training adds the standard load-balance aux loss
  (``router_aux_loss_coef``, reference ``sharded_moe.py`` aux-loss algebra).

HF weight layout (``MixtralForCausalLM``) maps 1:1 onto this tree — see
``inference/v2/checkpoint/huggingface_engine.py``.
"""

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from .llama import LlamaAttention, LlamaConfig, RMSNorm


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    router_aux_loss_coef: float = 0.02
    # qwen2-moe extensions: a dense "shared expert" runs for every token,
    # mixed in via a sigmoid gate; norm_topk_prob=False keeps raw top-k
    # routing probs (mixtral renormalizes)
    shared_expert_intermediate_size: int = 0  # 0 → no shared expert
    norm_topk_prob: bool = True


def mixtral_tiny(**overrides):
    return MixtralConfig(**{**dict(vocab_size=256, hidden_size=64,
                                   intermediate_size=128, num_hidden_layers=2,
                                   num_attention_heads=4, num_key_value_heads=2,
                                   max_position_embeddings=128,
                                   num_local_experts=4, num_experts_per_tok=2),
                            **overrides})


def moe_expert_ffn(x_sorted, group_sizes, w1, w2, w3):
    """Grouped SwiGLU over tokens sorted by expert.

    x_sorted: [Tk, D] (token copies ordered so expert e's tokens are
    contiguous); group_sizes: [E]; w1/w3: [E, D, I]; w2: [E, I, D].
    Returns [Tk, D].  ``ragged_dot`` is XLA's grouped matmul — each expert's
    contiguous token block hits the MXU with that expert's weights.
    """
    import os
    if os.environ.get("DS_TPU_MOE_GMM") == "1":
        # opt-in Pallas grouped GEMM (ops/pallas/grouped_matmul.py) — the
        # hand-schedulable alternative to XLA's ragged_dot for on-chip A/B
        try:
            from ..ops.pallas.grouped_matmul import gmm
            gs = group_sizes.astype(jnp.int32)
            gate = gmm(x_sorted, w1, gs)
            up = gmm(x_sorted, w3, gs)
            return gmm(nn.silu(gate) * up, w2, gs)
        except ValueError:
            pass   # dims not tile-divisible → XLA path below
    gate = jax.lax.ragged_dot(x_sorted, w1, group_sizes)
    up = jax.lax.ragged_dot(x_sorted, w3, group_sizes)
    return jax.lax.ragged_dot(nn.silu(gate) * up, w2, group_sizes)


def moe_apply(x, router_logits, w1, w2, w3, k, norm_topk=True):
    """Exact (no-drop) top-k MoE: route, sort token-copies by expert, grouped
    matmul, weighted scatter-add back.  x: [T, D] → [T, D].
    """
    T, D = x.shape
    E = w1.shape[0]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    topw, topi = jax.lax.top_k(probs, k)              # [T, k]
    if norm_topk:
        topw = topw / jnp.sum(topw, axis=-1, keepdims=True)

    flat_expert = topi.reshape(-1)                    # [T*k]
    order = jnp.argsort(flat_expert)                  # stable
    token_of = order // k                             # source token per copy
    group_sizes = jnp.bincount(flat_expert, length=E)

    x_sorted = x[token_of]                            # [T*k, D]
    y_sorted = moe_expert_ffn(x_sorted, group_sizes, w1, w2, w3)
    w_sorted = topw.reshape(-1)[order].astype(y_sorted.dtype)
    out = jnp.zeros((T, D), dtype=y_sorted.dtype)
    out = out.at[token_of].add(y_sorted * w_sorted[:, None])
    return out.astype(x.dtype)


def load_balance_aux_loss(router_logits, k):
    """Switch/GShard aux loss over a batch of router logits [T, E]."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    E = probs.shape[-1]
    _, topi = jax.lax.top_k(probs, k)
    counts = jnp.sum(jax.nn.one_hot(topi, E), axis=(0, 1))  # [E]
    frac_tokens = counts / jnp.maximum(jnp.sum(counts), 1.0)
    frac_probs = jnp.mean(probs, axis=0)
    return jnp.sum(frac_tokens * frac_probs) * E


class MixtralSparseMoeBlock(nn.Module):
    """Top-k router + stacked experts (HF ``block_sparse_moe`` analog)."""
    config: MixtralConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        E, I = cfg.num_local_experts, cfg.intermediate_size
        tokens = x.reshape(-1, D)

        gate = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="gate")
        router_logits = gate(tokens.astype(jnp.float32))  # [T, E]

        init = nn.initializers.lecun_normal()
        w1 = self.param("w1", init, (E, D, I), jnp.float32)
        w3 = self.param("w3", init, (E, D, I), jnp.float32)
        w2 = self.param("w2", init, (E, I, D), jnp.float32)
        out = moe_apply(tokens, router_logits,
                        w1.astype(dtype), w2.astype(dtype), w3.astype(dtype),
                        cfg.num_experts_per_tok,
                        norm_topk=cfg.norm_topk_prob)
        if cfg.shared_expert_intermediate_size:
            # qwen2-moe shared expert: dense SwiGLU on every token, mixed in
            # through a per-token sigmoid gate
            Is = cfg.shared_expert_intermediate_size
            dense = lambda f, name: nn.Dense(f, use_bias=False, dtype=dtype,
                                             param_dtype=jnp.float32,
                                             name=name)
            gate_s = dense(Is, "shared_gate_proj")(tokens)
            up_s = dense(Is, "shared_up_proj")(tokens)
            shared = dense(D, "shared_down_proj")(nn.silu(gate_s) * up_s)
            mix = nn.Dense(1, use_bias=False, dtype=jnp.float32,
                           param_dtype=jnp.float32,
                           name="shared_expert_gate")(
                               tokens.astype(jnp.float32))
            out = out + (jax.nn.sigmoid(mix) * shared.astype(
                jnp.float32)).astype(out.dtype)
        self.sow("intermediates", "router_logits", router_logits)
        return out.reshape(B, S, D)


class MixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        h = x + LlamaAttention(cfg, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, dtype, name="input_layernorm")(x),
            attention_mask, decode=decode)
        return h + MixtralSparseMoeBlock(cfg, name="moe")(
            RMSNorm(cfg.rms_norm_eps, dtype,
                    name="post_attention_layernorm")(h))


class MixtralModel(nn.Module):
    """Causal-LM.  ``__call__(input_ids, labels=None)`` → loss (+aux) if
    labels given else logits."""
    config: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=dtype,
                         name="embed_tokens")
        x = embed(input_ids)

        block = MixtralBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(MixtralBlock, policy=policy, static_argnums=(3, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"layers_{i}")(x, attention_mask, decode)

        x = RMSNorm(cfg.rms_norm_eps, dtype, name="norm")(x)
        if cfg.loss_chunk_vocab and labels is not None and not decode:
            # fused chunked head+loss (models/llama.py loss_chunk_vocab):
            # no [B, S, V] logits in either pass
            from .llama import _lm_loss_chunked
            if cfg.tie_word_embeddings:
                w = embed.variables["params"]["embedding"].T
            else:
                head = nn.Dense(cfg.vocab_size, use_bias=False,
                                dtype=jnp.float32, param_dtype=jnp.float32,
                                name="lm_head")
                head(x[:, :1].astype(jnp.float32))  # bind; dead code to XLA
                w = head.variables["params"]["kernel"]
            loss = _lm_loss_chunked(x.astype(jnp.float32), w, labels,
                                    attention_mask, cfg.loss_chunk_vocab,
                                    jnp.float32)
            return loss
        if cfg.tie_word_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=jnp.float32, param_dtype=jnp.float32,
                              name="lm_head")(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            loss = jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        else:
            loss = jnp.mean(loss)
        # load-balance aux loss from each layer's sown router logits is not
        # reachable inside @nn.compact without a variable pass; recompute is
        # avoided by sowing — the engine adds it when it applies the model
        # with mutable=["intermediates"].  Standalone callers get the plain
        # LM loss plus the coefficient-weighted aux via aux_loss_from_vars.
        return loss


def aux_loss_from_vars(variables, k, coef):
    """Sum the load-balance aux loss over all layers' sown router logits."""
    inter = variables.get("intermediates", {})
    total = 0.0
    n = 0
    for layer in inter.values():
        moe = layer.get("moe") if isinstance(layer, dict) else None
        if moe and "router_logits" in moe:
            for rl in moe["router_logits"]:
                total = total + load_balance_aux_loss(rl, k)
                n += 1
    return coef * total / max(n, 1)


def tp_rules(config: MixtralConfig):
    """Sharding rules: attention like Llama; experts sharded over "ep" on the
    expert axis (+ ZeRO pinned on a non-contracting dim)."""
    from .llama import tp_rules as llama_rules
    rules = dict(llama_rules(config))
    rules.pop("gate_proj/kernel", None)
    rules.pop("up_proj/kernel", None)
    rules.pop("down_proj/kernel", None)
    rules.update({
        "moe/gate/kernel": P(None, None),
        "moe/w1": P("ep", None, ("tp", "zero")),
        "moe/w3": P("ep", None, ("tp", "zero")),
        "moe/w2": P("ep", ("tp", "zero"), None),
    })
    return rules


def param_count(config: MixtralConfig):
    D, I, V, L, E = (config.hidden_size, config.intermediate_size,
                     config.vocab_size, config.num_hidden_layers,
                     config.num_local_experts)
    H, Hkv, Dh = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim)
    per_layer = (D * H * Dh + 2 * D * Hkv * Dh + H * Dh * D) \
        + E * 3 * D * I + D * E + 2 * D
    total = V * D + L * per_layer + D
    if not config.tie_word_embeddings:
        total += D * V
    return total
