"""GPT-NeoX / Pythia — reference ``module_inject/containers/gptneox.py``
(v1 kernel-injection family; not in the FastGen model list, so serving goes
through ``init_inference`` like the reference).

Layout notes (HF ``modeling_gpt_neox``):
* fused ``query_key_value`` projects head-interleaved ``[H, 3·Dh]`` (q
  first within each head) — kept as-is so ingest is a plain transpose;
* partial rotary (``rotary_pct`` of the head dim, NeoX rotate-half
  convention — the same one llama uses);
* ``use_parallel_residual=True`` (default): attention and MLP both read
  their own layernorm of x and add into the residual together;
* untied LM head (``embed_out``).
"""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from .llama import _rope_freqs, apply_rotary


@dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 64
    intermediate_size: int = 256
    num_hidden_layers: int = 2
    num_attention_heads: int = 8
    max_position_embeddings: int = 2048
    rotary_pct: float = 0.25
    rotary_emb_base: float = 10000.0
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    hidden_act: str = "gelu"
    dtype: str = "bfloat16"
    remat: bool = False
    remat_policy: str = "nothing_saveable"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def rotary_dim(self):
        # HF truncates: int(head_dim * rotary_pct)
        return int(self.head_dim * self.rotary_pct)


def gpt_neox_tiny(**overrides):
    return GPTNeoXConfig(**{**dict(vocab_size=256, hidden_size=64,
                                   intermediate_size=128,
                                   num_hidden_layers=2,
                                   num_attention_heads=4,
                                   max_position_embeddings=128,
                                   rotary_pct=0.5), **overrides})


def _partial_rotary(x, cos, sin, rd, positions=None):
    if rd >= x.shape[-1]:
        return apply_rotary(x, cos, sin, positions=positions)
    return jnp.concatenate(
        [apply_rotary(x[..., :rd], cos, sin, positions=positions),
         x[..., rd:]], axis=-1)


class GPTNeoXBlock(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, x, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        rd = cfg.rotary_dim
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_eps, dtype=dtype,
                     param_dtype=jnp.float32)
        dense = partial(nn.Dense, dtype=dtype, param_dtype=jnp.float32)
        cos, sin = _rope_freqs(rd, cfg.max_position_embeddings,
                               cfg.rotary_emb_base)
        cos = jnp.asarray(cos, jnp.float32)
        sin = jnp.asarray(sin, jnp.float32)

        h = ln(name="input_layernorm")(x)
        qkv = dense(3 * D, name="query_key_value")(h)
        qkv = qkv.reshape(B, S, H, 3, Dh)          # per-head [q; k; v]
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]

        if decode:
            from .cache import decode_attention, kv_cache_update

            def rotate_k(kk, start):
                pos = start + jnp.arange(kk.shape[1])[None, :]
                return _partial_rotary(kk, cos, sin, rd, positions=pos)

            k, v, start = kv_cache_update(self, k, v, rotate_fn=rotate_k)
            q = _partial_rotary(q, cos, sin, rd,
                                positions=start + jnp.arange(S)[None, :])
            attn = decode_attention(q, k, v, start)
        else:
            q = _partial_rotary(q, cos, sin, rd)
            k = _partial_rotary(k, cos, sin, rd)
            from ..ops.attention import attention_core
            attn = attention_core(q, k, v, causal=True)
        attn_out = dense(D, name="dense")(attn.reshape(B, S, D))

        # HF default hidden_act="gelu" is the EXACT erf gelu (ACT2FN);
        # the tanh approximation is a different function
        act = {"gelu": partial(nn.gelu, approximate=False),
               "gelu_new": nn.gelu, "gelu_fast": nn.gelu,
               "gelu_pytorch_tanh": nn.gelu, "relu": nn.relu}.get(
                   cfg.hidden_act)
        if act is None:
            raise ValueError(f"unsupported hidden_act {cfg.hidden_act!r}")

        def mlp(h):
            return dense(D, name="dense_4h_to_h")(
                act(dense(cfg.intermediate_size,
                          name="dense_h_to_4h")(h)))

        if cfg.use_parallel_residual:
            # x + attn(ln1(x)) + mlp(ln2(x))
            return x + attn_out + mlp(ln(name="post_attention_layernorm")(x))
        x = x + attn_out
        return x + mlp(ln(name="post_attention_layernorm")(x))


class GPTNeoXModel(nn.Module):
    """Causal-LM.  ``__call__(input_ids, labels=None)`` → loss if labels
    given else logits (untied ``embed_out`` head)."""
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                     param_dtype=jnp.float32, dtype=dtype,
                     name="embed_in")(input_ids)
        block = GPTNeoXBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(GPTNeoXBlock, policy=policy,
                             static_argnums=(2, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"layers_{i}")(x, decode)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                         param_dtype=jnp.float32,
                         name="final_layer_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32,
                          name="embed_out")(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)


def tp_rules(config: GPTNeoXConfig):
    return {
        "query_key_value/kernel": P(None, ("tp", "zero")),
        "dense/kernel": P(("tp", "zero"), None),
        "dense_h_to_4h/kernel": P(None, ("tp", "zero")),
        "dense_4h_to_h/kernel": P(("tp", "zero"), None),
        "embed_in/embedding": P(("tp", "zero"), None),
        "embed_out/kernel": P(None, ("tp", "zero")),
    }
