from . import bert, gpt2, llama
from .bert import BertConfig, BertModel
from .gpt2 import GPT2Config, GPT2Model
from .llama import LlamaConfig, LlamaModel
