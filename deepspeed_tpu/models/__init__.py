from . import bert, gpt2, llama
from .bert import BertConfig, BertModel
from .gpt2 import GPT2Config, GPT2Model
from .llama import LlamaConfig, LlamaModel


def from_hf_pretrained(path, dtype="bfloat16", **config_overrides):
    """HF checkpoint directory → ``(flax model, params)`` ready for
    ``deepspeed_tpu.initialize`` — the fine-tuning entry (reference flow:
    hand an HF model straight to ``deepspeed.initialize``, engine.py:143).

    Reuses the FastGen ingestion (17 architectures,
    ``inference/v2/model_implementations/hf_builders.py``); the inference
    builders default ``remat=False`` — pass training-time config overrides
    (``remat=True``, ``use_ulysses=...``) as kwargs.
    """
    import dataclasses
    import jax
    import numpy as np
    from ..inference.v2.checkpoint.huggingface_engine import (
        HuggingFaceCheckpointEngine)
    from ..inference.v2.model_implementations.hf_builders import (
        build_model_and_params)
    ckpt = HuggingFaceCheckpointEngine(path)
    model, params = build_model_and_params(ckpt, dtype=dtype)
    if config_overrides:
        model = type(model)(
            dataclasses.replace(model.config, **config_overrides))
        # structural overrides (vocab_size, hidden_size, …) would silently
        # mismatch the already-ingested params — nn.Embed clamps
        # out-of-range ids under jit rather than erroring — so re-derive
        # the shape tree and fail loudly on any drift
        ids = np.zeros((1, 8), np.int32)
        want = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                              ids)["params"]
        got_shapes = jax.tree_util.tree_map(lambda x: tuple(x.shape), params)
        want_shapes = jax.tree_util.tree_map(lambda x: tuple(x.shape), want)
        if got_shapes != want_shapes:
            raise ValueError(
                f"config_overrides {sorted(config_overrides)} change the "
                "parameter structure — they no longer match the ingested "
                "checkpoint (only non-structural fields like remat/"
                "remat_policy/use_ulysses/max_position_embeddings/rope_* "
                "can be overridden)")
    return model, params
