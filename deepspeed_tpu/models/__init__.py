from . import bert, gpt2, llama
from .bert import BertConfig, BertModel
from .gpt2 import GPT2Config, GPT2Model
from .llama import LlamaConfig, LlamaModel


def from_hf_pretrained(path, dtype="bfloat16", **config_overrides):
    """HF checkpoint directory → ``(flax model, params)`` ready for
    ``deepspeed_tpu.initialize`` — the fine-tuning entry (reference flow:
    hand an HF model straight to ``deepspeed.initialize``, engine.py:143).

    Reuses the FastGen ingestion (17 architectures,
    ``inference/v2/model_implementations/hf_builders.py``); the inference
    builders default ``remat=False`` — pass training-time config overrides
    (``remat=True``, ``use_ulysses=...``) as kwargs.
    """
    import dataclasses
    from ..inference.v2.checkpoint.huggingface_engine import (
        HuggingFaceCheckpointEngine)
    from ..inference.v2.model_implementations.hf_builders import (
        build_model_and_params)
    ckpt = HuggingFaceCheckpointEngine(path)
    model, params = build_model_and_params(ckpt, dtype=dtype)
    if config_overrides:
        model = type(model)(
            dataclasses.replace(model.config, **config_overrides))
    return model, params
