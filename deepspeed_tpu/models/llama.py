"""Llama-family model (TPU-first flax implementation).

Fills the role of the reference's model coverage for Llama/Llama-2 (inference
containers ``module_inject/containers/llama.py``, FastGen impl
``inference/v2/model_implementations/llama_v2``) — but as a *training-capable*
flax module designed for the MXU:

* all matmuls batched [B*S, D]×[D, ·], bf16 compute, fp32 RMSNorm accums;
* rotary embeddings precomputed once (static S) and fused by XLA;
* GQA (n_kv_heads ≤ n_heads) with head-dim layouts [B, S, H, Dh];
* optional Ulysses attention (sp axis) via ``deepspeed_tpu.sequence``;
* ``remat`` flag → ``jax.checkpoint`` per block (activation checkpointing,
  reference ``runtime/activation_checkpointing``);
* TP logical sharding rules exposed via ``tp_rules()`` — column-parallel
  qkv/gate/up, row-parallel o/down (AutoTP analog, reference
  ``module_inject/auto_tp.py:273``).

Returns loss when ``labels`` is given (DeepSpeed 'model returns loss'
convention used across the reference's tests).
"""

from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    sliding_window: int = 0       # 0 → full causal (Mistral sets 4096)
    attention_bias: bool = False  # Qwen2-style q/k/v biases
    # RoPE scaling (HF rope_scaling): "none" | "linear" | "llama3".
    # Scalar fields (not a dict) so the frozen config stays hashable as a
    # flax static attribute.
    rope_scaling_type: str = "none"
    rope_scaling_factor: float = 1.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    dtype: str = "bfloat16"
    # lm-head / final-logits matmul dtype.  fp32 (the HF default) runs the
    # [B*S, D]×[D, V] matmul at the MXU's fp32 rate — ~4× below bf16 peak;
    # with V=32k that single matmul can dominate a small model's step.
    # "bfloat16" computes logits on the fast path (CE upcasts to fp32 for
    # the logsumexp either way).
    head_dtype: str = "float32"
    # > 0 → fused chunked head+loss: the lm-head matmul and cross entropy
    # run per vocab-chunk under an online logsumexp (sequence/cross_entropy
    # .fused_linear_cross_entropy) so the [B, S, V] logits are never
    # materialized in either pass.  Frees ~V·S·B·(2+4) bytes of live HBM
    # (bf16 logits + fp32 softmax), which is what forces remat at larger
    # batch.  Value = chunk width; MXU-friendly divisors of V (multiples of
    # 128) avoid padding, e.g. 6400 for V=32000.
    loss_chunk_vocab: int = 0
    remat: bool = True
    remat_policy: str = "nothing_saveable"  # or "dots_saveable", "none"
    use_ulysses: bool = False
    sp_backend: str = "ulysses"  # "ulysses" (a2a reshard) | "ring" (ppermute)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def rope_scaling(self):
        """Scaling tuple for :func:`_rope_freqs`, or None when unscaled."""
        if self.rope_scaling_type == "none":
            return None
        return (self.rope_scaling_type, self.rope_scaling_factor,
                self.rope_low_freq_factor, self.rope_high_freq_factor,
                self.rope_original_max_position)


def llama_7b(**overrides):
    return LlamaConfig(**{**dict(vocab_size=32000, hidden_size=4096,
                                 intermediate_size=11008, num_hidden_layers=32,
                                 num_attention_heads=32, num_key_value_heads=32),
                          **overrides})


def llama_13b(**overrides):
    return LlamaConfig(**{**dict(vocab_size=32000, hidden_size=5120,
                                 intermediate_size=13824, num_hidden_layers=40,
                                 num_attention_heads=40, num_key_value_heads=40),
                          **overrides})


def llama_tiny(**overrides):
    """Test-scale config."""
    return LlamaConfig(**{**dict(vocab_size=256, hidden_size=64,
                                 intermediate_size=128, num_hidden_layers=2,
                                 num_attention_heads=4, num_key_value_heads=2,
                                 max_position_embeddings=128),
                          **overrides})


def mistral_7b(**overrides):
    """Mistral-7B-v0.1: llama architecture + GQA + 4096 sliding window."""
    return LlamaConfig(**{**dict(vocab_size=32000, hidden_size=4096,
                                 intermediate_size=14336,
                                 num_hidden_layers=32,
                                 num_attention_heads=32,
                                 num_key_value_heads=8,
                                 sliding_window=4096, rope_theta=10000.0,
                                 max_position_embeddings=32768),
                          **overrides})


def qwen2_7b(**overrides):
    """Qwen2-7B: llama architecture + GQA + q/k/v biases."""
    return LlamaConfig(**{**dict(vocab_size=152064, hidden_size=3584,
                                 intermediate_size=18944,
                                 num_hidden_layers=28,
                                 num_attention_heads=28,
                                 num_key_value_heads=4,
                                 attention_bias=True, rope_theta=1e6,
                                 max_position_embeddings=131072),
                          **overrides})


def _rope_freqs(head_dim, max_len, theta, scaling=None):
    """cos/sin tables; ``scaling`` is ``LlamaConfig.rope_scaling`` —
    ``(type, factor, low_freq_factor, high_freq_factor, original_max)``.

    "linear" divides all frequencies by ``factor``; "llama3" is the HF
    piecewise rule (frequencies below the low-freq wavelength are scaled by
    ``factor``, above high-freq kept, smooth interpolation between)."""
    inv = 1.0 / (theta**(np.arange(0, head_dim, 2) / head_dim))
    if scaling is not None:
        stype, factor, low_f, high_f, orig_max = scaling
        if stype == "linear":
            inv = inv / factor
        elif stype == "llama3":
            wavelen = 2 * np.pi / inv
            low_wavelen = orig_max / low_f
            high_wavelen = orig_max / high_f
            smooth = (orig_max / wavelen - low_f) / (high_f - low_f)
            smoothed = ((1 - smooth) / factor + smooth) * inv
            inv = np.where(wavelen > low_wavelen, inv / factor,
                           np.where(wavelen < high_wavelen, inv, smoothed))
        else:
            raise ValueError(f"unsupported rope scaling type {stype!r}")
    t = np.arange(max_len)
    freqs = np.outer(t, inv)  # [S, Dh/2]
    return np.cos(freqs), np.sin(freqs)


def apply_rotary(x, cos, sin, positions=None):
    """x: [B, S, H, Dh]; cos/sin: [Smax, Dh/2]."""
    S = x.shape[1]
    if positions is None:
        c = cos[:S][None, :, None, :]
        s = sin[:S][None, :, None, :]
    else:
        c = cos[positions][:, :, None, :]
        s = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def _lm_loss(logits, labels, attention_mask=None):
    """Shifted next-token cross-entropy (shared by the monolithic forward and
    the Infinity streaming head)."""
    from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
    loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
    if attention_mask is not None:
        m = attention_mask[:, 1:].astype(jnp.float32)
        return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(loss)


def _lm_loss_chunked(x, w, labels, attention_mask, chunk, head_dtype):
    """Shifted CE via the fused chunked head+loss (no [B, S, V] logits).
    ``x``: [B, S, D] final hidden states, ``w``: [D, V] head kernel."""
    from ..sequence.cross_entropy import fused_linear_cross_entropy
    b, s, d = x.shape
    n = b * (s - 1)
    loss = fused_linear_cross_entropy(
        x[:, :-1].reshape(n, d), w, labels[:, 1:].reshape(n),
        chunk, logit_dtype=head_dtype)
    if attention_mask is not None:
        m = attention_mask[:, 1:].astype(jnp.float32).reshape(n)
        return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(loss)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (x.shape[-1], ))
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.eps)
        return (y * w).astype(self.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=dtype,
                        param_dtype=jnp.float32)
        qkv = partial(nn.DenseGeneral, use_bias=cfg.attention_bias,
                      dtype=dtype, param_dtype=jnp.float32)
        q = qkv(features=(H, Dh), name="q_proj")(x)
        k = qkv(features=(Hkv, Dh), name="k_proj")(x)
        v = qkv(features=(Hkv, Dh), name="v_proj")(x)

        cos, sin = _rope_freqs(Dh, cfg.max_position_embeddings, cfg.rope_theta,
                               cfg.rope_scaling)
        cos, sin = jnp.asarray(cos, jnp.float32), jnp.asarray(sin, jnp.float32)

        if decode:
            # KV-cached path (inference): rotary offset by the cache cursor,
            # keys stored rotated (models/cache.py).
            from .cache import decode_attention, kv_cache_update

            def rotate_k(kk, start):
                pos = start + jnp.arange(kk.shape[1])[None, :]
                return apply_rotary(kk, cos, sin, positions=pos)

            k, v, start = kv_cache_update(self, k, v, rotate_fn=rotate_k)
            q = apply_rotary(
                q, cos, sin,
                positions=start + jnp.arange(S)[None, :])
            # GQA handled inside decode_attention (no cache-wide repeat)
            out = decode_attention(q, k, v, start,
                                   window=cfg.sliding_window)
        else:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)

            if cfg.use_ulysses and cfg.sp_backend == "ring":
                if cfg.sliding_window:
                    raise NotImplementedError(
                        "sliding_window is not supported by the ring SP "
                        "backend; use sp_backend='ulysses'")
                # ring handles Hkv < H internally — K/V circulate the ICI
                # ring at native KV width (repeating first would multiply
                # every ppermute hop's bytes by H/Hkv)
                from ..sequence.ring_attention import RingAttention
                out = RingAttention()(q, k, v, causal=True)
            elif cfg.use_ulysses:
                # kv at NATIVE width: DistributedAttention aligns GQA
                # inside its reshard (a2a + local group-repeat, or routed
                # a2a) — repeating to H first would multiply the kv a2a's
                # wire bytes by H/Hkv
                from ..sequence.layer import DistributedAttention
                out = DistributedAttention()(q, k, v, causal=True,
                                             window=cfg.sliding_window)
            else:
                # GQA: repeat kv heads up to H for the local core
                if Hkv != H:
                    rep = H // Hkv
                    k = jnp.repeat(k, rep, axis=2)
                    v = jnp.repeat(v, rep, axis=2)
                from ..ops.attention import attention_core
                out = attention_core(q, k, v, causal=True,
                                     window=cfg.sliding_window)

        out = out.reshape(B, S, H * Dh)
        return dense(features=D, axis=-1, name="o_proj")(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        dense = partial(nn.Dense, use_bias=False, dtype=dtype,
                        param_dtype=jnp.float32)
        gate = dense(cfg.intermediate_size, name="gate_proj")(x)
        up = dense(cfg.intermediate_size, name="up_proj")(x)
        return dense(cfg.hidden_size, name="down_proj")(nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        h = x + LlamaAttention(cfg, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, dtype, name="input_layernorm")(x),
            attention_mask, decode=decode)
        return h + LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, dtype, name="post_attention_layernorm")(h))


class LlamaModel(nn.Module):
    """Causal-LM.  ``__call__(input_ids, labels=None)`` → loss (scalar) if
    labels given else logits."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=dtype,
                         name="embed_tokens")
        x = embed(input_ids)

        block = LlamaBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(LlamaBlock, policy=policy, static_argnums=(3, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"layers_{i}")(x, attention_mask, decode)

        x = RMSNorm(cfg.rms_norm_eps, dtype, name="norm")(x)
        hd = jnp.dtype(cfg.head_dtype)
        if cfg.loss_chunk_vocab and labels is not None and not decode:
            # fused chunked head+loss: pull the head kernel and skip the
            # monolithic [B, S, V] logits entirely
            if cfg.tie_word_embeddings:
                w = embed.variables["params"]["embedding"].T
            else:
                head = nn.Dense(cfg.vocab_size, use_bias=False,
                                dtype=hd, param_dtype=jnp.float32,
                                name="lm_head")
                # one-row call creates/binds lm_head with the standard
                # {kernel} layout (checkpoint/HF-ingest compatible); the
                # unused output is dead code to XLA
                head(x[:, :1].astype(hd))
                w = head.variables["params"]["kernel"]
            return _lm_loss_chunked(x, w, labels, attention_mask,
                                    cfg.loss_chunk_vocab, hd)
        if cfg.tie_word_embeddings:
            logits = embed.attend(x.astype(hd))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=hd, param_dtype=jnp.float32,
                              name="lm_head")(x.astype(hd))
        if labels is None:
            return logits
        return _lm_loss(logits, labels, attention_mask)

    @nn.nowrap
    def streaming_parts(self):
        """ZeRO-Infinity param-streaming protocol (``runtime/zero/infinity``):
        expose the model as embed → L homogeneous blocks → head so the
        executor can stream one block's params HBM-resident at a time.
        Reference role: ``deepspeed/runtime/zero/partitioned_param_coordinator
        .py:276`` fetch/release over submodules — here the split is explicit
        because the executor drives per-block jitted calls.
        ``nn.nowrap``: the helper modules must be constructed OUTSIDE this
        module's scope machinery."""
        return llama_streaming_parts(self.config)


def llama_streaming_parts(cfg):
    from ..runtime.zero.infinity import StreamingSpec
    dtype = jnp.dtype(cfg.dtype)
    embed_mod = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=dtype)
    block_mod = LlamaBlock(cfg)
    norm_mod = RMSNorm(cfg.rms_norm_eps, dtype)
    hd = jnp.dtype(cfg.head_dtype)
    head_mod = (None if cfg.tie_word_embeddings else
                nn.Dense(cfg.vocab_size, use_bias=False, dtype=hd,
                         param_dtype=jnp.float32))
    block_keys = tuple(f"layers_{i}" for i in range(cfg.num_hidden_layers))
    resident_keys = ("embed_tokens", "norm") + \
        (() if cfg.tie_word_embeddings else ("lm_head", ))

    def embed_apply(res, input_ids, labels=None, attention_mask=None):
        return embed_mod.apply({"params": res["embed_tokens"]}, input_ids)

    def block_apply(w, x):
        # attention_mask intentionally not threaded: the monolithic
        # LlamaAttention also ignores it inside attention (causal-only
        # kernels); padding is handled at the loss (same _lm_loss in
        # head_apply), so streamed and monolithic trajectories agree
        return block_mod.apply({"params": w}, x, None, False)

    def head_apply(res, x, input_ids, labels=None, attention_mask=None):
        x = norm_mod.apply({"params": res["norm"]}, x)
        if cfg.tie_word_embeddings:
            logits = embed_mod.apply({"params": res["embed_tokens"]},
                                     x.astype(hd),
                                     method=embed_mod.attend)
        else:
            logits = head_mod.apply({"params": res["lm_head"]},
                                    x.astype(hd))
        if labels is None:
            return logits
        return _lm_loss(logits, labels, attention_mask)

    def init_block(rng, key, x):
        return block_mod.init(rng, x)["params"]

    def init_resident(rng, input_ids, labels=None, attention_mask=None):
        r_embed, r_norm, r_head = jax.random.split(rng, 3)
        x = jnp.zeros(
            (*np.asarray(input_ids).shape, cfg.hidden_size), dtype)
        res = {"embed_tokens": embed_mod.init(r_embed, input_ids)["params"],
               "norm": norm_mod.init(r_norm, x)["params"]}
        if not cfg.tie_word_embeddings:
            res["lm_head"] = head_mod.init(
                r_head, x.astype(hd))["params"]
        return res

    return StreamingSpec(block_keys=block_keys,
                         resident_keys=resident_keys,
                         embed_apply=embed_apply, block_apply=block_apply,
                         head_apply=head_apply, init_block=init_block,
                         init_resident=init_resident)


def tp_rules(config: LlamaConfig):
    """AutoTP-style sharding rules: param-path suffix → PartitionSpec.
    Column-parallel q/k/v/gate/up (+ embed vocab dim), row-parallel o/down.

    The ``"zero"`` pseudo-axis pins where the ZeRO-3 shard lands (expanded by
    ``ZeroPartitionPlan`` per stage).  Placement is chosen so ZeRO never
    shards a contracting/hidden dim: GSPMD would otherwise propagate
    hidden-dim sharding into the activations and full-rematerialize them back
    to (dp, sp) batch/seq sharding at every norm boundary (the round-1
    "involuntary full rematerialization" warnings).  q/k/v take it on the
    head dim, o/gate/up/down on their output dim, embed/lm_head on vocab.
    """
    tp = "tp"
    return {
        "q_proj/kernel": P(None, tp, "zero"),
        "k_proj/kernel": P(None, tp, "zero"),
        "v_proj/kernel": P(None, tp, "zero"),
        "o_proj/kernel": P(tp, "zero"),
        "gate_proj/kernel": P(None, (tp, "zero")),
        "up_proj/kernel": P(None, (tp, "zero")),
        "down_proj/kernel": P(tp, "zero"),
        "embed_tokens/embedding": P((tp, "zero"), None),
        "lm_head/kernel": P(None, (tp, "zero")),
    }


def param_count(config: LlamaConfig):
    D, I, V, L = (config.hidden_size, config.intermediate_size,
                  config.vocab_size, config.num_hidden_layers)
    H, Hkv, Dh = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim)
    per_layer = (D * H * Dh + 2 * D * Hkv * Dh + H * Dh * D) + 3 * D * I + 2 * D
    total = V * D + L * per_layer + D
    if not config.tie_word_embeddings:
        total += D * V
    return total
