"""Phi-2 family model (TPU-first flax implementation).

Covers the reference's phi support (FastGen impl
``inference/v2/model_implementations/phi/``).  Distinctives vs Llama:

* **parallel block**: attention and the GELU MLP both read the same
  layernormed input; ``x + attn + mlp`` closes the residual;
* **partial rotary**: only the first ``partial_rotary_factor·head_dim``
  channels rotate, the rest pass through;
* LayerNorm with bias; every linear has a bias (including ``lm_head``).
"""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from .llama import _rope_freqs, apply_rotary


def apply_partial_rotary(x, cos, sin, rotary_dim, positions=None):
    """Rotate the first ``rotary_dim`` channels of [.., Dh]; pass the rest."""
    if rotary_dim == x.shape[-1]:
        return apply_rotary(x, cos, sin, positions=positions)
    x_rot, x_pass = x[..., :rotary_dim], x[..., rotary_dim:]
    return jnp.concatenate(
        [apply_rotary(x_rot, cos, sin, positions=positions), x_pass], axis=-1)


@dataclass(frozen=True)
class PhiConfig:
    vocab_size: int = 51200
    hidden_size: int = 2560
    intermediate_size: int = 10240
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    partial_rotary_factor: float = 0.4
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    remat: bool = True
    remat_policy: str = "nothing_saveable"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def rotary_dim(self):
        # HF floors to an even channel count
        return int(self.partial_rotary_factor * self.head_dim) // 2 * 2


def phi_tiny(**overrides):
    return PhiConfig(**{**dict(vocab_size=256, hidden_size=64,
                               intermediate_size=128, num_hidden_layers=2,
                               num_attention_heads=4, num_key_value_heads=4,
                               max_position_embeddings=128,
                               partial_rotary_factor=0.5),
                        **overrides})


class PhiBlock(nn.Module):
    config: PhiConfig

    @nn.compact
    def __call__(self, x, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=dtype,
                        param_dtype=jnp.float32)

        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                         param_dtype=jnp.float32, name="input_layernorm")(x)
        q = dense(features=(H, Dh), name="q_proj")(h)
        k = dense(features=(Hkv, Dh), name="k_proj")(h)
        v = dense(features=(Hkv, Dh), name="v_proj")(h)
        rd = cfg.rotary_dim
        cos, sin = _rope_freqs(rd, cfg.max_position_embeddings,
                               cfg.rope_theta)
        cos, sin = jnp.asarray(cos, jnp.float32), jnp.asarray(sin, jnp.float32)
        q = apply_partial_rotary(q, cos, sin, rd)
        k = apply_partial_rotary(k, cos, sin, rd)
        if Hkv != H:
            k = jnp.repeat(k, H // Hkv, axis=2)
            v = jnp.repeat(v, H // Hkv, axis=2)
        from ..ops.attention import attention_core
        attn = attention_core(q, k, v, causal=True)
        attn = dense(features=D, axis=-1,
                     name="dense")(attn.reshape(B, S, H * Dh))

        mlp = dense(features=D, name="fc2")(
            nn.gelu(dense(features=cfg.intermediate_size, name="fc1")(h)))
        return x + attn + mlp  # parallel residual


class PhiModel(nn.Module):
    """Causal-LM.  ``__call__(input_ids, labels=None)`` → loss if labels
    given else logits."""
    config: PhiConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=dtype,
                         name="embed_tokens")
        x = embed(input_ids)
        block = PhiBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(PhiBlock, policy=policy, static_argnums=(2, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"layers_{i}")(x, decode)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                         param_dtype=jnp.float32, name="final_layernorm")(x)
        if cfg.tie_word_embeddings:
            # HF ties only the weight; the lm_head bias stays a live param
            bias = self.param("lm_head_bias", nn.initializers.zeros,
                              (cfg.vocab_size,), jnp.float32)
            logits = embed.attend(x.astype(jnp.float32)) + bias
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=True,
                              dtype=jnp.float32, param_dtype=jnp.float32,
                              name="lm_head")(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)


def tp_rules(config: PhiConfig):
    return {
        "q_proj/kernel": P(None, "tp", "zero"),
        "k_proj/kernel": P(None, "tp", "zero"),
        "v_proj/kernel": P(None, "tp", "zero"),
        "dense/kernel": P("tp", "zero"),
        "fc1/kernel": P(None, ("tp", "zero")),
        "fc2/kernel": P("tp", "zero"),
        "embed_tokens/embedding": P(("tp", "zero"), None),
        "lm_head/kernel": P(None, ("tp", "zero")),
    }
