"""GPT-2 family (TPU-first flax) — covers BASELINE configs 2/5 (GPT-2 350M,
GPT-3-13B-style scaling).  Learned positions, pre-LN blocks, GELU MLP, tied
LM head (GPT-2 convention).  Same 'returns loss with labels' contract as
``models/llama.py``."""

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dtype: str = "bfloat16"
    # > 0 → fused chunked head+loss (see models/llama.py loss_chunk_vocab):
    # the tied-head logits [B, S, V] never materialize; with V=50257 the
    # fp32 logits+softmax are the largest activations in the model
    loss_chunk_vocab: int = 0
    remat: bool = True
    use_ulysses: bool = False

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def gpt2_350m(**overrides):
    return GPT2Config(**{**dict(hidden_size=1024, num_hidden_layers=24,
                                num_attention_heads=16), **overrides})


def gpt2_tiny(**overrides):
    return GPT2Config(**{**dict(vocab_size=256, hidden_size=64,
                                num_hidden_layers=2, num_attention_heads=4,
                                max_position_embeddings=128), **overrides})


def gpt3_13b(**overrides):
    return GPT2Config(**{**dict(vocab_size=50257, hidden_size=5120,
                                num_hidden_layers=40, num_attention_heads=40,
                                max_position_embeddings=2048), **overrides})


class GPT2Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                     param_dtype=jnp.float32)
        dense = partial(nn.DenseGeneral, dtype=dtype, param_dtype=jnp.float32)

        h = ln(name="ln_1")(x)
        q = dense(features=(H, Dh), name="q_proj")(h)
        k = dense(features=(H, Dh), name="k_proj")(h)
        v = dense(features=(H, Dh), name="v_proj")(h)
        if decode:
            from .cache import decode_attention, kv_cache_update
            k, v, start = kv_cache_update(self, k, v)
            attn_out = decode_attention(q, k, v, start)
        elif cfg.use_ulysses:
            from ..sequence.layer import DistributedAttention
            attn_out = DistributedAttention()(q, k, v, causal=True)
        else:
            from ..ops.attention import attention_core
            attn_out = attention_core(q, k, v, causal=True)
        attn_out = dense(features=D, axis=(-2, -1), name="c_proj")(attn_out)
        x = x + attn_out

        h = ln(name="ln_2")(x)
        h = dense(features=4 * D, name="c_fc")(h)
        h = nn.gelu(h)
        h = dense(features=D, name="mlp_proj")(h)
        return x + h


class GPT2Model(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False, positions=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S = input_ids.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=dtype,
                       param_dtype=jnp.float32, name="wte")
        wpe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       dtype=dtype, param_dtype=jnp.float32, name="wpe")
        if positions is None:
            positions = jnp.arange(S)[None, :]
        x = wte(input_ids) + wpe(positions)

        block = GPT2Block
        if cfg.remat and not decode:
            block = nn.remat(GPT2Block,
                             policy=jax.checkpoint_policies.nothing_saveable,
                             static_argnums=(2, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"h_{i}")(x, decode)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        if cfg.loss_chunk_vocab and labels is not None and not decode:
            from .llama import _lm_loss_chunked
            w = wte.variables["params"]["embedding"].T  # tied head [D, V]
            return _lm_loss_chunked(x.astype(jnp.float32), w, labels,
                                    attention_mask, cfg.loss_chunk_vocab,
                                    jnp.float32)
        logits = wte.attend(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)

    @nn.nowrap
    def streaming_parts(self):
        """ZeRO-Infinity streaming protocol (see ``models/llama.py`` — same
        shape: embed → L homogeneous blocks → head; tied wte head)."""
        return gpt2_streaming_parts(self.config)


def gpt2_streaming_parts(cfg):
    from ..runtime.zero.infinity import StreamingSpec
    from .llama import _lm_loss
    dtype = jnp.dtype(cfg.dtype)
    wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=dtype,
                   param_dtype=jnp.float32)
    wpe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                   dtype=dtype, param_dtype=jnp.float32)
    block_mod = GPT2Block(cfg)
    lnf = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                       param_dtype=jnp.float32)
    block_keys = tuple(f"h_{i}" for i in range(cfg.num_hidden_layers))
    resident_keys = ("wte", "wpe", "ln_f")

    def embed_apply(res, input_ids, labels=None, attention_mask=None):
        S = input_ids.shape[1]
        pos = jnp.arange(S)[None, :]
        return (wte.apply({"params": res["wte"]}, input_ids) +
                wpe.apply({"params": res["wpe"]}, pos))

    def block_apply(w, x):
        return block_mod.apply({"params": w}, x, False)

    def head_apply(res, x, input_ids, labels=None, attention_mask=None):
        x = lnf.apply({"params": res["ln_f"]}, x)
        logits = wte.apply({"params": res["wte"]}, x.astype(jnp.float32),
                           method=wte.attend)
        if labels is None:
            return logits
        return _lm_loss(logits, labels, attention_mask)

    def init_block(rng, key, x):
        return block_mod.init(rng, x, False)["params"]

    def init_resident(rng, input_ids, labels=None, attention_mask=None):
        r_wte, r_wpe, r_ln = jax.random.split(rng, 3)
        S = np.asarray(input_ids).shape[1]
        x = jnp.zeros((*np.asarray(input_ids).shape, cfg.hidden_size), dtype)
        return {"wte": wte.init(r_wte, input_ids)["params"],
                "wpe": wpe.init(r_wpe, jnp.arange(S)[None, :])["params"],
                "ln_f": lnf.init(r_ln, x)["params"]}

    return StreamingSpec(block_keys=block_keys, resident_keys=resident_keys,
                         embed_apply=embed_apply, block_apply=block_apply,
                         head_apply=head_apply, init_block=init_block,
                         init_resident=init_resident)


def tp_rules(config: GPT2Config):
    tp = "tp"
    return {
        "q_proj/kernel": P(None, tp, None),
        "k_proj/kernel": P(None, tp, None),
        "v_proj/kernel": P(None, tp, None),
        "c_proj/kernel": P(tp, None, None),
        "c_fc/kernel": P(None, tp),
        "mlp_proj/kernel": P(tp, None),
        "wte/embedding": P(tp, None),
    }
