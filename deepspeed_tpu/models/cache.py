"""KV-cache machinery for autoregressive decode.

TPU-native answer to the reference's inference KV handling (v1 kernels
``csrc/transformer/inference/csrc/transform.cu`` copy KV into a contiguous
cache; FastGen's blocked KV in ``inference/v2/ragged/kv_cache.py``).  Here the
cache is a flax ``"cache"`` variable collection with **static shapes** so the
whole decode loop jits once:

* ``cached_key/cached_value`` — [B, max_len, Hkv, Dh] ring-less buffers;
* ``cache_index``             — scalar int32 write cursor;
* prefill writes S tokens at index 0, each decode step appends 1 token via
  ``lax.dynamic_update_slice`` (no dynamic shapes → no recompilation).

The cache is created by ``model.init(..., decode=True)`` on a [B, max_len]
dummy — the init pass sizes the buffers; subsequent ``apply(...,
mutable=["cache"])`` calls stream tokens through it.
"""

import jax
import jax.numpy as jnp
from jax import lax


def kv_cache_update(module, k, v, rotate_fn=None):
    """Create-or-append to the module's KV cache.

    ``k``/``v``: freshly projected [B, S, Hkv, Dh] (pre-rotary).
    ``rotate_fn(k, start_index)``: optional positional rotation applied to the
    keys *before* they are stored (the cache holds rotated keys so decode
    steps never re-rotate history).

    Returns ``(k_full, v_full, start_index)`` where ``start_index`` is the
    cursor *before* this write (callers rotate q with it).
    """
    is_initialized = module.has_variable("cache", "cached_key")
    cached_key = module.variable("cache", "cached_key", jnp.zeros, k.shape,
                                 k.dtype)
    cached_value = module.variable("cache", "cached_value", jnp.zeros, v.shape,
                                   v.dtype)
    cache_index = module.variable("cache", "cache_index",
                                  lambda: jnp.zeros((), jnp.int32))
    if not is_initialized:
        # init pass: the [B, max_len] dummy input sizes the buffers
        idx = jnp.zeros((), jnp.int32)
        if rotate_fn is not None:
            k = rotate_fn(k, idx)
        return k, v, idx

    idx = cache_index.value
    if rotate_fn is not None:
        k = rotate_fn(k, idx)
    cached_key.value = lax.dynamic_update_slice(
        cached_key.value, k.astype(cached_key.value.dtype), (0, idx, 0, 0))
    cached_value.value = lax.dynamic_update_slice(
        cached_value.value, v.astype(cached_value.value.dtype), (0, idx, 0, 0))
    cache_index.value = idx + k.shape[1]
    return cached_key.value, cached_value.value, idx


def decode_attention(q, k_full, v_full, start_index, softmax_scale=None,
                     window=0, alibi_slopes=None):
    """Attention of S query tokens (global positions ``start_index + s``)
    over a full-length KV buffer, masked so query s sees keys
    ``j <= start_index + s``.  Degenerates to plain causal attention for the
    prefill/init pass (start_index == 0, S == L).

    GQA-native: ``k_full``/``v_full`` keep their Hkv heads — queries are
    grouped as [B, S, Hkv, rep, Dh] and contracted against the unexpanded
    cache, so no step materializes an H/Hkv-times larger KV tensor.

    q: [B, S, H, Dh]; k_full/v_full: [B, L, Hkv, Dh] with H % Hkv == 0.
    """
    B, S, H, Dh = q.shape
    L, Hkv = k_full.shape[1], k_full.shape[2]
    rep = H // Hkv
    scale = softmax_scale if softmax_scale is not None else Dh**-0.5
    qg = q.reshape(B, S, Hkv, rep, Dh).astype(jnp.float32)
    scores = jnp.einsum("bsgrd,blgd->bgrsl", qg,
                        k_full.astype(jnp.float32)) * scale
    key_pos = jnp.arange(L)[None, :]
    query_pos = start_index + jnp.arange(S)[:, None]
    mask = key_pos <= query_pos                      # [S, L]
    if window:  # sliding window: only the last `window` keys are visible
        mask &= key_pos > query_pos - window
    if alibi_slopes is not None:
        # ALiBi in its softmax-invariant form: + slope_h * key_pos (differs
        # from -slope*(q-k) by a per-row constant the softmax cancels)
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(Hkv, rep)
        kp = jnp.arange(L, dtype=jnp.float32)
        scores = scores + sl[None, :, :, None, None] \
            * kp[None, None, None, None, :]
    scores = jnp.where(mask[None, None, None], scores,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bgrsl,blgd->bsgrd", probs, v_full.astype(jnp.float32))
    return out.reshape(B, S, H, Dh).astype(q.dtype)
