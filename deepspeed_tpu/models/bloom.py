"""Bloom — ALiBi-attention causal LM (reference ``module_inject/containers/
bloom.py`` serves it via v1 kernel injection; Bloom is NOT in the FastGen
model list, so here too it serves through the v1 ``init_inference`` engine).

Layout notes (HF ``modeling_bloom``):
* fused ``query_key_value`` projects to head-interleaved ``[H, 3, Dh]`` —
  the flax module keeps exactly that layout so checkpoint ingest is a plain
  transpose;
* ALiBi replaces positional embeddings: per-head slope × key position added
  to the attention scores (the softmax-invariant form of −slope·distance);
* embeddings pass through a LayerNorm, and the LM head is always tied.
"""

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 64
    num_hidden_layers: int = 2
    num_attention_heads: int = 8
    layer_norm_epsilon: float = 1e-5
    apply_residual_connection_post_layernorm: bool = False
    dtype: str = "bfloat16"
    remat: bool = False
    remat_policy: str = "nothing_saveable"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def bloom_tiny(**overrides):
    return BloomConfig(**{**dict(vocab_size=256, hidden_size=64,
                                 num_hidden_layers=2,
                                 num_attention_heads=4), **overrides})


def alibi_slopes(n_heads):
    """Per-head ALiBi slopes (the published recipe: powers of
    2^(−8/n) for the closest power of two, interleaved extras beyond)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if np.log2(n_heads).is_integer():
        return np.asarray(pow2_slopes(n_heads), np.float32)
    closest = 2 ** int(np.floor(np.log2(n_heads)))
    extra = pow2_slopes(2 * closest)[0::2][:n_heads - closest]
    return np.asarray(pow2_slopes(closest) + extra, np.float32)


class BloomBlock(nn.Module):
    config: BloomConfig

    @nn.compact
    def __call__(self, x, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_epsilon,
                     dtype=dtype, param_dtype=jnp.float32)
        dense = partial(nn.Dense, dtype=dtype, param_dtype=jnp.float32)
        slopes = jnp.asarray(alibi_slopes(H))

        h = ln(name="input_layernorm")(x)
        qkv = dense(3 * D, name="query_key_value")(h)
        qkv = qkv.reshape(B, S, H, 3, Dh)          # HF head-interleaved
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]

        if decode:
            from .cache import decode_attention, kv_cache_update
            k, v, start = kv_cache_update(self, k, v)
            attn = decode_attention(q, k, v, start, alibi_slopes=slopes)
        else:
            from ..ops.attention import attention_core
            attn = attention_core(q, k, v, causal=True,
                                  alibi_slopes=slopes)
        attn_out = dense(D, name="dense")(attn.reshape(B, S, D))

        residual = h if cfg.apply_residual_connection_post_layernorm else x
        x = residual + attn_out

        h2 = ln(name="post_attention_layernorm")(x)
        mlp = dense(D, name="dense_4h_to_h")(
            nn.gelu(dense(4 * D, name="dense_h_to_4h")(h2)))
        residual2 = h2 if cfg.apply_residual_connection_post_layernorm else x
        return residual2 + mlp


class BloomModel(nn.Module):
    """Causal LM.  ``__call__(input_ids, labels=None)`` → loss if labels
    given else logits (tied LM head — Bloom checkpoints never carry one)."""
    config: BloomConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=dtype,
                         name="word_embeddings")
        x = embed(input_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         param_dtype=jnp.float32,
                         name="word_embeddings_layernorm")(x)
        block = BloomBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(BloomBlock, policy=policy, static_argnums=(2, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"h_{i}")(x, decode)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        logits = embed.attend(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)


def tp_rules(config: BloomConfig):
    return {
        "query_key_value/kernel": P(None, ("tp", "zero")),
        "dense/kernel": P(("tp", "zero"), None),
        "dense_h_to_4h/kernel": P(None, ("tp", "zero")),
        "dense_4h_to_h/kernel": P(("tp", "zero"), None),
        "word_embeddings/embedding": P(("tp", "zero"), None),
    }
