"""BERT family (TPU-first flax) — covers BASELINE config 1 (BERT-base ZeRO-0
fp32) and the BERT-Large pretraining throughput baseline (BASELINE.md).
Post-LN encoder blocks per original BERT; MLM head; 'returns loss with labels'
contract (labels = masked-token ids, -100 = ignore)."""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import flax.linen as nn


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    # HF BertForMaskedLM head: transform dense+gelu+LN before the tied
    # decoder, plus a decoder bias — enabled when serving HF checkpoints
    mlm_transform: bool = False
    dtype: str = "float32"
    remat: bool = False

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def bert_base(**overrides):
    return BertConfig(**overrides)


def bert_large(**overrides):
    return BertConfig(**{**dict(hidden_size=1024, num_hidden_layers=24,
                                num_attention_heads=16, intermediate_size=4096),
                         **overrides})


def bert_tiny(**overrides):
    return BertConfig(**{**dict(vocab_size=256, hidden_size=64,
                                num_hidden_layers=2, num_attention_heads=4,
                                intermediate_size=128,
                                max_position_embeddings=128), **overrides})


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        dense = partial(nn.DenseGeneral, dtype=dtype, param_dtype=jnp.float32)
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_eps, dtype=dtype,
                     param_dtype=jnp.float32)

        q = dense(features=(H, Dh), name="query")(x)
        k = dense(features=(H, Dh), name="key")(x)
        v = dense(features=(H, Dh), name="value")(x)
        scale = Dh**-0.5
        logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
        if mask is not None:
            logits = jnp.where(mask[:, None, None, :].astype(bool), logits,
                               jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(dtype)
        ctx = jnp.einsum("bhst,bthd->bshd", probs, v)
        attn = dense(features=D, axis=(-2, -1), name="attention_output")(ctx)
        x = ln(name="attention_ln")(x + attn)

        h = dense(features=cfg.intermediate_size, name="intermediate")(x)
        h = nn.gelu(h, approximate=False)  # BERT's gelu is the exact erf
        h = dense(features=D, name="output")(h)
        return ln(name="output_ln")(x + h)


class BertModel(nn.Module):
    """Encoder + MLM head; ``__call__(input_ids, labels=None, attention_mask=None)``."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 token_type_ids=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S = input_ids.shape
        we = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=dtype,
                      param_dtype=jnp.float32, name="word_embeddings")
        pe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                      dtype=dtype, param_dtype=jnp.float32,
                      name="position_embeddings")
        te = nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=dtype,
                      param_dtype=jnp.float32, name="token_type_embeddings")
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = we(input_ids) + pe(jnp.arange(S)[None, :]) + te(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                         param_dtype=jnp.float32, name="embeddings_ln")(x)

        layer = BertLayer
        if cfg.remat:
            layer = nn.remat(BertLayer,
                             policy=jax.checkpoint_policies.nothing_saveable)
        for i in range(cfg.num_hidden_layers):
            x = layer(cfg, name=f"layer_{i}")(x, attention_mask)

        if cfg.mlm_transform:
            x = nn.Dense(cfg.hidden_size, dtype=dtype,
                         param_dtype=jnp.float32, name="mlm_dense")(x)
            x = nn.gelu(x, approximate=False)
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                             param_dtype=jnp.float32, name="mlm_ln")(x)
            bias = self.param("mlm_bias", nn.initializers.zeros,
                              (cfg.vocab_size,), jnp.float32)
            logits = we.attend(x.astype(jnp.float32)) + bias
        else:
            logits = we.attend(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        per_tok = softmax_cross_entropy_with_logits(logits, jnp.maximum(labels, 0))
        m = (labels >= 0).astype(jnp.float32)
        return jnp.sum(per_tok * m) / jnp.maximum(jnp.sum(m), 1.0)
