"""GPT-J — reference ``module_inject/containers/gptj.py`` (v1 injection
family; serves through ``init_inference``).

Layout notes (HF ``modeling_gptj``):
* separate UNBIASED q/k/v/out projections;
* INTERLEAVED rotary over the first ``rotary_dim`` dims (GPT-J convention:
  rotate-every-two — NOT the llama/neox half-split);
* one shared LayerNorm feeds both attention and the MLP (parallel
  residual: ``x + attn(ln(x)) + mlp(ln(x))``);
* untied ``lm_head`` WITH bias.
"""

from dataclasses import dataclass
from functools import partial


import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from .llama import _rope_freqs


@dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    hidden_size: int = 64
    num_hidden_layers: int = 2
    num_attention_heads: int = 4
    rotary_dim: int = 16
    intermediate_size: int = 256
    max_position_embeddings: int = 2048
    layer_norm_epsilon: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = False
    remat_policy: str = "nothing_saveable"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def gptj_tiny(**overrides):
    return GPTJConfig(**{**dict(vocab_size=256, hidden_size=64,
                                num_hidden_layers=2, num_attention_heads=4,
                                rotary_dim=8, intermediate_size=128,
                                max_position_embeddings=128), **overrides})


def apply_rotary_interleaved(x, cos, sin, rd, positions=None):
    """GPT-J rotary: rotate-every-two over the first ``rd`` dims.
    x: [B, S, H, Dh]; cos/sin: [Smax, rd/2]."""
    S = x.shape[1]
    if positions is None:
        c = cos[:S][None, :, None, :]
        s = sin[:S][None, :, None, :]
    else:
        c = cos[positions][:, :, None, :]
        s = sin[positions][:, :, None, :]
    xr, xp = x[..., :rd], x[..., rd:]
    x1, x2 = xr[..., 0::2], xr[..., 1::2]
    out = jnp.stack([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    out = out.reshape(*xr.shape)
    return jnp.concatenate([out, xp], axis=-1).astype(x.dtype)


class GPTJBlock(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, x, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        rd = cfg.rotary_dim
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=dtype,
                        param_dtype=jnp.float32)
        cos, sin = _rope_freqs(rd, cfg.max_position_embeddings, 10000.0)
        cos = jnp.asarray(cos, jnp.float32)
        sin = jnp.asarray(sin, jnp.float32)

        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         param_dtype=jnp.float32, name="ln_1")(x)
        q = dense(features=(H, Dh), name="q_proj")(h)
        k = dense(features=(H, Dh), name="k_proj")(h)
        v = dense(features=(H, Dh), name="v_proj")(h)

        if decode:
            from .cache import decode_attention, kv_cache_update

            def rotate_k(kk, start):
                pos = start + jnp.arange(kk.shape[1])[None, :]
                return apply_rotary_interleaved(kk, cos, sin, rd,
                                                positions=pos)

            k, v, start = kv_cache_update(self, k, v, rotate_fn=rotate_k)
            q = apply_rotary_interleaved(
                q, cos, sin, rd, positions=start + jnp.arange(S)[None, :])
            attn = decode_attention(q, k, v, start, softmax_scale=Dh**-0.5)
        else:
            q = apply_rotary_interleaved(q, cos, sin, rd)
            k = apply_rotary_interleaved(k, cos, sin, rd)
            from ..ops.attention import attention_core
            attn = attention_core(q, k, v, causal=True)
        attn_out = nn.Dense(D, use_bias=False, dtype=dtype,
                            param_dtype=jnp.float32,
                            name="out_proj")(attn.reshape(B, S, H * Dh))

        mlp = nn.Dense(D, dtype=dtype, param_dtype=jnp.float32,
                       name="fc_out")(
            nn.gelu(nn.Dense(cfg.intermediate_size, dtype=dtype,
                             param_dtype=jnp.float32, name="fc_in")(h)))
        return x + attn_out + mlp  # parallel residual off ONE shared ln


class GPTJModel(nn.Module):
    """Causal-LM.  ``__call__(input_ids, labels=None)`` → loss if labels
    given else logits (untied biased ``lm_head``)."""
    config: GPTJConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                     param_dtype=jnp.float32, dtype=dtype,
                     name="wte")(input_ids)
        block = GPTJBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(GPTJBlock, policy=policy, static_argnums=(2, ))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, name=f"h_{i}")(x, decode)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=True, dtype=jnp.float32,
                          param_dtype=jnp.float32,
                          name="lm_head")(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)


def tp_rules(config: GPTJConfig):
    return {
        "q_proj/kernel": P(None, "tp", "zero"),
        "k_proj/kernel": P(None, "tp", "zero"),
        "v_proj/kernel": P(None, "tp", "zero"),
        "out_proj/kernel": P("tp", "zero"),
        "fc_in/kernel": P(None, ("tp", "zero")),
        "fc_out/kernel": P(("tp", "zero"), None),
        "wte/embedding": P(("tp", "zero"), None),
        "lm_head/kernel": P(None, ("tp", "zero")),
    }
