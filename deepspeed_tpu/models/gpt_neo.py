"""GPT-Neo — reference ``module_inject/containers/gptneo.py`` (v1
injection family; serves through ``init_inference``).

Layout notes (HF ``modeling_gpt_neo``):
* learned positions (``wpe``), gpt2-style sequential residual;
* alternating per-layer attention types: "global" (full causal) and
  "local" (sliding window of ``window_size`` keys) — the window reuses the
  same Pallas flash block-skip path Mistral does;
* **unscaled** attention scores (GPT-Neo skips the 1/sqrt(Dh) factor);
* unbiased q/k/v, biased out_proj/mlp, tied LM head.
"""

from dataclasses import dataclass, field
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class GPTNeoConfig:
    vocab_size: int = 50257
    hidden_size: int = 64
    num_hidden_layers: int = 2
    num_attention_heads: int = 4
    intermediate_size: int = 256
    max_position_embeddings: int = 2048
    window_size: int = 256
    attention_layers: Tuple[str, ...] = ("global", "local")
    layer_norm_epsilon: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = False
    remat_policy: str = "nothing_saveable"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def gpt_neo_tiny(**overrides):
    return GPTNeoConfig(**{**dict(vocab_size=256, hidden_size=64,
                                  num_hidden_layers=2,
                                  num_attention_heads=4,
                                  intermediate_size=128,
                                  max_position_embeddings=128,
                                  window_size=8), **overrides})


class GPTNeoBlock(nn.Module):
    config: GPTNeoConfig
    attention_type: str = "global"

    @nn.compact
    def __call__(self, x, decode=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        window = cfg.window_size if self.attention_type == "local" else 0
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_epsilon,
                     dtype=dtype, param_dtype=jnp.float32)
        qkv = partial(nn.DenseGeneral, use_bias=False, dtype=dtype,
                      param_dtype=jnp.float32)

        h = ln(name="ln_1")(x)
        q = qkv(features=(H, Dh), name="q_proj")(h)
        k = qkv(features=(H, Dh), name="k_proj")(h)
        v = qkv(features=(H, Dh), name="v_proj")(h)

        if decode:
            from .cache import decode_attention, kv_cache_update
            k, v, start = kv_cache_update(self, k, v)
            attn = decode_attention(q, k, v, start, softmax_scale=1.0,
                                    window=window)
        else:
            from ..ops.attention import attention_core
            # GPT-Neo does NOT scale scores by 1/sqrt(Dh)
            attn = attention_core(q, k, v, causal=True, softmax_scale=1.0,
                                  window=window)
        attn_out = nn.Dense(D, dtype=dtype, param_dtype=jnp.float32,
                            name="out_proj")(attn.reshape(B, S, H * Dh))
        x = x + attn_out

        h2 = ln(name="ln_2")(x)
        mlp = nn.Dense(D, dtype=dtype, param_dtype=jnp.float32,
                       name="c_proj")(
            nn.gelu(nn.Dense(cfg.intermediate_size, dtype=dtype,
                             param_dtype=jnp.float32, name="c_fc")(h2)))
        return x + mlp


class GPTNeoModel(nn.Module):
    """Causal-LM.  ``__call__(input_ids, labels=None)`` → loss if labels
    given else logits (tied head)."""
    config: GPTNeoConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 decode=False, positions=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S = input_ids.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       param_dtype=jnp.float32, dtype=dtype, name="wte")
        wpe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       param_dtype=jnp.float32, dtype=dtype, name="wpe")
        if positions is None:
            positions = jnp.arange(S)[None, :]
        x = wte(input_ids) + wpe(positions)

        block = GPTNeoBlock
        if cfg.remat and not decode:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block = nn.remat(GPTNeoBlock, policy=policy, static_argnums=(2, ))
        at = cfg.attention_layers
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, attention_type=at[i % len(at)],
                      name=f"h_{i}")(x, decode)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        logits = wte.attend(x.astype(jnp.float32))
        if labels is None:
            return logits
        from ..sequence.cross_entropy import softmax_cross_entropy_with_logits
        loss = softmax_cross_entropy_with_logits(logits[:, :-1], labels[:, 1:])
        if attention_mask is not None:
            m = attention_mask[:, 1:].astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)


def tp_rules(config: GPTNeoConfig):
    return {
        "q_proj/kernel": P(None, "tp", "zero"),
        "k_proj/kernel": P(None, "tp", "zero"),
        "v_proj/kernel": P(None, "tp", "zero"),
        "out_proj/kernel": P("tp", "zero"),
        "c_fc/kernel": P(None, ("tp", "zero")),
        "c_proj/kernel": P(("tp", "zero"), None),
        "wte/embedding": P(("tp", "zero"), None),
    }
