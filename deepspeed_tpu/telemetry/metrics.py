"""Metrics registry: counters / gauges / histograms with pluggable sinks.

The registry is the live-metrics half of the telemetry spine.  Emit sites
(engine step metrics, checkpoint durations, watchdog heartbeats, collective
variant picks) update instruments in memory; sinks export snapshots:

* :class:`MonitorSink` — fans a snapshot out through the existing
  ``monitor/`` backends (TensorBoard / W&B / CSV / Comet), making them
  sinks of the unified registry instead of a parallel event path;
* :func:`render_prometheus` — Prometheus text exposition format, served
  live by :class:`PrometheusEndpoint` (a tiny stdlib HTTP server) or
  scraped from the returned string;
* :meth:`MetricsRegistry.snapshot` / :meth:`merge` — the rank-0
  aggregation path: non-zero ranks snapshot, ship the dict (e.g. over
  ``dist.send_obj``), and rank 0 merges before exporting, so dashboards see
  one job-level series instead of world_size disjoint ones.

Instrument names use ``/`` as the namespace separator (monitor-style);
Prometheus rendering sanitizes them to ``_``.
"""

import math
import re
import threading

from ..utils.logging import logger

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0, 300.0)


class Counter:
    __slots__ = ("name", "help", "value")

    kind = "counter"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, n=1.0):
        self.value += n


class Gauge:
    __slots__ = ("name", "help", "value")

    kind = "gauge"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, v):
        self.value = float(v)

    def inc(self, n=1.0):
        self.value += n

    def dec(self, n=1.0):
        self.value -= n


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations ≤ its upper bound; +Inf is implicit = count)."""

    __slots__ = ("name", "help", "buckets", "counts", "count", "sum")

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * len(self.buckets)
        self.count = 0
        self.sum = 0.0

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        for i, bound in enumerate(self.buckets):
            if v <= bound:
                self.counts[i] += 1

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0


class MetricsRegistry:

    def __init__(self):
        self._instruments = {}
        self._lock = threading.Lock()

    def _get(self, name, cls, help, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, help=help, **kw)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{inst.kind}, requested {cls.kind}")
            return inst

    def counter(self, name, help=""):
        return self._get(name, Counter, help)

    def gauge(self, name, help=""):
        return self._get(name, Gauge, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        return self._get(name, Histogram, help, buckets=buckets)

    def instruments(self):
        with self._lock:
            return list(self._instruments.values())

    def __len__(self):
        return len(self._instruments)

    # -------------------------------------------------- snapshot / aggregate
    def snapshot(self):
        """Plain-dict snapshot, pickle/JSON-safe — the wire format of the
        rank-0 aggregation path."""
        out = {}
        for inst in self.instruments():
            if inst.kind == "histogram":
                out[inst.name] = {"kind": "histogram",
                                  "buckets": list(inst.buckets),
                                  "counts": list(inst.counts),
                                  "count": inst.count, "sum": inst.sum}
            else:
                out[inst.name] = {"kind": inst.kind, "value": inst.value}
        return out

    def merge(self, snapshot):
        """Fold another rank's :meth:`snapshot` into this registry:
        counters and histograms sum; gauges keep the max (the conservative
        job-level read for ages/backlogs)."""
        for name, rec in snapshot.items():
            kind = rec.get("kind")
            if kind == "counter":
                self.counter(name).inc(rec.get("value", 0.0))
            elif kind == "gauge":
                g = self.gauge(name)
                g.set(max(g.value, rec.get("value", 0.0)))
            elif kind == "histogram":
                h = self.histogram(name,
                                   buckets=rec.get("buckets",
                                                   DEFAULT_BUCKETS))
                if list(h.buckets) == [float(b) for b in
                                       rec.get("buckets", [])]:
                    for i, c in enumerate(rec.get("counts", [])):
                        h.counts[i] += int(c)
                else:
                    logger.warning("telemetry: bucket mismatch merging "
                                   "histogram %r; folding count/sum only",
                                   name)
                h.count += int(rec.get("count", 0))
                h.sum += float(rec.get("sum", 0.0))

    def export(self, sinks, step=0):
        """Push the current snapshot to each sink; a failing sink warns and
        is skipped — metrics export must never kill a training step."""
        for sink in sinks:
            try:
                sink.write(self, step)
            except Exception as e:
                logger.warning("telemetry: sink %s failed (%s: %s)",
                               type(sink).__name__, type(e).__name__, e)


class MonitorSink:
    """Adapter: the ``monitor/`` backends (TB / W&B / CSV / Comet) become
    sinks of the unified registry.  Histograms export as ``_mean`` +
    ``_count`` scalars (the backends are scalar streams)."""

    def __init__(self, monitor, prefix="Telemetry/"):
        self.monitor = monitor
        self.prefix = prefix

    def write(self, registry, step):
        if self.monitor is None or not getattr(self.monitor, "enabled",
                                               False):
            return
        events = []
        for inst in registry.instruments():
            name = self.prefix + inst.name
            if inst.kind == "histogram":
                events.append((name + "_mean", inst.mean, step))
                events.append((name + "_count", float(inst.count), step))
            else:
                events.append((name, float(inst.value), step))
        if events:
            self.monitor.write_events(events)


# ------------------------------------------------------------- prometheus
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name):
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _fmt(v):
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def render_prometheus(registry, labels=None):
    """Prometheus text exposition (version 0.0.4) of the registry."""
    label_str = ""
    if labels:
        inner = ",".join(f'{_prom_name(k)}="{v}"'
                         for k, v in sorted(labels.items()))
        label_str = "{" + inner + "}"
    lines = []
    for inst in sorted(registry.instruments(), key=lambda i: i.name):
        name = _prom_name(inst.name)
        if inst.help:
            lines.append(f"# HELP {name} {inst.help}")
        lines.append(f"# TYPE {name} {inst.kind}")
        if inst.kind == "histogram":
            for bound, c in zip(inst.buckets, inst.counts):
                le = (f'{{le="{_fmt(bound)}"' +
                      (("," + label_str[1:]) if label_str else "}"))
                lines.append(f"{name}_bucket{le} {c}")
            inf_label = ('{le="+Inf"' +
                         (("," + label_str[1:]) if label_str else "}"))
            lines.append(f"{name}_bucket{inf_label} {inst.count}")
            lines.append(f"{name}_sum{label_str} {_fmt(inst.sum)}")
            lines.append(f"{name}_count{label_str} {inst.count}")
        else:
            lines.append(f"{name}{label_str} {_fmt(inst.value)}")
    return "\n".join(lines) + "\n"


class PrometheusEndpoint:
    """Threaded stdlib HTTP server exposing ``/metrics``.  Start on rank 0
    only — the registry it serves is the post-:meth:`~MetricsRegistry.merge`
    aggregate."""

    def __init__(self, registry, port, host="0.0.0.0", labels=None):
        self.registry = registry
        self.port = int(port)
        self.host = host
        self.labels = labels or {}
        self._server = None
        self._thread = None

    def start(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        registry, labels = self.registry, self.labels

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path.rstrip("/") not in ("", "/metrics",
                                                 "/healthz"):
                    self.send_error(404)
                    return
                body = render_prometheus(registry, labels).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # keep training logs clean
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="ds-tpu-metrics",
                                        daemon=True)
        self._thread.start()
        logger.info("telemetry: Prometheus endpoint on :%d/metrics",
                    self.port)
        return self

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None
