"""Structured step traces: spans → Chrome-trace JSON + per-step JSONL.

The :class:`TraceRecorder` is the event spine every subsystem emits into
(engine phases, collectives, checkpoint engine, watchdog).  Two outputs:

* ``trace.json`` — Chrome trace-event format (load in ``chrome://tracing``
  or https://ui.perfetto.dev): one complete-event (``"ph": "X"``) per span,
  comm ops on their own track, written on :meth:`close` (and at interpreter
  exit as a backstop);
* ``steps.jsonl`` — one compact JSON record per optimizer step, appended as
  the step ends: wall time, per-phase breakdown, per-``op[variant]`` comm
  attribution with the exposed-comm-fraction estimate, and engine metrics
  (loss, grad norm, throughput).  This is what ``tools/trace_report.py``
  and the future autotuner ingest.

Timing is host wall time (``time.perf_counter``).  With ``fence=True`` the
recorder blocks on the accelerator at phase boundaries, so phase times are
CPU-accurate attributions instead of async-dispatch shadows — the same
trade ``comms_logger.sync_timing`` makes, documented in
docs/observability.md.  With ``device_annotations=True`` spans additionally
wrap ``jax.profiler`` annotations so an xplane capture
(``engine.start_device_trace``) carries the phase names into the
device-time view.
"""

import atexit
import json
import os
import sys
import time

from ..utils.logging import logger
from .comm_attribution import (CommAttribution, exposed_fraction,
                               overlap_efficiency)

# canonical phase names — the engine emits exactly these, and
# tools/trace_report.py columns key off them
SPAN_FORWARD = "forward"
SPAN_BACKWARD = "backward"
SPAN_GRAD_REDUCE = "grad_reduce"
SPAN_OPTIMIZER = "optimizer"
SPAN_CHECKPOINT = "checkpoint"

PHASES = (SPAN_FORWARD, SPAN_BACKWARD, SPAN_GRAD_REDUCE, SPAN_OPTIMIZER,
          SPAN_CHECKPOINT)

#: per-bucket reduce spans render as ``bucket_reduce/<index>`` — their own
#: namespace (the ``overlap`` section of the step record), never a phase
#: column (the overlap bench and eager bucket paths emit them; a fully
#: jitted step has none — its buckets live inside the compiled graph and
#: are visible only as trace metadata + HLO structure)
SPAN_BUCKET_PREFIX = "bucket_reduce"
#: forward-direction twin: per-bucket param-gather prefetch spans render
#: as ``param_gather/<index>`` in the same ``overlap`` namespace
SPAN_GATHER_PREFIX = "param_gather"
_BUCKET_SPAN_PREFIXES = (SPAN_BUCKET_PREFIX + "/", SPAN_GATHER_PREFIX + "/")

TRACE_FILE = "trace.json"
STEPS_FILE = "steps.jsonl"

#: chrome-trace keys every complete event must carry (schema contract the
#: unit tests and trace_report validate against)
CHROME_EVENT_KEYS = ("name", "ph", "ts", "dur", "pid", "tid")

_COMM_TID = 1  # comm ops render on their own track under each pid


def _sync_device():
    """Block until the accelerator drains (fence mode)."""
    from ..accelerator import get_accelerator
    get_accelerator().synchronize()


class _SpanHandle:
    """Context manager for one span; also usable via explicit begin/end."""

    __slots__ = ("_rec", "name", "cat", "args", "_t0", "_annotation")

    def __init__(self, rec, name, cat, args):
        self._rec = rec
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = None
        self._annotation = None

    def __enter__(self):
        self._rec._begin(self)
        return self

    def __exit__(self, *exc):
        self._rec._end(self)
        return False


class TraceRecorder:

    def __init__(self, trace_dir, fence=False, device_annotations=False,
                 trace_steps=0, rank=0, max_events=200_000,
                 sync_fn=_sync_device):
        self.trace_dir = os.path.abspath(trace_dir)
        self.fence = bool(fence)
        self.device_annotations = bool(device_annotations)
        self.trace_steps = int(trace_steps)  # 0 = unbounded
        self.rank = int(rank)
        self.max_events = int(max_events)
        self._sync = sync_fn
        self._epoch = time.perf_counter()
        self._events = []            # chrome complete events
        self._meta = {}              # metadata blobs (zero plan, config, …)
        self._dropped = 0
        self._stack = []             # open _SpanHandle frames
        self._steps_file = None
        self._closed = False
        # per-step state
        self._step = None
        self._step_t0 = None
        self._step_annotation = None
        self._phase_s = {}
        self._bucket_s = {}
        self._moe_s = {}             # layer → accumulated routing stats
        self._hbm = None             # memory_stats snapshot for the step
        self._step_comm = CommAttribution()
        self._run_comm = CommAttribution()
        self.steps_recorded = 0
        os.makedirs(self.trace_dir, exist_ok=True)
        atexit.register(self.close)

    # ------------------------------------------------------------- internals
    def _now_us(self):
        return (time.perf_counter() - self._epoch) * 1e6

    def _emit(self, name, cat, ts_us, dur_us, tid=0, args=None):
        if len(self._events) >= self.max_events:
            self._dropped += 1
            return
        ev = {"name": name, "cat": cat, "ph": "X", "ts": ts_us,
              "dur": dur_us, "pid": self.rank, "tid": tid}
        if args:
            ev["args"] = args
        self._events.append(ev)

    @property
    def recording(self):
        """False once the trace_steps budget is spent — emit sites stay
        cheap because the engine stops opening steps."""
        return not self._closed and (
            self.trace_steps <= 0 or self.steps_recorded < self.trace_steps)

    # ----------------------------------------------------------------- spans
    def span(self, name, cat="compute", **args):
        """``with recorder.span("forward"): ...`` — spans nest; every span
        feeds the per-step phase breakdown by name, so a nested phase
        (``grad_reduce`` inside ``backward``) reports its own time AND is
        contained in its parent's — phase columns are attributions, not a
        partition of the wall time."""
        return _SpanHandle(self, name, cat, args or None)

    def begin_span(self, name, cat="compute", **args):
        """Explicit-begin variant for linear call sites (engine hot path);
        pair with :meth:`end_span`."""
        h = _SpanHandle(self, name, cat, args or None)
        self._begin(h)
        return h

    def end_span(self, name=None):
        """Close the innermost open span (``name`` asserts intent; a
        mismatch is logged, never raised — telemetry must not kill a
        step)."""
        if not self._stack:
            logger.warning("telemetry: end_span(%r) with no open span", name)
            return
        h = self._stack[-1]
        if name is not None and h.name != name:
            logger.warning("telemetry: end_span(%r) closes open span %r",
                           name, h.name)
        self._end(h)

    def _begin(self, h):
        if self.fence:
            self._sync()
        if self.device_annotations:
            try:
                import jax
                h._annotation = jax.profiler.TraceAnnotation(h.name)
                h._annotation.__enter__()
            except Exception:
                h._annotation = None
        self._stack.append(h)
        h._t0 = time.perf_counter()

    def _end(self, h):
        if self.fence:
            self._sync()
        t1 = time.perf_counter()
        if h._annotation is not None:
            try:
                h._annotation.__exit__(None, None, None)
            except Exception:
                pass
            h._annotation = None
        try:
            depth = self._stack.index(h)
        except ValueError:
            return  # already closed
        # close anything left open underneath (exception unwound past it)
        del self._stack[depth:]
        dur = t1 - h._t0
        self._emit(h.name, h.cat, (h._t0 - self._epoch) * 1e6, dur * 1e6,
                   args=h.args)
        if self._step is not None:
            if h.name.startswith(_BUCKET_SPAN_PREFIXES):
                self._bucket_s[h.name] = self._bucket_s.get(h.name, 0.0) \
                    + dur
            else:
                self._phase_s[h.name] = self._phase_s.get(h.name, 0.0) + dur

    # ----------------------------------------------------------------- steps
    def begin_step(self, step):
        """Open the per-step record window.  Idempotent for the same step
        index (forward() calls it once per micro-batch)."""
        if self._step == step or not self.recording:
            return
        if self._step is not None:
            self.end_step()   # unterminated previous window: flush it
        self._step = step
        self._step_t0 = time.perf_counter()
        self._phase_s = {}
        self._bucket_s = {}
        self._moe_s = {}
        self._hbm = None
        self._step_comm.reset()
        if self.device_annotations:
            try:
                import jax
                self._step_annotation = jax.profiler.StepTraceAnnotation(
                    "train_step", step_num=step)
                self._step_annotation.__enter__()
            except Exception:
                self._step_annotation = None

    def end_step(self, metrics=None):
        """Close the step window: emit the chrome step event and append one
        JSONL record.  ``metrics`` is a flat dict of engine numbers (loss,
        grad_norm, throughput, …) copied into the record verbatim."""
        if self._step is None:
            return
        if self.fence:
            self._sync()
        if self._step_annotation is not None:
            try:
                self._step_annotation.__exit__(None, None, None)
            except Exception:
                pass
            self._step_annotation = None
        wall_s = time.perf_counter() - self._step_t0
        step = self._step
        self._step = None
        self._emit(f"step {step}", "step",
                   (self._step_t0 - self._epoch) * 1e6, wall_s * 1e6,
                   tid=2, args={"step": step})
        exposed_s = self._step_comm.total_seconds()
        hidden_s = self._step_comm.hidden_seconds()
        record = {
            "step": step,
            "wall_ms": wall_s * 1e3,
            "phases": {k: v * 1e3 for k, v in sorted(self._phase_s.items())},
            "comm": {
                "total_ms": (exposed_s + hidden_s) * 1e3,
                "exposed_ms": exposed_s * 1e3,
                "hidden_ms": hidden_s * 1e3,
                "exposed_comm_fraction": exposed_fraction(exposed_s, wall_s),
                "overlap_efficiency": overlap_efficiency(
                    hidden_s, exposed_s + hidden_s),
                "ops": self._step_comm.summary(),
            },
        }
        if self._hbm:
            record["hbm"] = self._hbm
        if self._bucket_s:
            record["overlap"] = {
                "buckets": len(self._bucket_s),
                "bucket_ms": {k: v * 1e3
                              for k, v in sorted(self._bucket_s.items())},
            }
        if self._moe_s:
            layers = {}
            for name, acc in sorted(self._moe_s.items()):
                n = max(1, acc.pop("_n", 1))
                vec_n = {k[3:]: max(1, acc.pop(k))
                         for k in [k for k in acc if k.startswith("_n_")]}
                layers[name] = {
                    k: (v if k == "k"
                        else ([x / vec_n.get(k, n) for x in v]
                              if isinstance(v, list) else v / n))
                    for k, v in acc.items()}
            # aggregate defensively: a client may book a partial stats
            # payload, and telemetry must never kill a step over it
            record["moe"] = {
                "layers": layers,
                "drop_fraction_mean": (sum(l.get("drop_fraction", 0.0)
                                           for l in layers.values())
                                       / len(layers)),
                "load_imbalance_max": max(l.get("load_imbalance", 0.0)
                                          for l in layers.values()),
                "aux_loss_total": sum(l.get("aux_loss", 0.0)
                                      for l in layers.values()),
            }
        if metrics:
            metrics = {k: v for k, v in metrics.items() if v is not None}
            # MFU is derived HERE because the recorder owns the step wall
            # clock: achieved per-chip flops/s ÷ per-chip peak.  Both
            # inputs ride the metrics dict (the engine's compiled-cost
            # registry supplies them) so the spine needs no profiler
            # import; absent inputs → no mfu key (refuse, don't guess).
            sf = metrics.get("step_flops_per_chip")
            peak = metrics.get("peak_flops_per_chip")
            if sf and peak and wall_s > 0 and "mfu" not in metrics:
                metrics["mfu"] = sf / wall_s / peak
            record["metrics"] = metrics
        self._append_step_record(record)
        self.steps_recorded += 1
        return record

    def _append_step_record(self, record):
        try:
            if self._steps_file is None:
                self._steps_file = open(
                    os.path.join(self.trace_dir, STEPS_FILE), "a")
            self._steps_file.write(json.dumps(record) + "\n")
            self._steps_file.flush()
        except (OSError, ValueError, TypeError) as e:
            logger.warning("telemetry: step record write failed (%s)", e)

    # ------------------------------------------------------------ comm + meta
    def bucket_span(self, index, kind=SPAN_BUCKET_PREFIX, **args):
        """Span for one bucket's eager collective — ``kind`` picks the
        direction namespace (``bucket_reduce`` for the backward gradient
        reduce, ``param_gather`` for the forward prefetch).  Lands in the
        step record's ``overlap`` section, not the phase columns."""
        return self.span(f"{kind}/{index}", cat="comm", **args)

    def hbm_stat(self, stats):
        """Attach the step-boundary device-memory snapshot to the open step
        window — the ``hbm`` section of the step record (``live_bytes`` /
        ``peak_bytes`` / ``limit_bytes`` from the accelerator's
        ``memory_stats()``, sampled on the boundary sync telemetry already
        pays for)."""
        if self._closed or self._step is None or not stats:
            return
        clean = {}
        for key, val in stats.items():
            try:
                clean[str(key)] = int(val)
            except (TypeError, ValueError):
                continue   # telemetry must never kill a step over a stat
        if clean:
            self._hbm = clean

    def moe_stat(self, layer, stats):
        """Accumulate one MoE layer's routed-token stats into the open step
        window (mean over the gas window's micro-batches at end_step).
        ``stats``: drop_fraction / overflow_tokens / load_imbalance /
        aux_loss floats plus the integer ``k``."""
        if self._closed or self._step is None:
            return
        acc = self._moe_s.setdefault(str(layer), {"_n": 0})
        acc["_n"] += 1
        for key, val in stats.items():
            if key == "k":
                acc["k"] = int(val)
            elif isinstance(val, (list, tuple)):
                # vector stats (per-expert capacity utilization) mean
                # elementwise over the gas window, like the scalars —
                # with their OWN call count (a vector present in only
                # some window calls must not be diluted by _n), and a
                # length change (resized expert group) restarts the sum
                # instead of zip-truncating silently
                vals = [float(v) for v in val]
                prev = acc.get(key)
                if isinstance(prev, list) and len(prev) == len(vals):
                    acc[key] = [a + b for a, b in zip(prev, vals)]
                    acc[f"_n_{key}"] += 1
                else:
                    acc[key] = vals
                    acc[f"_n_{key}"] = 1
            else:
                acc[key] = acc.get(key, 0.0) + float(val)

    def comm_event(self, op, variant, msg_bytes, wire_bytes, latency_s,
                   world_size=1, exposed=True):
        """One eager collective: chrome event on the comm track + join into
        the per-step (and whole-run) attribution.  ``exposed=False`` books
        the latency as hidden (overlapped-under-compute) comm time — it
        feeds ``overlap_efficiency`` instead of the exposed fraction."""
        if self._closed:
            return
        name = f"{op}[{variant}]" if variant else op
        t1 = time.perf_counter()
        self._emit(name, "comm", (t1 - latency_s - self._epoch) * 1e6,
                   latency_s * 1e6, tid=_COMM_TID,
                   args={"msg_bytes": int(msg_bytes),
                         "wire_bytes": int(wire_bytes if wire_bytes
                                           is not None else msg_bytes),
                         "exposed": bool(exposed)})
        self._run_comm.record(op, variant, msg_bytes, wire_bytes, latency_s,
                              world_size, exposed=exposed)
        if self._step is not None:
            self._step_comm.record(op, variant, msg_bytes, wire_bytes,
                                   latency_s, world_size, exposed=exposed)

    def metadata(self, name, payload):
        """Attach a structured metadata blob (zero plan, mesh, config hash);
        lands under ``otherData`` in the chrome trace."""
        try:
            json.dumps(payload)
        except (TypeError, ValueError):
            payload = repr(payload)
        self._meta[str(name)] = payload

    def comm_summary(self):
        """Whole-run per-``op[variant]`` attribution (``ds_bench --trace``
        and the smoke tool read this)."""
        return self._run_comm.summary()

    # ---------------------------------------------------------------- output
    def chrome_trace(self):
        other = dict(self._meta)
        other["rank"] = self.rank
        if self._dropped:
            other["dropped_events"] = self._dropped
        return {"traceEvents": list(self._events),
                "displayTimeUnit": "ms",
                "otherData": other}

    def write_chrome_trace(self, path=None):
        path = path or os.path.join(self.trace_dir, TRACE_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def close(self):
        """Flush both outputs.  Safe to call twice (atexit backstop)."""
        if self._closed:
            return
        if self._step is not None:
            self.end_step()
        self._closed = True
        atexit.unregister(self.close)  # bound-method equality: this entry
        if self._dropped and not sys.is_finalizing():
            logger.warning("telemetry: dropped %d trace events past the "
                           "max_events=%d cap", self._dropped,
                           self.max_events)
        try:
            self.write_chrome_trace()
        except OSError as e:
            logger.warning("telemetry: chrome trace write failed (%s)", e)
        if self._steps_file is not None:
            try:
                self._steps_file.close()
            except OSError:
                pass
            self._steps_file = None
