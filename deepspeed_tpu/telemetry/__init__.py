"""deepspeed_tpu.telemetry — the unified observability spine.

One structured-event model for everything the stack can measure:

* **step traces** (:mod:`.trace`): per-step spans (forward / backward /
  grad-reduce / optimizer / checkpoint) → Chrome-trace JSON + per-step
  JSONL records;
* **comm attribution** (:mod:`.comm_attribution`): wire-truthful bytes from
  ``utils/comms_logging`` joined with span timing → per-``op[variant]``
  latency, effective wire bandwidth, exposed-comm-fraction;
* **live metrics** (:mod:`.metrics`): counters/gauges/histograms with the
  ``monitor/`` backends as sinks plus a Prometheus text endpoint.

Disabled (the default) means **zero overhead**: every emit site in the hot
path guards on the module-level :data:`enabled` flag —

    from deepspeed_tpu import telemetry
    if telemetry.enabled:
        telemetry.record_comm_event(...)

one attribute read, no allocations, no dict churn.  ``configure()`` (called
by the engine when the ``telemetry`` config block enables it) flips the
flag and builds the recorder/registry; ``shutdown()`` flushes and flips it
back.  This module must stay import-light: ``comm/comm.py`` imports it at
module scope.
"""

from .comm_attribution import (CommAttribution,  # noqa: F401  (re-export)
                               overlap_efficiency)
from .metrics import (MetricsRegistry, MonitorSink,  # noqa: F401
                      PrometheusEndpoint, render_prometheus)
from .trace import (PHASES, SPAN_BACKWARD, SPAN_BUCKET_PREFIX,  # noqa: F401
                    SPAN_CHECKPOINT, SPAN_FORWARD, SPAN_GATHER_PREFIX,
                    SPAN_GRAD_REDUCE, SPAN_OPTIMIZER, STEPS_FILE, TRACE_FILE,
                    TraceRecorder)

#: THE flag every emit site guards on.  Only configure()/shutdown() write it.
enabled = False

_recorder = None
_registry = None
_sinks = []
_endpoint = None
_rank = 0


def get_recorder():
    """The active :class:`TraceRecorder`, or None (metrics-only mode)."""
    return _recorder


def get_registry():
    """The active :class:`MetricsRegistry`, or None when disabled."""
    return _registry


def configure(cfg, monitor=None, rank=0):
    """Enable telemetry from a ``TelemetryConfig``-shaped object (duck-typed:
    ``trace_dir``/``trace_steps``/``fence``/``device_profiler`` plus a
    ``metrics`` sub-object).  Reconfiguring tears the previous instance down
    first.  Returns (recorder, registry)."""
    global enabled, _recorder, _registry, _sinks, _endpoint, _rank
    shutdown()
    _rank = int(rank)
    trace_dir = getattr(cfg, "trace_dir", "") or "telemetry"
    _recorder = TraceRecorder(
        trace_dir,
        fence=getattr(cfg, "fence", False),
        device_annotations=getattr(cfg, "device_profiler", False),
        trace_steps=getattr(cfg, "trace_steps", 0),
        rank=_rank)
    _registry = MetricsRegistry()
    _sinks = []
    mc = getattr(cfg, "metrics", None)
    metrics_on = getattr(mc, "enabled", True) if mc is not None else True
    rank0_only = getattr(mc, "rank0_only", True) if mc is not None else True
    exporting = metrics_on and (not rank0_only or _rank == 0)
    if exporting and monitor is not None and \
            getattr(monitor, "enabled", False):
        _sinks.append(MonitorSink(monitor))
    port = getattr(mc, "prometheus_port", 0) if mc is not None else 0
    if exporting and port:
        try:
            _endpoint = PrometheusEndpoint(
                _registry, port, labels={"rank": _rank}).start()
        except OSError as e:
            from ..utils.logging import logger
            logger.warning("telemetry: Prometheus endpoint on port %s "
                           "unavailable (%s); text rendering still works",
                           port, e)
            _endpoint = None
    enabled = True
    return _recorder, _registry


def shutdown():
    """Flush traces, stop the endpoint, drop back to zero-overhead mode."""
    global enabled, _recorder, _registry, _sinks, _endpoint
    enabled = False
    if _endpoint is not None:
        _endpoint.stop()
        _endpoint = None
    if _recorder is not None:
        _recorder.close()
        _recorder = None
    _registry = None
    _sinks = []


# --------------------------------------------------------------- emit helpers
# All assume the caller already checked ``telemetry.enabled`` (the zero-
# overhead contract) but stay safe to call mid-shutdown.

def begin_step(step):
    if _recorder is not None:
        _recorder.begin_step(step)


def end_step(metrics=None):
    """Returns the just-written step record (dict) or None."""
    if _recorder is not None:
        return _recorder.end_step(metrics=metrics)
    return None


def begin_span(name, cat="compute", **args):
    if _recorder is not None:
        _recorder.begin_span(name, cat=cat, **args)


def end_span(name=None):
    if _recorder is not None:
        _recorder.end_span(name)


def span(name, cat="compute", **args):
    """Context-manager span for call sites with natural with-scoping
    (checkpoint engine, tools); the engine hot path uses begin/end."""
    if _recorder is not None:
        return _recorder.span(name, cat=cat, **args)
    import contextlib
    return contextlib.nullcontext()


def record_comm_event(op, variant, msg_bytes, wire_bytes, latency_s,
                      world_size=1, exposed=True):
    if _recorder is not None:
        _recorder.comm_event(op, variant, msg_bytes, wire_bytes, latency_s,
                             world_size, exposed=exposed)


def record_hbm(stats):
    """Device-memory snapshot (live/peak/limit bytes) into the open step
    window — the ``hbm`` section of the step record (the engine samples
    ``memory_stats()`` on the boundary sync it already pays for)."""
    if _recorder is not None:
        _recorder.hbm_stat(stats)


def record_moe_stats(layer, stats):
    """Per-layer routed-token accounting (drop fraction, overflow, expert
    load imbalance, aux loss) into the open step window — the ``moe``
    section of the step record (``moe/engine.record_routing`` emits)."""
    if _recorder is not None:
        _recorder.moe_stat(layer, stats)


def metadata(name, payload):
    if _recorder is not None:
        _recorder.metadata(name, payload)


def counter(name, help=""):
    return _registry.counter(name, help=help) if _registry is not None \
        else None


def gauge(name, help=""):
    return _registry.gauge(name, help=help) if _registry is not None \
        else None


def observe(name, value, help="", buckets=None):
    """Histogram observation (checkpoint/save durations etc.)."""
    if _registry is None:
        return
    from .metrics import DEFAULT_BUCKETS
    h = _registry.histogram(name, help=help,
                            buckets=buckets or DEFAULT_BUCKETS)
    h.observe(value)


def export_metrics(step=0):
    """Push the registry through the configured sinks (engine calls this at
    its ``steps_per_print`` cadence on the exporting rank)."""
    if _registry is not None and _sinks:
        _registry.export(_sinks, step=step)


def prometheus_text():
    """Render the live registry in Prometheus exposition format."""
    if _registry is None:
        return ""
    return render_prometheus(_registry, labels={"rank": _rank})
