"""Comm attribution — joining wire-truthful comm records with step timing.

``utils/comms_logging`` knows what each collective *transported* (logical
message bytes, post-quantization wire bytes, the ``op[variant]`` name);
the :class:`~deepspeed_tpu.telemetry.trace.TraceRecorder` knows *when* each
step ran.  This module is the join: per-step, per-``op[variant]`` latency,
effective wire bandwidth, and the **exposed-comm-fraction** estimate — the
number the backward-overlap scheduler and the comm autotuner (ROADMAP items
1 and 5) will optimize.

Semantics of "exposed": the host-observed latency of an eager collective is
time the dispatching thread actually waited — with ``telemetry.fence`` (or
``comms_logger.sync_timing``) it is the true blocked wall time; without it,
it is the dispatch cost and any backpressure XLA applied.  Communication
issued *inside* a compiled step is scheduled by XLA and shows up in the
compute phases instead — it is hidden by construction, which is exactly
what makes ``exposed_comm_fraction`` the overlap-efficiency metric: a
perfect overlap schedule drives it to 0.
"""


def variant_key(op, variant=None):
    """Canonical record key: ``all_reduce`` or ``all_reduce[q_int8]``."""
    return f"{op}[{variant}]" if variant else str(op)


def split_variant_key(key):
    """Inverse of :func:`variant_key` → ``(base_op, variant_or_None)``."""
    if "[" in key and key.endswith("]"):
        base, variant = key[:-1].split("[", 1)
        return base, variant
    return key, None


def effective_gbps(wire_bytes, seconds):
    """Wire bandwidth in Gbit/s from transported bytes (0 when unmeasured)."""
    if seconds <= 0:
        return 0.0
    return wire_bytes * 8.0 / seconds / 1e9


def exposed_fraction(exposed_seconds, window_seconds):
    """Exposed-comm fraction of a step window, clamped into [0, 1] (a
    latency sum can exceed the window when ops overlap each other)."""
    if window_seconds <= 0:
        return 0.0
    return max(0.0, min(1.0, exposed_seconds / window_seconds))


def overlap_efficiency(hidden_seconds, total_seconds):
    """Fraction of comm time hidden under compute — the overlap
    scheduler's score, clamped into [0, 1].  ``total`` is hidden+exposed
    comm time; zero total (no measured comm at all) scores 1.0: nothing
    was exposed, vacuously perfect — callers that need to distinguish
    "fully hidden" from "no comm" check the totals themselves
    (``tools/trace_report.py`` prints the fully-fused-step note)."""
    if total_seconds <= 0:
        return 1.0
    return max(0.0, min(1.0, hidden_seconds / total_seconds))


class CommAttribution:
    """Accumulates per-``op[variant]`` comm records over one window (a step,
    or a whole run) and summarizes latency / wire bandwidth."""

    def __init__(self):
        self._records = {}

    def record(self, op, variant, msg_bytes, wire_bytes, latency_s,
               world_size=1, exposed=True):
        """``exposed=False`` books the latency as *hidden* comm time —
        measured communication that ran under compute (the overlap bench
        and the bucket scheduler's accounting) — which feeds
        :func:`overlap_efficiency` instead of the exposed totals.  Hidden
        bookings do NOT bump ``count``: they annotate an op's overlapped
        share, so ``count``/``avg_ms`` keep meaning "eager calls" /
        "average exposed latency" for every existing consumer."""
        key = variant_key(op, variant)
        r = self._records.get(key)
        if r is None:
            r = self._records[key] = {
                "count": 0, "total_s": 0.0, "hidden_s": 0.0, "msg_bytes": 0,
                "wire_bytes": 0, "world_size": int(world_size),
            }
        if exposed:
            r["count"] += 1
            r["total_s"] += float(latency_s)
        else:
            r["hidden_s"] += float(latency_s)
        r["msg_bytes"] += int(msg_bytes)
        r["wire_bytes"] += int(wire_bytes if wire_bytes is not None
                               else msg_bytes)
        r["world_size"] = int(world_size)

    @property
    def empty(self):
        return not self._records

    def total_seconds(self):
        """Exposed comm seconds only — the historical meaning every
        exposed-comm-fraction consumer relies on."""
        return sum(r["total_s"] for r in self._records.values())

    def hidden_seconds(self):
        return sum(r["hidden_s"] for r in self._records.values())

    def summary(self):
        """{key: {count, total_ms, avg_ms, msg_bytes, wire_bytes, gbps,
        hidden_ms}} — each record counted exactly once; a run that falls
        back from a quantized variant to flat mid-run contributes its flat
        calls to the flat row and its quantized calls to the ``[q_*]``
        row, never both."""
        out = {}
        for key, r in sorted(self._records.items()):
            out[key] = {
                "count": r["count"],
                "total_ms": r["total_s"] * 1e3,
                "avg_ms": r["total_s"] * 1e3 / max(1, r["count"]),
                "msg_bytes": r["msg_bytes"],
                "wire_bytes": r["wire_bytes"],
                "gbps": effective_gbps(r["wire_bytes"],
                                       r["total_s"] + r["hidden_s"]),
                "hidden_ms": r["hidden_s"] * 1e3,
            }
        return out

    def reset(self):
        self._records = {}
