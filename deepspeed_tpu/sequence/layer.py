"""Ulysses sequence parallelism — TPU-native re-design of reference
``deepspeed/sequence/layer.py`` (``DistributedAttention`` ``:300``,
``_SeqAllToAll`` ``:245``, ``single_all_to_all`` ``:182``).

Semantics (identical to the reference): the transformer runs with the
**sequence** dimension sharded over the "sp" mesh axis; around attention, an
all-to-all re-shards from sequence-split to **head-split** (each rank holds
full sequence for H/sp heads), local attention runs, and the inverse
all-to-all restores sequence sharding.  On TPU both all-to-alls are
``jax.lax.all_to_all`` over the sp axis inside ``shard_map`` — XLA lays them
on ICI; gradients are handled by autodiff (all_to_all is its own transpose),
so no custom autograd.Function is needed.

GQA/uneven heads (reference ``uneven_heads_all2all`` ``:72-196``): when
``n_heads % sp != 0`` the q heads are zero-padded up to the next multiple of
sp — static shapes, so XLA still tiles the a2a + attention onto the MXU —
and sliced back after the inverse a2a.  KV heads are routed, not
replicated: each rank assembles (from its local sequence chunk) the kv head
every destination rank's q block needs, and ONE all-to-all delivers exactly
those — post-reshard kv memory is [B, S, H_local, D] like q, never
[B, S, n_kv, D] as a sequence all-gather would give.  All head-routing
indices are computed in Python at trace time.
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import groups


def _default_attention(q, k, v, causal=True, softmax_scale=None, window=0):
    """Local attention core [B, S, H, D].  After the Ulysses a2a the
    sequence axis is global, so causal/sliding-window masks apply directly;
    one shared implementation with attention_core's XLA path."""
    from ..ops.attention import _xla_attention
    return _xla_attention(q, k, v, causal=causal,
                          softmax_scale=softmax_scale, window=window)


def single_all_to_all(x, scatter_idx, gather_idx, axis_name):
    """All-to-all inside a shard_map region (reference ``:182``): scatter
    ``scatter_idx`` across the axis, gather ``gather_idx``."""
    return jax.lax.all_to_all(x, axis_name, split_axis=scatter_idx,
                              concat_axis=gather_idx, tiled=True)


class DistributedAttention:
    """Reference ``DistributedAttention`` (``sequence/layer.py:300``).

    ``local_attention``: callable (q, k, v, **kw) -> out, operating on
    [B, S_full, H_local, D] blocks.  Call this object *inside* a shard_map (or
    GSPMD-jit via ``__call__`` on global arrays with an sp-sharded seq dim).
    """

    def __init__(self, local_attention=None, sequence_process_group=None,
                 scatter_idx=2, gather_idx=1, sp_axis=None):
        self.local_attn = local_attention or _default_attention
        self.sp_axis = sp_axis or groups.SP_AXIS
        self.scatter_idx = scatter_idx  # head dim of [B,S,H,D]
        self.gather_idx = gather_idx    # sequence dim

    @staticmethod
    def _check_gqa_heads(n_q_heads, n_kv):
        """GQA requires q heads in whole groups per kv head — otherwise the
        routing table's clip-mode ``jnp.take`` silently maps the surplus q
        heads onto the LAST kv head (wrong attention, right shapes)."""
        if n_q_heads % n_kv != 0:
            raise ValueError(
                f"invalid GQA config: {n_q_heads} query heads are not an "
                f"integer multiple of {n_kv} kv heads — each kv head must "
                "serve the same whole number of q heads")

    def _align_gqa_local(self, q, k, v):
        """sp=1 / passthrough: the local core expects matched head counts,
        so native-width GQA kv repeats here (callers pass kv UN-repeated —
        the sp>1 reshard aligns on the wire instead)."""
        n_kv, H = k.shape[self.scatter_idx], q.shape[self.scatter_idx]
        if n_kv != H:
            self._check_gqa_heads(H, n_kv)
            rep = H // n_kv
            k = jnp.repeat(k, rep, axis=self.scatter_idx)
            v = jnp.repeat(v, rep, axis=self.scatter_idx)
        return k, v

    # ---- traced form: call inside shard_map; x are local blocks ------------
    def attend_local(self, q, k, v, **kwargs):
        a = self.sp_axis
        sp = jax.lax.axis_size(a)
        if sp == 1:
            k, v = self._align_gqa_local(q, k, v)
            return self.local_attn(q, k, v, **kwargs)
        H = q.shape[self.scatter_idx]
        hpad = (-H) % sp  # uneven heads: zero-pad to the next sp multiple
        if hpad:
            widths = [(0, 0)] * q.ndim
            widths[self.scatter_idx] = (0, hpad)
            q = jnp.pad(q, widths)
        # seq-sharded [B, S/sp, Hp, D] → head-sharded [B, S, Hp/sp, D]
        q = single_all_to_all(q, self.scatter_idx, self.gather_idx, a)
        k = self._kv_reshard(k, sp, H)
        v = self._kv_reshard(v, sp, H)
        out = self.local_attn(q, k, v, **kwargs)
        # back: head-sharded → seq-sharded (+ drop the padding heads)
        out = single_all_to_all(out, self.gather_idx, self.scatter_idx, a)
        if hpad:
            out = jax.lax.slice_in_dim(out, 0, H, axis=self.scatter_idx)
        return out

    def _kv_reshard(self, t, sp, n_q_heads):
        """KV reshard with GQA alignment (reference ``uneven_heads_all2all``,
        ``sequence/layer.py:72``).  Returns kv with exactly the head count
        the local (padded) q block has, so ``local_attn`` always sees
        matched heads:

        * both head counts divisible by sp → all-to-all like Q, then local
          group-repeat (contiguous head blocks keep q↔kv group alignment);
        * else → duplicate-then-route: build, from the local seq chunk, the
          [sp × qh_local] slot layout where slot (r, j) holds the kv head
          rank r's j-th q head attends to, and ONE all-to-all scatters the
          slot axis / gathers the sequence.  No rank ever materializes the
          full [B, S, n_kv, D] kv (the sequence-all-gather fallback this
          replaces); wire+memory cost equals the q path's."""
        n_kv = t.shape[self.scatter_idx]
        self._check_gqa_heads(n_q_heads, n_kv)
        group = max(1, n_q_heads // n_kv)  # q heads per kv head
        if n_kv % sp == 0 and n_q_heads % sp == 0:
            t = single_all_to_all(t, self.scatter_idx, self.gather_idx,
                                  self.sp_axis)
            if n_kv != n_q_heads:
                t = jnp.repeat(t, group, axis=self.scatter_idx)
            return t
        qh_local = -(-n_q_heads // sp)  # padded q heads per rank
        # slot (r, j) ← kv head of global (padded) q head r*qh_local + j;
        # padding q heads clamp to the last real head (their output is
        # sliced away).  Pure-Python index table → static gather.
        g = np.arange(sp * qh_local)
        kv_idx = np.minimum(g, n_q_heads - 1) // group
        t = jnp.take(t, jnp.asarray(kv_idx), axis=self.scatter_idx)
        return single_all_to_all(t, self.scatter_idx, self.gather_idx,
                                 self.sp_axis)

    # ---- eager/GSPMD form: global arrays, seq dim sp-sharded ---------------
    def __call__(self, query, key, value, mesh=None, **kwargs):
        if mesh is None:
            # inside another partial-manual region (e.g. the fused pipeline's
            # {pp,dp,ep}-manual program) the inner shard_map must target the
            # CONTEXT abstract mesh, not the concrete global mesh — enables
            # pp×sp (BASELINE config-5 shape)
            cur = jax.sharding.get_abstract_mesh()
            if getattr(cur, "manual_axes", ()):
                mesh = cur
            else:
                from ..utils import jax_compat
                if jax_compat.is_legacy_shard_map() and \
                        jax_compat.inside_axis_context():
                    # nested manual region on a jax without
                    # get_abstract_mesh: we cannot resolve the context mesh
                    # and the nested program CHECK-fails the legacy SPMD
                    # partitioner (native abort) — refuse cleanly
                    raise ValueError(
                        "DistributedAttention called inside a manual "
                        "shard_map region, but this legacy jax cannot "
                        "resolve the context abstract mesh — upgrade jax "
                        "for fused pp×sp, or run sp without pp")
                mesh = groups.get_global_mesh()
        a = self.sp_axis
        if mesh.shape.get(a, 1) == 1:
            key, value = self._align_gqa_local(query, key, value)
            return self.local_attn(query, key, value, **kwargs)
        key_ = (mesh, tuple(sorted(kwargs.items())))
        cache = getattr(self, "_jit_cache", None)
        if cache is None:
            cache = {}
            self._jit_cache = cache
        if key_ not in cache:
            # PARTIAL-manual: only "sp" is a manual axis (the a2a lives on
            # it); batch/head dims keep whatever dp/tp sharding GSPMD gave
            # the operands.  A full-manual region with P(None, a) specs
            # would replicate the batch into every dp group and the heads
            # into every tp rank — correct numerics, dp·tp× dead compute.
            spec = P(None, a)  # [B, S(sp), ...]; trailing dims auto

            def f(q, k, v):
                return self.attend_local(q, k, v, **kwargs)

            sm_kw = dict(mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)
            from ..utils import jax_compat
            if not jax_compat.is_legacy_shard_map():
                sm_kw["axis_names"] = frozenset({a})
            # else FULL-manual: the legacy partitioner CHECK-fails (native
            # abort) on manual-subgroup sharding, so eat the dead compute
            cache[key_] = jax.jit(jax.shard_map(f, **sm_kw))
        return cache[key_](query, key, value)


class UlyssesAttention(DistributedAttention):
    """Name parity with user-facing import in reference examples."""
