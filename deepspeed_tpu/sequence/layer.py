"""Ulysses sequence parallelism — TPU-native re-design of reference
``deepspeed/sequence/layer.py`` (``DistributedAttention`` ``:300``,
``_SeqAllToAll`` ``:245``, ``single_all_to_all`` ``:182``).

Semantics (identical to the reference): the transformer runs with the
**sequence** dimension sharded over the "sp" mesh axis; around attention, an
all-to-all re-shards from sequence-split to **head-split** (each rank holds
full sequence for H/sp heads), local attention runs, and the inverse
all-to-all restores sequence sharding.  On TPU both all-to-alls are
``jax.lax.all_to_all`` over the sp axis inside ``shard_map`` — XLA lays them
on ICI; gradients are handled by autodiff (all_to_all is its own transpose),
so no custom autograd.Function is needed.

GQA/uneven heads: the reference has ``uneven_heads_all2all`` (``:72``); here
heads must divide sp (asserted), and KV heads with n_kv < sp are *replicated*
gather-style — see ``_kv_reshard``.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import groups


def _default_attention(q, k, v, causal=True, softmax_scale=None, window=0):
    """Local attention core [B, S, H, D].  After the Ulysses a2a the
    sequence axis is global, so causal/sliding-window masks apply directly;
    one shared implementation with attention_core's XLA path."""
    from ..ops.attention import _xla_attention
    return _xla_attention(q, k, v, causal=causal,
                          softmax_scale=softmax_scale, window=window)


def single_all_to_all(x, scatter_idx, gather_idx, axis_name):
    """All-to-all inside a shard_map region (reference ``:182``): scatter
    ``scatter_idx`` across the axis, gather ``gather_idx``."""
    return jax.lax.all_to_all(x, axis_name, split_axis=scatter_idx,
                              concat_axis=gather_idx, tiled=True)


class DistributedAttention:
    """Reference ``DistributedAttention`` (``sequence/layer.py:300``).

    ``local_attention``: callable (q, k, v, **kw) -> out, operating on
    [B, S_full, H_local, D] blocks.  Call this object *inside* a shard_map (or
    GSPMD-jit via ``__call__`` on global arrays with an sp-sharded seq dim).
    """

    def __init__(self, local_attention=None, sequence_process_group=None,
                 scatter_idx=2, gather_idx=1, sp_axis=None):
        self.local_attn = local_attention or _default_attention
        self.sp_axis = sp_axis or groups.SP_AXIS
        self.scatter_idx = scatter_idx  # head dim of [B,S,H,D]
        self.gather_idx = gather_idx    # sequence dim

    # ---- traced form: call inside shard_map; x are local blocks ------------
    def attend_local(self, q, k, v, **kwargs):
        a = self.sp_axis
        sp = jax.lax.axis_size(a)
        if sp == 1:
            return self.local_attn(q, k, v, **kwargs)
        H = q.shape[self.scatter_idx]
        n_kv = k.shape[self.scatter_idx]
        # seq-sharded [B, S/sp, H, D] → head-sharded [B, S, H/sp, D]
        q = single_all_to_all(q, self.scatter_idx, self.gather_idx, a)
        k = self._kv_reshard(k, sp, H)
        v = self._kv_reshard(v, sp, H)
        out = self.local_attn(q, k, v, **kwargs)
        # back: head-sharded → seq-sharded
        return single_all_to_all(out, self.gather_idx, self.scatter_idx, a)

    def _kv_reshard(self, t, sp, n_q_heads):
        """KV reshard with GQA alignment (reference uneven-heads analog,
        ``sequence/layer.py:72``).  Returns kv with exactly the head count the
        local q block has (n_q_heads / sp), so ``local_attn`` always sees
        matched heads:

        * n_kv divisible by sp → all-to-all like Q, then local group-repeat
          (contiguous head blocks keep q↔kv group alignment);
        * else → all-gather the sequence (kv stays whole) and gather-select
          the kv heads serving this rank's q-head block."""
        n_kv = t.shape[self.scatter_idx]
        group = max(1, n_q_heads // n_kv)  # q heads per kv head
        qh_local = n_q_heads // sp
        if n_kv % sp == 0:
            t = single_all_to_all(t, self.scatter_idx, self.gather_idx,
                                  self.sp_axis)
            if n_kv != n_q_heads:
                t = jnp.repeat(t, group, axis=self.scatter_idx)
            return t
        # small-kv path: full kv heads on every rank
        t = jax.lax.all_gather(t, self.sp_axis, axis=self.gather_idx,
                               tiled=True)
        r = jax.lax.axis_index(self.sp_axis)
        local_q_global = r * qh_local + jnp.arange(qh_local)
        kv_idx = local_q_global // group
        return jnp.take(t, kv_idx, axis=self.scatter_idx)

    # ---- eager/GSPMD form: global arrays, seq dim sp-sharded ---------------
    def __call__(self, query, key, value, mesh=None, **kwargs):
        if mesh is None:
            # inside another partial-manual region (e.g. the fused pipeline's
            # {pp,dp,ep}-manual program) the inner shard_map must target the
            # CONTEXT abstract mesh, not the concrete global mesh — enables
            # pp×sp (BASELINE config-5 shape)
            cur = jax.sharding.get_abstract_mesh()
            mesh = (cur if getattr(cur, "manual_axes", ())
                    else groups.get_global_mesh())
        a = self.sp_axis
        if mesh.shape.get(a, 1) == 1:
            return self.local_attn(query, key, value, **kwargs)
        key_ = (mesh, tuple(sorted(kwargs.items())))
        cache = getattr(self, "_jit_cache", None)
        if cache is None:
            cache = {}
            self._jit_cache = cache
        if key_ not in cache:
            # PARTIAL-manual: only "sp" is a manual axis (the a2a lives on
            # it); batch/head dims keep whatever dp/tp sharding GSPMD gave
            # the operands.  A full-manual region with P(None, a) specs
            # would replicate the batch into every dp group and the heads
            # into every tp rank — correct numerics, dp·tp× dead compute.
            spec = P(None, a)  # [B, S(sp), ...]; trailing dims auto

            def f(q, k, v):
                return self.attend_local(q, k, v, **kwargs)

            cache[key_] = jax.jit(
                jax.shard_map(f, mesh=mesh, in_specs=(spec, spec, spec),
                              out_specs=spec, check_vma=False,
                              axis_names=frozenset({a})))
        return cache[key_](query, key, value)


class UlyssesAttention(DistributedAttention):
    """Name parity with user-facing import in reference examples."""
