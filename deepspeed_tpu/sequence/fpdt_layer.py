"""FPDT — Fully Pipelined Distributed Transformer for ~M-token contexts.

TPU rebuild of reference ``deepspeed/sequence/fpdt_layer.py``:
``update_out_and_lse`` (:58) online-softmax merge, ``SequenceChunk`` (:462)
host-offloaded KV residency, ``_FPDTGPUOffloadingAttentionImpl_`` (:510)
chunk-streamed attention, ``FPDT_FFN`` (:1056) and ``FPDT_LogitsLoss``
(:1137) chunked tails.

TPU-native design:

* **In-jit chunked attention** (`chunked_attention`) — a ``lax.scan`` over KV
  chunks per Q chunk with online softmax; each Q-chunk body is
  ``jax.checkpoint``-ed, so peak activation memory is O(q_chunk × kv_chunk)
  while XLA overlaps chunk DMA with MXU compute.  This is the trainable path:
  value_and_grad flows through the scan, recomputing chunks on the backward
  pass (the reference gets the same effect with manual autograd.Function
  bookkeeping).
* **Host KV streaming** (`FPDTHostOffloadAttention`) — the reference's GPU↔CPU
  chunk round-trip (:462-510) maps to arrays pinned in host memory via
  ``jax.device_put(..., memory_kind="pinned_host")``; decode/eval appends KV
  chunks host-side and streams them through the merge kernel one at a time,
  bounding HBM by one chunk regardless of context length.
* Ulysses composition: apply ``DistributedAttention``'s a2a head↔sequence
  reshard first, then chunk the local attention — matching the reference's
  FPDT-on-Ulysses layering (FPDT_Attention :971 wraps the a2a).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.logging import logger

NEG_INF = -1e30


# ------------------------------------------------------------- online softmax
def update_out_and_lse(out, lse, new_out, new_lse):
    """Merge a new chunk's attention output into the running (out, lse)
    accumulator (reference fpdt_layer.py:58).

    out:  [B, Sq, H, D] fp32 running numerator/denominator-normalized output
    lse:  [B, Sq, H]    fp32 running log-sum-exp
    """
    max_lse = jnp.maximum(lse, new_lse)
    w_old = jnp.exp(lse - max_lse)
    w_new = jnp.exp(new_lse - max_lse)
    denom = w_old + w_new
    merged = (out * (w_old / denom)[..., None] +
              new_out * (w_new / denom)[..., None])
    merged_lse = max_lse + jnp.log(denom)
    return merged, merged_lse


def _chunk_attend(q, k, v, mask=None, softmax_scale=None):
    """Attention of one (q-chunk, kv-chunk) pair returning (out, lse), both
    fp32.  q: [B, Sq, H, D]; k,v: [B, Sk, H, D]; mask: [Sq, Sk] bool or None."""
    D = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else D**-0.5
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    lse = jax.nn.logsumexp(logits, axis=-1)              # [B, H, Sq]
    probs = jnp.exp(logits - lse[..., None])
    out = jnp.einsum("bhst,bthd->bshd", probs.astype(v.dtype), v)
    # [B,H,Sq] → [B,Sq,H]
    return out.astype(jnp.float32), jnp.transpose(lse, (0, 2, 1))


def chunked_attention(q, k, v, q_chunk=1024, kv_chunk=1024, causal=True,
                      softmax_scale=None):
    """Flash-style chunked attention entirely under jit.

    [B, S, H, D] → [B, S, H, D]; memory O(q_chunk × kv_chunk) instead of
    O(S²).  Q-chunk bodies are rematerialized on backward."""
    B, S, H, D = q.shape
    Sk = k.shape[1]
    q_chunk = min(q_chunk, S)
    kv_chunk = min(kv_chunk, Sk)
    if S % q_chunk or Sk % kv_chunk:
        # fall back to one chunk when shapes don't tile (tiny tests)
        q_chunk = S if S % q_chunk else q_chunk
        kv_chunk = Sk if Sk % kv_chunk else kv_chunk
    nq, nk = S // q_chunk, Sk // kv_chunk

    kc = k.reshape(B, nk, kv_chunk, H, D)
    vc = v.reshape(B, nk, kv_chunk, H, D)

    def one_q_chunk(qi, q_blk):
        """q_blk: [B, q_chunk, H, D] → attended output."""
        q_start = qi * q_chunk

        def body(carry, inputs):
            out, lse = carry
            ki, k_blk, v_blk = inputs
            k_start = ki * kv_chunk

            def attend(carry):
                out, lse = carry
                if causal:
                    rows = q_start + jnp.arange(q_chunk)[:, None]
                    cols = k_start + jnp.arange(kv_chunk)[None, :]
                    mask = rows >= cols
                else:
                    mask = None
                new_out, new_lse = _chunk_attend(q_blk, k_blk, v_blk,
                                                 mask=mask,
                                                 softmax_scale=softmax_scale)
                return update_out_and_lse(out, lse, new_out, new_lse)

            if causal:
                # Chunks entirely above the diagonal are fully masked: skip
                # both einsums (halves the O(S²) work at FPDT's scales).
                live = k_start <= q_start + q_chunk - 1
                out, lse = jax.lax.cond(live, attend, lambda c: c, (out, lse))
            else:
                out, lse = attend((out, lse))
            return (out, lse), None

        init = (jnp.zeros((B, q_chunk, H, D), jnp.float32),
                jnp.full((B, q_chunk, H), NEG_INF, jnp.float32))
        ks = jnp.arange(nk)
        (out, lse), _ = jax.lax.scan(
            jax.checkpoint(body),
            init, (ks, jnp.moveaxis(kc, 1, 0), jnp.moveaxis(vc, 1, 0)))
        return out.astype(q.dtype)

    qcs = q.reshape(B, nq, q_chunk, H, D)
    outs = jax.lax.map(lambda args: one_q_chunk(args[0], args[1]),
                       (jnp.arange(nq), jnp.moveaxis(qcs, 1, 0)))
    # outs: [nq, B, q_chunk, H, D] → [B, S, H, D]
    return jnp.moveaxis(outs, 0, 1).reshape(B, S, H, D)


# ------------------------------------------------------------- host offload
def _host_sharding():
    """TransferToHost target: a pinned-host sharding on TPU, None elsewhere."""
    dev = jax.local_devices()[0]
    try:
        if "pinned_host" in [m.kind for m in dev.addressable_memories()]:
            return jax.sharding.SingleDeviceSharding(
                dev, memory_kind="pinned_host")
    except Exception:
        pass
    return None


class SequenceChunk:
    """One KV chunk resident in host memory (reference SequenceChunk :462)."""

    def __init__(self, k, v, offload=True):
        tgt = _host_sharding() if offload else None
        if tgt is not None:
            self.k = jax.device_put(k, tgt)
            self.v = jax.device_put(v, tgt)
        else:
            self.k, self.v = k, v
        self.length = k.shape[1]

    def fetch(self):
        """Bring the chunk back to default device memory."""
        dev = jax.local_devices()[0]
        tgt = jax.sharding.SingleDeviceSharding(dev, memory_kind="device")
        return jax.device_put(self.k, tgt), jax.device_put(self.v, tgt)


class FPDTHostOffloadAttention:
    """Streaming attention over host-resident KV chunks (reference
    _FPDTGPUOffloadingAttentionImpl_ :510).  Append-only KV (decode/eval):
    HBM holds ≤ 2 chunks at a time (current + prefetch); context length is
    bounded by host RAM.

    ``double_buffer`` (default on) software-pipelines the stream the way
    the reference's ``general_offloading`` double-buffers cudaMemcpyAsync
    (fpdt_layer.py:462-560): chunk i+1's H2D transfer is ISSUED before
    chunk i's merge is dispatched, so the transfer rides the DMA engine
    while the MXU runs the merge — without it, dispatch order makes the
    transfer eligible only after the merge is enqueued."""

    def __init__(self, chunk_size=4096, softmax_scale=None, offload=True,
                 double_buffer=True):
        self.chunk_size = chunk_size
        self.softmax_scale = softmax_scale
        self.offload = offload
        self.double_buffer = double_buffer
        self.chunks = []

        # ONE compiled merge serves both the streamed chunks (causal=False:
        # every stored chunk is entirely in the past) and the current
        # block's causal tail.  The O(chunk²) score temp and the causal
        # mask live inside XLA, bounded by the chunk size —
        # context-independent, no mask operand.
        def merge(q, k, v, out, lse, scale, causal):
            mask = (jnp.arange(q.shape[1])[:, None] >=
                    jnp.arange(k.shape[1])[None, :]) if causal else None
            return update_out_and_lse(
                out, lse, *_chunk_attend(q, k, v, mask=mask,
                                         softmax_scale=scale))

        self._merge = jax.jit(merge, static_argnums=(6, ))

    def append_kv(self, k, v):
        """Store a [B, S_chunk, H, D] KV block host-side."""
        self.chunks.append(SequenceChunk(k, v, offload=self.offload))

    def reset(self):
        self.chunks = []

    @property
    def context_length(self):
        return sum(c.length for c in self.chunks)

    def attend(self, q, k_new=None, v_new=None, causal_tail=True):
        """Attend q [B, Sq, H, D] over all stored chunks (+ the current
        block, causally masked).  Appends (k_new, v_new) afterwards."""
        B, Sq, H, D = q.shape
        out = jnp.zeros((B, Sq, H, D), jnp.float32)
        lse = jnp.full((B, Sq, H), NEG_INF, jnp.float32)
        scale = self.softmax_scale if self.softmax_scale is not None else D**-0.5
        if self.double_buffer and self.chunks:
            # prefetch-ahead pipeline: kick chunk i+1's H2D before merging
            # chunk i, keeping ≤ 2 chunks device-resident
            fetched = self.chunks[0].fetch()
            for i in range(len(self.chunks)):
                nxt = (self.chunks[i + 1].fetch()
                       if i + 1 < len(self.chunks) else None)
                out, lse = self._merge(q, *fetched, out, lse, scale, False)
                fetched = nxt
        else:
            for chunk in self.chunks:
                k, v = chunk.fetch()
                out, lse = self._merge(q, k, v, out, lse, scale, False)
        if k_new is not None:
            # current block attends (causally) to itself — jitted, mask
            # built in-program
            out, lse = self._merge(q, k_new, v_new, out, lse, scale,
                                   bool(causal_tail))
            self.append_kv(k_new, v_new)
        return out.astype(q.dtype)


# ------------------------------------------------------------ chunked tails
def fpdt_ffn(ffn_fn, x, chunk_size=4096):
    """Chunked FFN over the sequence dim (reference FPDT_FFN :1056): the
    [B, S, H] block is processed in S/chunk slabs under ``lax.map`` with
    remat, so the FFN intermediate (4H) never materializes for the full
    sequence."""
    B, S, H = x.shape
    cs = min(chunk_size, S)
    if S % cs:
        return ffn_fn(x)
    n = S // cs
    xs = jnp.moveaxis(x.reshape(B, n, cs, H), 1, 0)
    ys = jax.lax.map(jax.checkpoint(ffn_fn), xs)
    return jnp.moveaxis(ys, 0, 1).reshape(B, S, -1)


def fpdt_logits_loss(hidden, vocab_kernel, labels, chunk_size=4096,
                     reduction="mean"):
    """Chunked LM cross-entropy (reference FPDT_LogitsLoss :1137): computes
    softmax-CE slab by slab so the [S, V] logits tensor never exists."""
    B, S, H = hidden.shape
    V = vocab_kernel.shape[-1]
    cs = min(chunk_size, S)
    if S % cs:
        cs = S
    n = S // cs
    hs = jnp.moveaxis(hidden.reshape(B, n, cs, H), 1, 0)
    ls = jnp.moveaxis(labels.reshape(B, n, cs), 1, 0)

    def slab(args):
        h, lab = args
        logits = (h @ vocab_kernel).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lab[..., None], axis=-1)[..., 0]
        return logz - gold

    losses = jax.lax.map(jax.checkpoint(slab), (hs, ls))  # [n, B, cs]
    losses = jnp.moveaxis(losses, 0, 1).reshape(B, S)
    if reduction == "none":
        return losses
    return jnp.mean(losses)


# ---------------------------------------------------------------- FPDT layer
class FPDT_Attention:
    """Ulysses + chunked attention (reference FPDT_Attention :971).

    Call on [B, S_global(sp-sharded), H, D] arrays; the a2a reshards
    sequence↔heads, then local attention runs chunked."""

    def __init__(self, q_chunk=1024, kv_chunk=1024, causal=True,
                 softmax_scale=None, sp_axis=None):
        from .layer import DistributedAttention
        self.q_chunk = q_chunk
        self.kv_chunk = kv_chunk
        local = functools.partial(chunked_attention, q_chunk=q_chunk,
                                  kv_chunk=kv_chunk, causal=causal,
                                  softmax_scale=softmax_scale)
        self.dist = DistributedAttention(local_attention=local,
                                         sp_axis=sp_axis)

    def __call__(self, q, k, v, **kw):
        return self.dist(q, k, v, **kw)

    def attend_local(self, q, k, v, **kw):
        return self.dist.attend_local(q, k, v, **kw)
