from .layer import DistributedAttention, UlyssesAttention, single_all_to_all
from .ring_attention import RingAttention, ring_attention_local
from .cross_entropy import vocab_sequence_parallel_cross_entropy
from .fpdt_layer import (FPDT_Attention, FPDTHostOffloadAttention,
                         SequenceChunk, chunked_attention, fpdt_ffn,
                         fpdt_logits_loss, update_out_and_lse)
