"""Sequence-parallel cross entropy — analog of reference
``deepspeed/sequence/cross_entropy.py:11`` (vocab_sequence_parallel_cross_entropy).

With the sequence dim sharded over sp, each rank computes CE over its local
tokens; the mean over the full sequence is a psum.  Usable inside shard_map
(axis-name form) or on global arrays (GSPMD handles the reduction).
"""

import jax
import jax.numpy as jnp


def softmax_cross_entropy_with_logits(logits, labels):
    """[.., V] logits, [..] int labels → [..] per-token loss (stable)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return logz - gold


def fused_linear_cross_entropy(x, w, labels, chunk_size, logit_dtype=None):
    """CE of ``x @ w`` against ``labels`` WITHOUT materializing the [N, V]
    logits — the TPU answer to the reference's chunked logits loss
    (``deepspeed/sequence/fpdt_layer.py:1137`` FPDT_LogitsLoss chunks the
    sequence; here the vocab dim is chunked, which also removes the [N, V]
    fp32 softmax intermediate from the backward pass).

    ``x``: [N, D] hidden states (head dtype), ``w``: [D, V] head kernel,
    ``labels``: [N] int32.  Returns [N] fp32 per-token loss.

    A ``lax.scan`` runs an online logsumexp over vocab chunks; the body is
    ``jax.checkpoint``-ed so backward recomputes each chunk's logits —
    peak live logits are [N, chunk_size] instead of [N, V] in BOTH passes.
    The extra head-matmul recompute is ~2·N·D·V flops; the saving is the
    [N, V] fp32 round-trips to HBM, which at V≳32k dominate and otherwise
    force gradient checkpointing (lower MFU) at batch sizes that would
    fit without them.
    """
    n, d = x.shape
    v = w.shape[1]
    chunk_size = int(min(chunk_size, v))
    n_chunks = -(-v // chunk_size)
    if v % chunk_size:
        # pad once so every scan step slices a full chunk; padded columns
        # are masked to -inf below and contribute exp(-inf)=0
        w = jnp.pad(w, ((0, 0), (0, n_chunks * chunk_size - v)))
    ld = jnp.dtype(logit_dtype) if logit_dtype is not None else x.dtype
    xc = x.astype(ld)

    def body(carry, c):
        m, s, gold = carry
        base = c * chunk_size
        wc = jax.lax.dynamic_slice_in_dim(w, base, chunk_size, axis=1)
        logits = (xc @ wc.astype(ld)).astype(jnp.float32)  # [N, chunk]
        col = base + jnp.arange(chunk_size)
        logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        in_chunk = (labels >= base) & (labels < base + chunk_size)
        idx = jnp.clip(labels - base, 0, chunk_size - 1)
        g = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        gold = jnp.where(in_chunk, g, gold)
        return (m_new, s, gold), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, gold), _ = jax.lax.scan(jax.checkpoint(body), init,
                                   jnp.arange(n_chunks))
    return m + jnp.log(s) - gold


def vocab_sequence_parallel_cross_entropy(logits, labels, sp_axis=None,
                                          reduction="mean"):
    """Per-token CE; if called inside shard_map with ``sp_axis`` given, the
    mean reduces over the global sequence via pmean."""
    loss = softmax_cross_entropy_with_logits(logits, labels)
    if reduction == "none":
        return loss
    local = jnp.mean(loss)
    if sp_axis is not None:
        try:
            local = jax.lax.pmean(local, sp_axis)
        except NameError:
            pass
    return local
