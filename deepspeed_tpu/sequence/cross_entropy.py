"""Sequence-parallel cross entropy — analog of reference
``deepspeed/sequence/cross_entropy.py:11`` (vocab_sequence_parallel_cross_entropy).

With the sequence dim sharded over sp, each rank computes CE over its local
tokens; the mean over the full sequence is a psum.  Usable inside shard_map
(axis-name form) or on global arrays (GSPMD handles the reduction).
"""

import jax
import jax.numpy as jnp


def softmax_cross_entropy_with_logits(logits, labels):
    """[.., V] logits, [..] int labels → [..] per-token loss (stable)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return logz - gold


def vocab_sequence_parallel_cross_entropy(logits, labels, sp_axis=None,
                                          reduction="mean"):
    """Per-token CE; if called inside shard_map with ``sp_axis`` given, the
    mean reduces over the global sequence via pmean."""
    loss = softmax_cross_entropy_with_logits(logits, labels)
    if reduction == "none":
        return loss
    local = jnp.mean(loss)
    if sp_axis is not None:
        try:
            local = jax.lax.pmean(local, sp_axis)
        except NameError:
            pass
    return local
