"""Ring attention — context-parallel long-sequence backend.

The reference's sequence parallelism is Ulysses (all-to-all head↔sequence
reshard, ``sequence/layer.py``) + FPDT chunking; it has no ring/blockwise CP
(SURVEY.md §2.3).  On TPU a ring is the natural *additional* backend: K/V
blocks rotate around the "sp" mesh axis via ``ppermute`` (neighbor ICI hops,
bandwidth-optimal, overlapping compute), and each rank folds every block into
its local queries with the flash-attention online-softmax recurrence — the
S×S score matrix never exists, activation memory is O(S/sp), and unlike
Ulysses the head count does NOT need to divide sp (MQA/GQA-friendly).

Math (blockwise softmax rescaling) follows the published RingAttention /
blockwise-parallel-transformer formulation; gradients fall out of AD through
``lax.scan`` + ``ppermute``.
"""

import jax
import jax.numpy as jnp

_NEG_INF = float("-inf")


def ring_attention_local(q, k, v, axis_name, causal=True, softmax_scale=None):
    """Inside-shard_map ring attention.

    q/k/v: local sequence shards [B, S_local, H(_kv), D]; returns
    [B, S_local, H, D].  K/V circulate sp-1 hops; block (i) on rank r at step
    t originated at rank (r - t) mod sp, which fixes the causal-mask offsets.
    """
    sp = jax.lax.axis_size(axis_name)
    r = jax.lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    scale = softmax_scale if softmax_scale is not None else D ** -0.5

    n_kv = k.shape[2]
    if n_kv != H:  # GQA/MQA: local repeat (no cross-rank constraint)
        rep = H // n_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    q32 = q.astype(jnp.float32)
    q_pos = r * Sl + jnp.arange(Sl)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def fold(k_cur, v_cur, src, m, l, acc):
        """Online-softmax accumulation of one K/V block."""
        s = jnp.einsum("bshd,bthd->bhst", q32,
                       k_cur.astype(jnp.float32)) * scale  # [B,H,Sl,Sl]
        if causal:
            k_pos = src * Sl + jnp.arange(Sl)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))          # [B,H,Sl]
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        alpha = jnp.where(m == _NEG_INF, 0.0, jnp.exp(m - m_safe))
        l = alpha * l + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhst,bthd->bhsd", p, v_cur.astype(jnp.float32))
        return m_new, l, acc

    # local block first (no hop), then rotate-and-fold the remaining sp-1
    # blocks — exactly sp-1 neighbor hops (a trailing rotate whose result is
    # discarded would move two full K/V blocks per layer for nothing)
    m0 = jnp.full((B, H, Sl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sl), jnp.float32)
    acc0 = jnp.zeros((B, H, Sl, D), jnp.float32)
    m0, l0, acc0 = fold(k, v, r, m0, l0, acc0)

    def step(carry, t):
        k_cur, v_cur, m, l, acc = carry
        # one ICI hop; XLA overlaps the permute with this step's matmuls
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        m, l, acc = fold(k_cur, v_cur, (r - t) % sp, m, l, acc)
        return (k_cur, v_cur, m, l, acc), None

    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(1, sp))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)      # [B, Sl, H, D]


from .layer import DistributedAttention


class RingAttention(DistributedAttention):
    """API twin of :class:`deepspeed_tpu.sequence.DistributedAttention` with
    the ring backend: the GSPMD ``__call__`` wrapper (mesh lookup, sp==1
    fallback, jit/shard_map cache) is inherited; only the inside-shard_map
    body differs."""

    def attend_local(self, q, k, v, causal=True, softmax_scale=None):
        sp = jax.lax.axis_size(self.sp_axis)
        if sp == 1:
            return self.local_attn(q, k, v, causal=causal,
                                   softmax_scale=softmax_scale)
        return ring_attention_local(q, k, v, self.sp_axis, causal=causal,
                                    softmax_scale=softmax_scale)
