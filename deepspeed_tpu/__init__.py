"""deepspeed_tpu — a TPU-native framework with DeepSpeed's capabilities.

Brand-new design (not a port): JAX/XLA/pjit/Pallas compute path over a global
``jax.sharding.Mesh``; ZeRO = sharding policies; comm = mesh collectives.
Public API mirrors the reference's ``deepspeed/__init__.py`` surface
(``initialize`` at reference ``deepspeed/__init__.py:69``, ``init_inference``
at ``:291``, ``add_config_arguments`` at ``:268``).
"""

__version__ = "0.5.0"   # keep in sync with version.txt (setup.py reads it)
# __git_branch__/git_hash/git_branch resolve lazily from the checkout (see
# __getattr__); "unknown" outside a git checkout
__git_branch__ = "unknown"

# must run before anything touches jax.shard_map: the pinned 0.4.x jaxlib
# only ships the experimental spelling (see utils/jax_compat.py)
from .utils import jax_compat as _jax_compat
_jax_compat.install()

from . import comm
from . import utils
from .accelerator import get_accelerator
from .utils.logging import logger, log_dist

dist = comm


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port=29500,
               mesh_param=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               mpu=None,
               config_params=None,
               tp_rules=None):
    """Build the training engine.

    Reference ``deepspeed/__init__.py:69``.  Returns
    ``(engine, optimizer, training_dataloader, lr_scheduler)``.

    TPU-native signature differences:
      * ``model`` is a flax ``nn.Module``, haiku transform, or a plain apply
        callable ``f(params, batch, rngs) -> output``;
      * ``model_parameters`` is the parameter pytree (or ``None`` to let the
        engine initialize from ``model.init``);
      * ``mpu``/``mesh_param`` configure the (pp, dp, sp, tp) mesh factoring.
    """
    from .runtime.engine import DeepSpeedEngine
    from .runtime.config import DeepSpeedConfig
    from .runtime.pipe.module import PipelineModule

    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)

    ds_config = DeepSpeedConfig(config, mesh_param=mesh_param)

    _offload_param_dev = (str(ds_config.zero_config.offload_param.device)
                          if ds_config.zero_config.offload_param is not None
                          else "none")
    if isinstance(model, PipelineModule):
        if _offload_param_dev in ("cpu", "nvme"):
            raise ValueError(
                "offload_param (ZeRO-Infinity param streaming) does not "
                "compose with PipelineModule — the fused pipeline program "
                "needs its stage weights resident; use offload_optimizer "
                "for state offload under pipeline parallelism")
        from .runtime.pipe.engine import PipelineEngine  # noqa
        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                collate_fn=collate_fn,
                                config=ds_config,
                                mpu=mpu)
    elif _offload_param_dev in ("cpu", "nvme"):
        # ZeRO-Infinity param streaming (reference engine choice: stage-3
        # offload_param routes through DeepSpeedZeroOptimizer_Stage3 +
        # AsyncPartitionedParameterSwapper)
        from .runtime.infinity_engine import InfinityEngine
        engine = InfinityEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                collate_fn=collate_fn,
                                config=ds_config,
                                mpu=mpu,
                                tp_rules=tp_rules)
    elif ds_config.hybrid_engine.enabled:
        # RLHF flip-flop engine (reference engine choice deepspeed/__init__.py:214)
        from .runtime.hybrid_engine import DeepSpeedHybridEngine
        engine = DeepSpeedHybridEngine(args=args,
                                       model=model,
                                       optimizer=optimizer,
                                       model_parameters=model_parameters,
                                       training_data=training_data,
                                       lr_scheduler=lr_scheduler,
                                       collate_fn=collate_fn,
                                       config=ds_config,
                                       mpu=mpu,
                                       tp_rules=tp_rules)
    else:
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 collate_fn=collate_fn,
                                 config=ds_config,
                                 mpu=mpu,
                                 tp_rules=tp_rules)

    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model, config=None, **kwargs):
    """Reference ``deepspeed/__init__.py:291``."""
    from .inference.engine import InferenceEngine
    from .inference.config import DeepSpeedInferenceConfig
    if config is None:
        config = {}
    if isinstance(config, DeepSpeedInferenceConfig):
        if kwargs:
            # merge explicit kwargs over the config object (reference
            # init_inference rejects double-specification; we apply overrides)
            merged = config.model_dump()
            merged.update(kwargs)
            config = DeepSpeedInferenceConfig(**merged)
        ds_inference_config = config
    else:
        config = dict(config)
        config.update(kwargs)
        ds_inference_config = DeepSpeedInferenceConfig(**config)
    return InferenceEngine(model, config=ds_inference_config)


def add_config_arguments(parser):
    """Reference ``deepspeed/__init__.py:268`` — argparse plumbing."""
    group = parser.add_argument_group("DeepSpeed-TPU",
                                      "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag for config)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    return parser


def argparse_suppress():
    import argparse
    return argparse.SUPPRESS


def default_inference_config():
    """Default DeepSpeedInferenceConfig as a dict (reference
    ``deepspeed/__init__.py:284``)."""
    from .inference.config import DeepSpeedInferenceConfig
    return DeepSpeedInferenceConfig().model_dump()


def is_compile_supported():
    """Reference ``runtime/compiler.py`` — torch.compile availability.  On
    TPU every engine step is already XLA-compiled; always True."""
    return True


# lazy conveniences mirroring the reference's top-level namespace
def __getattr__(name):
    if name == "OnDevice":
        from .utils.init_on_device import OnDevice
        return OnDevice
    if name in ("DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig"):
        from .ops import transformer
        return getattr(transformer, name)
    if name in ("PipelineModule", "LayerSpec", "TiedLayerSpec"):
        from .runtime import pipe
        return getattr(pipe, name)
    if name == "DeepSpeedEngine":
        from .runtime.engine import DeepSpeedEngine
        return DeepSpeedEngine
    if name == "InferenceEngine":
        from .inference.engine import InferenceEngine
        return InferenceEngine
    if name == "DeepSpeedConfig":
        from .runtime.config import DeepSpeedConfig
        return DeepSpeedConfig
    if name in ("replace_transformer_layer", "revert_transformer_layer"):
        from . import module_inject
        return getattr(module_inject, name)
    if name == "zero":
        from .runtime import zero
        return zero
    if name == "init_distributed":
        # reference deepspeed.init_distributed (deepspeed/__init__.py)
        return comm.init_distributed
    if name in ("add_tuning_arguments", "get_config_from_args"):
        from .runtime import lr_schedules
        return getattr(lr_schedules, name)
    if name == "checkpointing":
        # reference deepspeed.checkpointing module alias
        from .runtime.activation_checkpointing import checkpointing
        return checkpointing
    if name == "ops":
        # NOT `from . import ops`: inside the package's own __getattr__
        # that spelling re-enters this function before sys.modules is
        # populated and recurses
        import importlib
        return importlib.import_module(".ops", __name__)
    if name in ("git_hash", "git_branch"):
        # reference bakes these at build; derive lazily from the checkout
        # and memoize (PEP 562: the globals() write makes later accesses
        # bypass __getattr__ — no subprocess per read)
        import os as _os
        import subprocess
        root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        out = {"git_hash": "unknown", "git_branch": "unknown"}
        if _os.path.isdir(_os.path.join(root, ".git")):
            # only trust git when THIS checkout is the repo — a
            # pip-installed copy inside someone else's repository must not
            # report their HEAD
            for key, arg in (("git_hash", ("rev-parse", "--short", "HEAD")),
                             ("git_branch",
                              ("rev-parse", "--abbrev-ref", "HEAD"))):
                try:
                    out[key] = subprocess.check_output(
                        ("git", "-C", root) + arg, text=True,
                        stderr=subprocess.DEVNULL).strip()
                except Exception:
                    pass
        globals().update(out)
        globals()["__git_branch__"] = out["git_branch"]
        return out[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
