"""LoRA / quantization configs (reference ``deepspeed/linear/config.py``)."""

from dataclasses import dataclass, field
from typing import List


@dataclass
class LoRAConfig:
    """Reference ``linear/config.py:11`` — same fields/defaults."""
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    offload: bool = False
    offload_ratio: float = 0.0
    delay_lora_init: bool = False
    target_mods: List[str] = field(default_factory=lambda: [
        'q_proj', 'k_proj', 'v_proj', 'o_proj', 'gate_proj', 'up_proj',
        'down_proj'
    ])


@dataclass
class QuantizationConfig:
    """Reference ``linear/config.py:37`` (+ ``q_dtype`` selecting the int
    blockwise kernels vs the FP6-LLM-style float formats)."""
    q_bits: int = 8
    mantissa_bits: int = 3
    group_size: int = 512
    q_dtype: str = "int"  # "int" (blockwise int8/4) | "fp" (e4m3/e3m2/e4m7)
