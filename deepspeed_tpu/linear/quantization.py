"""Quantized parameter container (reference ``linear/quantization.py``
``QuantizedParameter``): weights stored int8 + per-group scales, dequantized
on use.  Uses the blockwise quantizer kernel (``ops/pallas/quantizer``)."""

import jax.numpy as jnp

from ..ops.pallas.quantizer import dequantize_blockwise, quantize_blockwise
from .config import QuantizationConfig


class QuantizedParameter:
    """Host-side container: ``quantize`` once, ``dequantized()`` per use.
    2× (int8) memory saving on frozen base weights."""

    def __init__(self, data, quant_config: QuantizationConfig = None):
        self.quant_config = quant_config or QuantizationConfig()
        self.q, self.scales, self.meta = quantize_blockwise(
            jnp.asarray(data), num_bits=self.quant_config.q_bits,
            group_size=self.quant_config.group_size)

    def dequantized(self):
        return dequantize_blockwise(self.q, self.scales, self.meta)

    @property
    def shape(self):
        return self.meta[0]


def quantize_param_tree(tree, quant_config=None, predicate=None):
    """Quantize matching leaves of a pytree into QuantizedParameter holders."""
    import jax

    def q(x):
        if predicate is not None and not predicate(x):
            return x
        if getattr(x, "ndim", 0) < 2:
            return x
        return QuantizedParameter(x, quant_config)

    return jax.tree_util.tree_map(q, tree)
