"""Quantized parameter container (reference ``linear/quantization.py``
``QuantizedParameter``): weights stored quantized + per-group scales,
dequantized on use.

Formats (reference parametrization ``q_bits``/``mantissa_bits``):
  * ``q_dtype="int"`` — symmetric int8/int4 via the Pallas blockwise
    quantizer (``ops/pallas/quantizer``);
  * ``q_dtype="fp"`` — FP8 e4m3 / FP6 e3m2 / FP12 via ``ops/fp_quantizer``
    (FP6-LLM-style weight-only quant, reference ``csrc/fp_quantizer``):
    6-bit weights pack 4→3 bytes → 0.75 B/value.
"""

import jax.numpy as jnp

from ..ops.fp_quantizer import dequantize_fp, quantize_fp
from ..ops.pallas.quantizer import dequantize_blockwise, quantize_blockwise
from .config import QuantizationConfig


class QuantizedParameter:
    """Host-side container: ``quantize`` once, ``dequantized()`` per use.
    2× (int8) / 2.7× (fp6) memory saving on frozen base weights."""

    # canonical mantissa widths (must agree with
    # comm/collectives/quantized.py _FP_FORMATS): fp8 =
    # e4m3, fp6 = e3m2 (FP6-LLM), fp12 = e4m7.  The config's mantissa_bits
    # (default 3) applies to 8-bit; narrower formats use their canonical
    # layout or packed buffers would decode under the wrong bit split.
    _CANONICAL_MANTISSA = {6: 2, 12: 7}

    def __init__(self, data, quant_config: QuantizationConfig = None):
        self.quant_config = quant_config or QuantizationConfig()
        cfg = self.quant_config
        self._fp = getattr(cfg, "q_dtype", "int") == "fp" or cfg.q_bits in (6, 12)
        if self._fp:
            mantissa = self._CANONICAL_MANTISSA.get(cfg.q_bits,
                                                    cfg.mantissa_bits)
            self.q, self.scales, self.meta = quantize_fp(
                jnp.asarray(data), q_bits=cfg.q_bits,
                mantissa_bits=mantissa, group_size=cfg.group_size)
        else:
            self.q, self.scales, self.meta = quantize_blockwise(
                jnp.asarray(data), num_bits=cfg.q_bits,
                group_size=cfg.group_size)

    def dequantized(self):
        if self._fp:
            return dequantize_fp(self.q, self.scales, self.meta)
        return dequantize_blockwise(self.q, self.scales, self.meta)

    @property
    def shape(self):
        return self.meta[0]


def quantize_param_tree(tree, quant_config=None, predicate=None):
    """Quantize matching leaves of a pytree into QuantizedParameter holders."""
    import jax

    def q(x):
        if predicate is not None and not predicate(x):
            return x
        if getattr(x, "ndim", 0) < 2:
            return x
        return QuantizedParameter(x, quant_config)

    return jax.tree_util.tree_map(q, tree)
