"""deepspeed_tpu.linear (reference ``deepspeed/linear/``): OptimizedLinear
(QLoRA-style sharded/quantized base + LoRA adapters), LoRAConfig,
QuantizationConfig."""

from .config import LoRAConfig, QuantizationConfig
from .optimized_linear import (OptimizedLinear, init_lora, merge_lora,
                               unmerge_lora)
from .quantization import QuantizedParameter, quantize_param_tree
