"""OptimizedLinear (reference ``linear/optimized_linear.py:18``): a linear
layer for memory-efficient fine-tuning — frozen base weight, optionally
int8-quantized and sharded over the dp mesh axis, plus trainable LoRA
adapters ``y = x·W + (alpha/r)·(x·A)·B``.

TPU-native shape: a flax module whose base kernel carries a dp sharding
constraint (the "base_weight_sharding" of the reference becomes a
NamedSharding over the zero axes — XLA gathers on use), and whose quantized
variant fake-quantizes through the blockwise kernel with a straight-through
cast (the base is frozen, so no gradient flows there anyway).

``deepspeed_tpu.linear.init_lora`` offers the functional path: split an
existing param tree into (frozen base, trainable lora) and a merged apply.
"""

import math

import jax
import jax.numpy as jnp
import flax.linen as nn

from ..compression.quantizers import fake_quantize
from .config import LoRAConfig, QuantizationConfig


class OptimizedLinear(nn.Module):
    """Drop-in linear; LoRA + optional weight quantization.

    Reference semantics (``LoRAOptimizedLinear.forward``): base frozen via
    ``stop_gradient``; adapters initialized (A: he-uniform, B: zeros) so the
    initial output equals the base linear.
    """
    output_dim: int
    lora_config: LoRAConfig = None
    quantization_config: QuantizationConfig = None
    bias: bool = False
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x):
        cfg = self.lora_config or LoRAConfig()
        in_dim = x.shape[-1]
        dtype = jnp.dtype(self.dtype)
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (in_dim, self.output_dim), jnp.float32)
        if self.quantization_config is not None:
            qc = self.quantization_config
            kernel = fake_quantize(kernel, qc.q_bits, True,
                                   max(1, kernel.size // qc.group_size))
        base = jax.lax.stop_gradient(kernel)  # frozen base
        # base-weight sharding over the ZeRO/dp axes when a mesh is live
        from ..utils import groups
        if groups.mesh_is_initialized() and cfg.base_weight_sharding > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..runtime.zero.partition import shard_spec
            mesh = groups.get_global_mesh()
            spec = shard_spec(base.shape, mesh, groups.dp_axes())
            try:
                base = jax.lax.with_sharding_constraint(
                    base, NamedSharding(mesh, spec))
            except Exception as e:
                # a silently-replicated base defeats the memory saving the
                # user configured — make the failure visible
                from ..utils.logging import logger
                logger.warning(
                    "OptimizedLinear: base_weight_sharding constraint "
                    f"failed ({e}); base weight is replicated")
        out = x.astype(dtype) @ base.astype(dtype)

        lora_a = self.param(
            "lora_a",
            lambda key, shape: jax.random.uniform(
                key, shape, jnp.float32,
                -math.sqrt(1.0 / in_dim), math.sqrt(1.0 / in_dim)),
            (in_dim, cfg.lora_r))
        lora_b = self.param("lora_b", nn.initializers.zeros,
                            (cfg.lora_r, self.output_dim), jnp.float32)
        scaling = cfg.lora_alpha / cfg.lora_r
        out = out + scaling * (x.astype(dtype) @ lora_a.astype(dtype)
                               ) @ lora_b.astype(dtype)
        if self.bias:
            b = self.param("bias", nn.initializers.zeros,
                           (self.output_dim, ), jnp.float32)
            out = out + b.astype(dtype)
        return out


def init_lora(params, lora_config: LoRAConfig = None, rng=None):
    """Functional LoRA init over an existing tree: for each 2D kernel whose
    path matches ``target_mods``, create a (lora_a, lora_b) pair (A:
    he-uniform, B: zeros → merged output initially equals the base).

    Returns a flat dict ``{param_path: {"lora_a": A, "lora_b": B}}`` — the
    trainable adapter tree."""
    cfg = lora_config or LoRAConfig()
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    from ..runtime.zero.partition import path_str
    out = {}
    for kp, x in jax.tree_util.tree_leaves_with_path(params):
        path = path_str(kp)
        if getattr(x, "ndim", 0) != 2 or \
                not any(t in path for t in cfg.target_mods):
            continue
        k = jax.random.fold_in(rng, len(out))
        a = jax.random.uniform(k, (x.shape[0], cfg.lora_r), jnp.float32,
                               -math.sqrt(1.0 / x.shape[0]),
                               math.sqrt(1.0 / x.shape[0]))
        b = jnp.zeros((cfg.lora_r, x.shape[1]), jnp.float32)
        out[path] = {"lora_a": a, "lora_b": b}
    return out


def merge_lora(params, lora_params, lora_config: LoRAConfig = None):
    """Fold adapters into the base weights (the hybrid-engine 'fuse_lora'
    path, reference ``runtime/hybrid_engine.py:132``).  ``lora_params`` is
    the path-keyed dict from :func:`init_lora`."""
    cfg = lora_config or LoRAConfig()
    scaling = cfg.lora_alpha / cfg.lora_r
    from ..runtime.zero.partition import path_str

    def merge(kp, p):
        l = lora_params.get(path_str(kp))
        if l is None:
            return p
        return (p.astype(jnp.float32) +
                scaling * l["lora_a"] @ l["lora_b"]).astype(p.dtype)

    return jax.tree_util.tree_map_with_path(merge, params)


def unmerge_lora(params, lora_params, lora_config: LoRAConfig = None):
    """Inverse of :func:`merge_lora` (hybrid-engine 'unfuse_lora',
    reference ``runtime/hybrid_engine.py:146``)."""
    cfg = lora_config or LoRAConfig()
    scaling = cfg.lora_alpha / cfg.lora_r
    from ..runtime.zero.partition import path_str

    def unmerge(kp, p):
        l = lora_params.get(path_str(kp))
        if l is None:
            return p
        return (p.astype(jnp.float32) -
                scaling * l["lora_a"] @ l["lora_b"]).astype(p.dtype)

    return jax.tree_util.tree_map_with_path(unmerge, params)
