"""MoE layer — analog of reference ``deepspeed/moe/layer.py:17`` (``MoE``).

API mirrors the reference: wraps an expert module, owns the gate, returns
``(output, l_aux, exp_counts)``.  Expert-parallel groups are the "ep" mesh
axis (no ``_create_process_groups`` dance — reference moe/layer.py:89); use
``deepspeed_tpu.moe.experts.expert_sharding_rules()`` in ``initialize()``'s
``tp_rules`` to shard the expert params.

PR-MoE (residual MoE, reference ``layer.py:38 use_residual``): a dense MLP
runs in parallel and a learned coefficient mixes it with the MoE output.
"""

from typing import Optional, Type

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils import groups
from ..utils.logging import logger
from . import engine as moe_engine
from .experts import ExpertFFN, Experts
from .sharded_moe import TopKGate


class MoE(nn.Module):
    """``MoE(hidden_size, expert_module=..., num_experts=8, k=1, ...)``

    ``__call__(x)`` with x [B, S, D] (or [T, D]) →
    ``(output, l_aux, exp_counts)`` like the reference.
    """
    hidden_size: int
    num_experts: int = 8
    expert_module: Type[nn.Module] = ExpertFFN
    expert_kwargs: Optional[dict] = None
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_residual: bool = False
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x, used_token=None, train=True):
        D = self.hidden_size
        orig_shape = x.shape
        tokens = x.reshape(-1, D)  # [T, D]

        # gate (kept fp32 — reference gates in fp32 for stability)
        wg = nn.Dense(self.num_experts, use_bias=False, dtype=jnp.float32,
                      param_dtype=jnp.float32, name="gate")
        gate_in = tokens.astype(jnp.float32)
        if (train and self.noisy_gate_policy == "Jitter"
                and self.has_rng("gating")):
            # reference 'Jitter' policy: multiplicative uniform noise on the
            # gate INPUT (sharded_moe.py multiplicative_jitter)
            eps = 1e-2
            gate_in = gate_in * jax.random.uniform(
                self.make_rng("gating"), gate_in.shape,
                minval=1.0 - eps, maxval=1.0 + eps)
        logits = wg(gate_in)
        gate = TopKGate(k=self.k, capacity_factor=self.capacity_factor,
                        eval_capacity_factor=self.eval_capacity_factor,
                        min_capacity=self.min_capacity,
                        noisy_gate_policy=self.noisy_gate_policy,
                        drop_tokens=self.drop_tokens)
        rng = self.make_rng("gating") if (train and self.noisy_gate_policy
                                          and self.has_rng("gating")) else None
        l_aux, combine, dispatch, exp_counts = gate(logits, train=train, rng=rng)

        experts = Experts(expert_module=self.expert_module,
                          expert_kwargs=self.expert_kwargs or
                          {"hidden_size": D,
                           "intermediate_size": 4 * D,
                           "dtype": self.dtype},
                          num_experts=self.num_experts, name="deepspeed_moe")

        try:
            mesh = groups.get_global_mesh()
        except Exception:
            mesh = None
        # routed-token accounting on the telemetry spine (drop fraction,
        # overflow, expert-load imbalance, aux loss) — one attribute read
        # while telemetry is off
        moe_engine.record_routing(self._layer_id(), self.k, combine,
                                  dispatch, exp_counts, l_aux)
        # THE dispatch point: flat GSPMD constraints by default (bit-
        # identical), the manual quantized/hierarchical a2a when the ``moe``
        # config block arms it (docs/moe.md)
        out = moe_engine.dispatch_combine(tokens, combine, dispatch, experts,
                                          mesh=mesh)

        if self.use_residual:
            # PR-MoE: dense residual MLP + learned 2-way mixing coefficient
            mlp_out = self.expert_module(
                **(self.expert_kwargs or {"hidden_size": D,
                                          "intermediate_size": 4 * D,
                                          "dtype": self.dtype}),
                name="residual_mlp")(tokens)
            coef = nn.Dense(2, dtype=jnp.float32, param_dtype=jnp.float32,
                            name="coefficient")(tokens.astype(jnp.float32))
            coef = jax.nn.softmax(coef, axis=-1)
            out = (out.astype(jnp.float32) * coef[..., 0:1] +
                   mlp_out.astype(jnp.float32) * coef[..., 1:2]).astype(out.dtype)

        return out.reshape(orig_shape), l_aux, exp_counts

    def _layer_id(self):
        """Stable per-layer identity for the routed-token metric families —
        the flax scope path when available, the module name otherwise."""
        try:
            path = self.scope.path
            if path:
                return "/".join(str(p) for p in path)
        except Exception:
            pass
        return self.name or "moe"
