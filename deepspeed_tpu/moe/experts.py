"""Experts container — analog of reference ``deepspeed/moe/experts.py:13``
(``Experts`` holding per-rank expert copies).

Here all E experts live in ONE vmapped flax module whose params carry a
leading E dim; the engine's partition plan shards that dim over the "ep" mesh
axis (see ``expert_sharding_rules``), which is the per-rank-copies layout of
the reference without the module-list bookkeeping."""

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class ExpertFFN(nn.Module):
    """Default expert: 2-layer GELU MLP (what reference tests use)."""
    hidden_size: int
    intermediate_size: int
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x):
        dtype = jnp.dtype(self.dtype)
        h = nn.Dense(self.intermediate_size, dtype=dtype,
                     param_dtype=jnp.float32, name="fc1")(x)
        h = nn.gelu(h)
        return nn.Dense(self.hidden_size, dtype=dtype,
                        param_dtype=jnp.float32, name="fc2")(h)


class Experts(nn.Module):
    """Vmap an expert module over the leading E dim: input [E, C, D]."""
    expert_module: type
    expert_kwargs: dict
    num_experts: int

    @nn.compact
    def __call__(self, x):
        VmappedExpert = nn.vmap(
            self.expert_module,
            in_axes=0, out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        return VmappedExpert(**self.expert_kwargs, name="experts")(x)


def expert_sharding_rules():
    """Partition-plan rules: every param under an 'experts' scope gets its
    leading (expert) dim sharded over "ep".  Composes with the tp_rules
    mechanism (runtime/zero/partition.py) via the 'experts/*' wildcard."""
    return {"experts/*": P("ep")}
