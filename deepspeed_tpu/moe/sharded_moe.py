"""MoE gating + expert-parallel layer.

TPU-native re-design of reference ``deepspeed/moe/sharded_moe.py`` (TopKGate
``:374``, top1gating ``:183``, top2gating ``:290``, MOELayer ``:533``).

The reference dispatches tokens with einsums then ``all_to_all`` over the
expert group.  Here the same algebra runs under GSPMD: the dispatched tensor
[E, C, D] carries a sharding constraint P("ep", None, None) while tokens are
sharded over ("dp","ep") — XLA lowers the reshard to the all-to-all pair over
ICI, which *is* the reference's dispatch/return comm (SURVEY.md §2.1 MoE row).

Gating math (capacity, load-balance aux loss, random token priority) follows
GShard/the reference exactly so loss curves are comparable.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils import groups


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def _capacity(num_tokens, num_experts, capacity_factor, min_capacity=4):
    cap = int(num_tokens * capacity_factor / num_experts)
    cap = max(cap, min_capacity)
    # clamp at T: an expert can never receive more than every token, but for
    # tiny token counts min_capacity used to exceed T — silently inflating
    # the [E, C, D] dispatch buffer (and the a2a payload) with dead slots
    return min(cap, num_tokens)


def top1gating(logits, capacity_factor=1.0, min_capacity=4, noisy_gate_policy=None,
               rng=None, used_token=None):
    """Reference ``top1gating`` (sharded_moe.py:183): returns
    (l_aux, combine_weights [T,E,C], dispatch_mask [T,E,C], exp_counts [E])."""
    T, E = logits.shape
    C = _capacity(T, E, capacity_factor, min_capacity)

    if noisy_gate_policy == "RSample" and rng is not None:
        logits_for_sel = logits + jax.random.gumbel(rng, logits.shape)
    else:
        logits_for_sel = logits
    gates = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(logits_for_sel, axis=-1)  # [T]
    mask1 = _one_hot(idx, E)  # [T, E]
    if used_token is not None:
        mask1 = mask1 * used_token[:, None]

    # aux loss: E * mean(gates per expert) · mean(tokens per expert)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # position in expert buffer (cumsum over tokens), drop beyond capacity
    locations1 = jnp.cumsum(mask1, axis=0) - 1.0  # [T, E]
    mask1 = mask1 * (locations1 < C)
    pos = jnp.sum(locations1 * mask1, axis=-1).astype(jnp.int32)  # [T]

    gate1 = jnp.sum(gates * mask1, axis=-1)  # [T]
    combine = (gate1[:, None, None] * mask1[:, :, None] *
               _one_hot(pos, C)[:, None, :])  # [T, E, C]
    dispatch = combine > 0
    exp_counts = jnp.sum(mask1, axis=0)
    return l_aux, combine, dispatch, exp_counts


def top2gating(logits, capacity_factor=1.0, min_capacity=4, rng=None):
    """Reference ``top2gating`` (sharded_moe.py:290): top-2 with 2nd-expert
    jitter dropped (deterministic), capacity-bounded."""
    T, E = logits.shape
    C = _capacity(T, E, capacity_factor * 2, min_capacity)
    gates = jax.nn.softmax(logits, axis=-1)

    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)
    logits_wo1 = jnp.where(mask1 > 0, -jnp.inf, logits)
    idx2 = jnp.argmax(logits_wo1, axis=-1)
    mask2 = _one_hot(idx2, E)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    locations1 = jnp.cumsum(mask1, axis=0) - 1.0
    locations2 = jnp.cumsum(mask2, axis=0) - 1.0 + jnp.sum(mask1, axis=0)[None]
    mask1 = mask1 * (locations1 < C)
    mask2 = mask2 * (locations2 < C)
    pos1 = jnp.sum(locations1 * mask1, axis=-1).astype(jnp.int32)
    pos2 = jnp.sum(locations2 * mask2, axis=-1).astype(jnp.int32)

    g1 = jnp.sum(gates * mask1, axis=-1)
    g2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, jnp.finfo(gates.dtype).eps)
    g1, g2 = g1 / denom, g2 / denom

    combine = (g1[:, None, None] * mask1[:, :, None] * _one_hot(pos1, C)[:, None, :]
               + g2[:, None, None] * mask2[:, :, None] * _one_hot(pos2, C)[:, None, :])
    dispatch = combine > 0
    exp_counts = jnp.sum(mask1 + mask2, axis=0)
    return l_aux, combine, dispatch, exp_counts


def topkgating(logits, k, capacity_factor=1.0, min_capacity=4, drop_tokens=True):
    """Reference ``topkgating`` (sharded_moe.py:374) — general k.

    ``drop_tokens=False``: capacity becomes the static worst case (T slots
    per expert) so every token keeps its slot — positions past a smaller C
    would silently fall out of the one-hot below, dropping tokens the mode
    promises to keep (the reference instead pads C to the dynamic max,
    which XLA's static shapes cannot express)."""
    T, E = logits.shape
    C = _capacity(T, E, capacity_factor * k, min_capacity) if drop_tokens \
        else T
    gates = jax.nn.softmax(logits, axis=-1)
    topk_gates, topk_idx = jax.lax.top_k(gates, k)  # [T, k]
    mask = jnp.sum(_one_hot(topk_idx, E), axis=1)  # [T, E]

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask, axis=0)
    l_aux = jnp.sum(me * ce) * E / k

    locations = jnp.cumsum(mask, axis=0) - 1.0
    if drop_tokens:
        mask = mask * (locations < C)
    pos = (locations * mask).astype(jnp.int32)  # [T, E]

    gates_masked = gates * mask
    denom = jnp.maximum(jnp.sum(gates_masked, axis=-1, keepdims=True),
                        jnp.finfo(gates.dtype).eps)
    gates_norm = gates_masked / denom

    combine = gates_norm[:, :, None] * mask[:, :, None] * \
        jax.nn.one_hot(pos, C, dtype=gates.dtype)
    dispatch = combine > 0
    return l_aux, combine, dispatch, jnp.sum(mask, axis=0)


class TopKGate:
    """Reference ``TopKGate`` (sharded_moe.py:374 class) — functional form:
    ``gate(wg_logits)`` returns (l_aux, combine, dispatch, counts)."""

    def __init__(self, k=1, capacity_factor=1.0, eval_capacity_factor=1.0,
                 min_capacity=4, noisy_gate_policy=None, drop_tokens=True):
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    def __call__(self, logits, train=True, rng=None):
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.drop_tokens:
            if self.k == 1:
                return top1gating(logits, cf, self.min_capacity,
                                  self.noisy_gate_policy if train else None,
                                  rng)
            if self.k == 2:
                return top2gating(logits, cf, self.min_capacity, rng)
        # general-k path; also the no-drop path for every k (worst-case
        # static capacity — top1/top2 specializations always drop)
        return topkgating(logits, self.k, cf, self.min_capacity,
                          self.drop_tokens)


def dispatch_combine(x, combine, dispatch, expert_fn, ep_axis=groups.EP_AXIS,
                     mesh=None):
    """Einsum dispatch → experts → einsum combine, with "ep" sharding
    constraints so XLA emits the a2a pair (reference MOELayer.forward
    sharded_moe.py:533).

    x: [T, D]; combine/dispatch: [T, E, C]; expert_fn: [E, C, D] → [E, C, D].
    """
    dispatched = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    if mesh is not None and mesh.shape.get(ep_axis, 1) > 1:
        dispatched = jax.lax.with_sharding_constraint(
            dispatched, jax.sharding.NamedSharding(mesh, P(ep_axis, None, None)))
    out = expert_fn(dispatched)  # [E, C, D]
    if mesh is not None and mesh.shape.get(ep_axis, 1) > 1:
        out = jax.lax.with_sharding_constraint(
            out, jax.sharding.NamedSharding(mesh, P(ep_axis, None, None)))
    return jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out)
