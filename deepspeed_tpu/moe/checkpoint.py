"""Expert-parallel checkpoint layout (reference ``engine.py:3241
_save_moe_checkpoint`` / ``:3007 _get_moe_state_dicts``: experts saved as
one file per (layer, expert) so EP-degree can change on load).

Here experts are STACKED arrays (leading E dim sharded over "ep"), so the
engine checkpoint already holds global expert weights and resumes at any EP
degree — this module provides the *interchange* layout: explode stacks into
per-expert files (reference naming ``layer_{L}_expert_{E}_...``) and
reassemble them, so expert weights can be moved to/from systems that store
experts separately."""

import os
import re

import numpy as np

import jax

from ..runtime.zero.partition import path_str
from ..utils.logging import logger

# paths that hold stacked expert params: anything under an "experts" scope
# (moe/layer.py vmapped Experts) or mixtral's stacked w1/w2/w3
_EXPERT_PAT = re.compile(r"(^|/)experts(/|$)|(^|/)moe/w[123]$")
_LAYER_PAT = re.compile(r"(?:^|/)layers?_(\d+)(?:/|$)")


def is_expert_path(path):
    return bool(_EXPERT_PAT.search(path))


def _layer_of(path):
    m = _LAYER_PAT.search(path)
    return int(m.group(1)) if m else 0


def save_moe_expert_files(params, save_dir, tag="exported"):
    """Explode stacked expert leaves into per-(layer, expert) npz files.
    Returns the list of files written."""
    root = os.path.join(save_dir, tag)
    os.makedirs(root, exist_ok=True)
    per_file = {}
    for kp, leaf in jax.tree_util.tree_leaves_with_path(params):
        path = path_str(kp)
        if not is_expert_path(path):
            continue
        arr = np.asarray(leaf)
        layer = _layer_of(path)
        for e in range(arr.shape[0]):
            fname = f"layer_{layer}_expert_{e}_model_states.npz"
            per_file.setdefault(fname, {})[path] = arr[e]
    files = []
    for fname, tree in per_file.items():
        out = os.path.join(root, fname)
        np.savez(out, **tree)
        files.append(out)
    logger.info(f"saved {len(files)} expert files to {root}")
    return files


def load_moe_expert_files(params, load_dir, tag="exported"):
    """Reassemble per-expert files into the stacked leaves of ``params``
    (non-expert leaves pass through).  Returns the updated pytree."""
    root = os.path.join(load_dir, tag)
    stacks = {}
    for fname in sorted(os.listdir(root)):
        m = re.match(r"layer_(\d+)_expert_(\d+)_model_states\.npz", fname)
        if not m:
            continue
        e = int(m.group(2))
        with np.load(os.path.join(root, fname)) as data:
            for path in data.files:
                stacks.setdefault(path, {})[e] = data[path]

    def rebuild(kp, leaf):
        path = path_str(kp)
        if path not in stacks:
            return leaf
        by_e = stacks[path]
        arr = np.stack([by_e[e] for e in sorted(by_e)])
        if arr.shape != leaf.shape:
            raise ValueError(f"{path}: expert files give {arr.shape}, "
                             f"model expects {leaf.shape}")
        return jax.device_put(arr.astype(leaf.dtype), leaf.sharding)

    return jax.tree_util.tree_map_with_path(rebuild, params)
