from .checkpoint import (is_expert_path, load_moe_expert_files,
                         save_moe_expert_files)
from .experts import ExpertFFN, Experts, expert_sharding_rules
from .layer import MoE
from .sharded_moe import TopKGate, top1gating, top2gating, topkgating
