from .experts import ExpertFFN, Experts, expert_sharding_rules
from .layer import MoE
from .sharded_moe import TopKGate, top1gating, top2gating, topkgating
