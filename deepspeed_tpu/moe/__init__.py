from .checkpoint import (is_expert_path, load_moe_expert_files,
                         save_moe_expert_files)
from .engine import (MoeOptions, dispatch_combine, dispatch_wire,
                     ep_hierarchy, expert_dispatch_wire_bytes)
from .experts import ExpertFFN, Experts, expert_sharding_rules
from .layer import MoE
from .sharded_moe import TopKGate, top1gating, top2gating, topkgating
from .utils import (configure_moe_param_groups, has_moe_layers,
                    is_moe_param, is_moe_param_group, moe_param_mask,
                    split_params_grads_into_shared_and_expert_params,
                    split_params_into_different_moe_groups_for_optimizer,
                    split_params_into_shared_and_expert_params)
