"""MoE parameter-group utilities — analog of reference
``deepspeed/moe/utils.py`` (``is_moe_param`` :27,
``split_params_into_shared_and_expert_params`` :33,
``split_params_into_different_moe_groups_for_optimizer`` :72,
``configure_moe_param_groups`` :155, ``has_moe_layers`` :15).

The reference tags torch Parameters with ``.allreduce=False`` and splits
optimizer param groups so expert grads reduce over expert-DP groups and
experts can carry their own lr/weight-decay.  Under SPMD the grad
reduction is already correct by sharding (expert leaves live on the "ep"
axis), so what remains user-facing is the GROUPING itself: identifying
expert leaves by pytree path and deriving masks/splits that plug into
optax (``adamw(mask=...)``, ``multi_transform``) or the engine's
optimizer config.
"""

import jax

from .checkpoint import is_expert_path
from ..runtime.zero.partition import path_str


def is_moe_param(path_or_keypath) -> bool:
    """True if the pytree path addresses a stacked-expert leaf.

    Accepts a ``"a/b/c"`` string or a jax key-path tuple (reference
    ``is_moe_param`` reads a ``.allreduce`` tag off the tensor; JAX params
    carry identity in their tree path instead)."""
    if not isinstance(path_or_keypath, str):
        path_or_keypath = path_str(path_or_keypath)
    return is_expert_path(path_or_keypath)


def has_moe_layers(params):
    """(bool, num_expert_leaves) — reference ``has_moe_layers`` :15."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    n = sum(1 for kp, _ in flat if is_moe_param(kp))
    return n > 0, n


def moe_param_mask(params, experts=True):
    """Boolean pytree matching ``params``: True on expert leaves (or the
    complement with ``experts=False``).  Plugs directly into
    ``optax.adamw(..., mask=moe_param_mask(params, experts=False))`` —
    the reference tutorial's 'no weight decay on experts' recipe."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, _: is_moe_param(kp) == experts, params)


def split_params_into_shared_and_expert_params(params):
    """(shared, expert): two pytrees shaped like ``params`` where the
    other split's leaves are ``None`` (the functional analog of the
    reference's two python lists).  NOTE: jax treats ``None`` as an empty
    subtree, so ``tree_map`` against the FULL ``params`` tree needs
    ``is_leaf=lambda x: x is None`` — for per-leaf selection prefer
    :func:`moe_param_mask` (a boolean tree with identical treedef)."""
    shared = jax.tree_util.tree_map_with_path(
        lambda kp, v: None if is_moe_param(kp) else v, params)
    expert = jax.tree_util.tree_map_with_path(
        lambda kp, v: v if is_moe_param(kp) else None, params)
    return shared, expert


def split_params_grads_into_shared_and_expert_params(grads):
    """Reference :46 — identical split applied to a grad tree."""
    return split_params_into_shared_and_expert_params(grads)


def configure_moe_param_groups(params, expert_lr=None,
                               expert_weight_decay=None):
    """Torch-parity param groups (reference :72/:155): a list of dicts —
    one shared group and one expert group, the expert group carrying its
    optional lr/weight_decay overrides.  Each group's ``"params"`` holds
    its None-holed split of the param tree; the optax-style LABEL tree
    (``"shared"``/``"expert"`` per leaf, treedef identical to ``params``)
    lives under the FIRST group's ``"param_labels"`` key — that tree is
    what ``optax.multi_transform`` takes."""
    labels = jax.tree_util.tree_map_with_path(
        lambda kp, _: "expert" if is_moe_param(kp) else "shared", params)
    shared, expert = split_params_into_shared_and_expert_params(params)
    groups = [
        {"name": "shared", "params": shared, "moe": False,
         "param_labels": labels},
        {"name": "expert", "params": expert, "moe": True},
    ]
    if expert_lr is not None:
        groups[1]["lr"] = expert_lr
    if expert_weight_decay is not None:
        groups[1]["weight_decay"] = expert_weight_decay
    return groups


def is_moe_param_group(param_group) -> bool:
    """Reference :151."""
    return bool(param_group.get("moe", False))


def split_params_into_different_moe_groups_for_optimizer(
        param_groups, max_group_size=None):
    """Reference :72 — the tutorial-facing name.  Accepts either a params
    pytree or torch-style ``{"params": tree, ...}`` group dict(s) and
    returns the shared + expert group list (``configure_moe_param_groups``
    does the work; per-expert sub-grouping via ``max_group_size`` is a
    CUDA-allreduce-bucketing concern with no SPMD analog and is
    ignored)."""
    if isinstance(param_groups, dict) and "params" in param_groups:
        base = dict(param_groups)
        tree = base.pop("params")
        groups = configure_moe_param_groups(tree)
        for g in groups:
            for k, v in base.items():
                g.setdefault(k, v)
        return groups
    if isinstance(param_groups, (list, tuple)) and param_groups and \
            all(isinstance(pg, dict) and "params" in pg
                for pg in param_groups):
        # torch-style LIST of groups — a list-topped params pytree (e.g.
        # per-layer list of dicts) must fall through to the pytree branch
        out = []
        for pg in param_groups:
            out.extend(
                split_params_into_different_moe_groups_for_optimizer(pg))
        return out
    return configure_moe_param_groups(param_groups)
