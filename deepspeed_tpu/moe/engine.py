"""Expert-parallel MoE engine — quantized all-to-all dispatch over the
collectives engine, plus routed-token accounting on the telemetry spine.

``moe/sharded_moe.py`` keeps the reference-faithful gating math and the
GSPMD constraint dispatch (tokens sharded over ("dp","ep"), the [E, C, D]
dispatch buffer constrained to P("ep") — XLA lowers the reshard to the
dispatch/return all-to-all pair).  This module is the *production* layer on
top of it:

* **one dispatch point** (:func:`dispatch_combine`) the :class:`~deepspeed_tpu
  .moe.layer.MoE` layer routes through.  With the ``moe`` config block absent
  or ``quantized_dispatch: false`` it delegates verbatim to the GSPMD path —
  bit-identical program, the same contract as ``comm_optimizations``;
* **manual-SPMD quantized dispatch** (``moe.quantized_dispatch: true``): the
  dispatch reduce and the return gather run inside ``shard_map`` regions that
  reuse :mod:`deepspeed_tpu.comm.collectives.quantized`'s blockwise codecs —
  int8/int4/fp8/fp6/fp12 payload + f32 scales on the wire instead of the fp
  activations (ZeRO++ qgZ/qwZ applied to expert exchange, arxiv 2306.10209;
  the scalable-collectives recipe of arxiv 2504.18658).  The
  ``comm_optimizations.wire_dtype_by_size`` ladder is honored: the payload
  size picks the rung, ``"fp32"`` rungs keep that band on the identical
  unquantized schedule;
* **hierarchical (ICI-intra / DCN-inter) variants** picked by
  ``topology.factor_group`` like the other collectives: full-precision
  psum-scatter over the intra-node ``ep`` factor, quantized all-to-all over
  the inter-node factor only — one quantization error on the slow hop;
* **manual-context operation**: inside the qgZ manual micro
  (``zeropp.build_manual_dp_micro``) the whole step already runs under
  ``shard_map`` — the dispatcher detects the axis context and issues the
  collectives directly (the GSPMD constraint path would emit an invalid
  nested ``with_sharding_constraint`` there);
* **routed-token accounting**: per-layer drop-fraction, overflow tokens,
  expert-load imbalance (max/mean tokens per expert) and aux loss land on
  the telemetry spine as ``moe/*`` metric families and a ``moe`` section of
  the per-step trace record (:func:`record_routing`; zero overhead while
  telemetry is off).

Gradients: the quantized exchanges are **straight-through** — forward moves
the quantized payload, backward is the exact VJP of the flat (unquantized)
linear exchange, same rule as ``qdq_all_gather_st``.  The expert compute
itself stays outside the manual regions, so expert parameters keep their
``P("ep")`` sharding and ZeRO's ``("dp","ep")`` factorization untouched.
"""

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry as _telemetry
from ..comm.collectives import quantized as Q
from ..comm.collectives.engine import (LADDER_FP, build_wire_ladder,
                                       resolve_in_ladder)
from ..utils import groups
from ..utils.logging import logger

#: wire formats the dispatch accepts: the quantized family plus the flat rung
DISPATCH_WIRES = (LADDER_FP, ) + Q.WIRE_FORMATS


@dataclass
class MoeOptions:
    """Runtime-independent mirror of the ``moe`` config block
    (``runtime/config.py:MoeConfig``) for standalone consumers — benchmarks,
    tools, tests.  The dispatcher is duck-typed: either object works."""
    enabled: bool = False
    # route the dispatch/return exchange through the manual quantized path;
    # False (default) = the GSPMD constraint path, bit-identical to pre-MoE
    quantized_dispatch: bool = False
    # wire format of the quantized exchange ("fp32" = the manual schedule
    # with the raw fp payload — schedule-identical, no codec)
    wire_dtype: str = "int8"
    quantization_group_size: int = Q.DEFAULT_GROUP_SIZE
    # 2-hop dispatch (fp intra-node, quantized inter-node) when
    # topology.factor_group sees a hierarchy on the ep axis
    hierarchical_dispatch: bool = True
    # devices-per-node override for the ep-axis hierarchy split (0 = device
    # metadata / DS_TPU_INTRA_NODE_SIZE, like the collectives engine)
    intra_node_size: int = 0
    # base seed folded (per step, per layer) into the noisy-gate rngs the
    # runtime engine threads through flax apply; None = the config "seed"
    gating_seed: int = None


# --------------------------------------------------------------- module state
_active = None       # MoeOptions / MoeConfig duck-typed, or None (disabled)
_comm_opts = None    # comm_optimizations view (wire ladder + intra override)
_ladder = None       # normalized wire_dtype_by_size rungs
_meta_emitted = set()


def configure(moe_opts, comm_opts=None):
    """Install the active ``moe`` options (the runtime engine calls this at
    bring-up; ``None``/disabled resets to the flat GSPMD path).  The
    ``comm_optimizations`` view supplies the ``wire_dtype_by_size`` ladder
    and the ``intra_node_size`` fallback."""
    global _active, _comm_opts, _ladder
    active = moe_opts if (moe_opts is not None
                          and getattr(moe_opts, "enabled", False)) else None
    # validate BEFORE mutating the module state: a rejected configure must
    # leave the previously-installed dispatcher untouched (callers restore
    # in a finally that never runs if this raises)
    ladder = None
    if active is not None:
        wire = getattr(active, "wire_dtype", "int8")
        if wire not in DISPATCH_WIRES:
            raise ValueError(
                f"moe.wire_dtype {wire!r} unknown "
                f"(have {', '.join(DISPATCH_WIRES)})")
        if comm_opts is not None and getattr(comm_opts, "enabled", False):
            ladder = build_wire_ladder(
                getattr(comm_opts, "wire_dtype_by_size", None))
    _active = active
    _comm_opts = comm_opts
    _ladder = ladder
    _meta_emitted.clear()
    return _active


def reset():
    configure(None)


def active_options():
    return _active


def snapshot():
    """The full dispatcher state as an opaque pair — hand it back to
    :func:`restore` to reinstall options AND the comm view (a bare
    ``configure(active_options())`` would drop the wire ladder)."""
    return (_active, _comm_opts)


def restore(state):
    opts, comm_opts = state
    return configure(opts, comm_opts=comm_opts)


def dispatch_wire(nbytes, opts=None):
    """Wire format for an expert-dispatch payload of ``nbytes`` logical
    bytes: the ``comm_optimizations.wire_dtype_by_size`` ladder rung when a
    ladder is installed (the autotuner's per-size choice applies to the
    hardest collective too), else ``moe.wire_dtype``.  ``"fp32"`` = the
    manual schedule with the raw fp payload."""
    opts = opts if opts is not None else _active
    default = getattr(opts, "wire_dtype", "int8") if opts is not None \
        else LADDER_FP
    return resolve_in_ladder(_ladder, nbytes, default)


def _intra_override(opts):
    if opts is not None and getattr(opts, "intra_node_size", 0):
        return int(opts.intra_node_size)
    if _comm_opts is not None:
        return int(getattr(_comm_opts, "intra_node_size", 0) or 0)
    return 0


def ep_hierarchy(mesh, opts=None, ep_axis=groups.EP_AXIS):
    """The (inter, intra) factorization of the expert-parallel axis, or
    None — the same ``topology.factor_group`` pick the other collectives
    dispatch on."""
    opts = opts if opts is not None else _active
    if opts is not None and not getattr(opts, "hierarchical_dispatch", True):
        return None
    if mesh.shape.get(ep_axis, 1) <= 1:
        return None
    from ..comm.backend import ProcessGroup
    from ..comm.collectives.topology import factor_group
    return factor_group(ProcessGroup(mesh, (ep_axis, )),
                        intra_node_size=_intra_override(opts))


def expert_dispatch_wire_bytes(n_elements, wire, group_size, n_inner=1):
    """Transported bytes of one dispatch (or return) exchange on the
    bottleneck (inter-node) link: quantized payload + scales on 1/n_inner
    of the data under the hierarchical variant; the logical fp bytes for
    the flat rung."""
    n = int(n_elements) // max(1, int(n_inner))
    if wire == LADDER_FP:
        return n * 4
    return Q.quantized_wire_bytes(n, wire, group_size)


# --------------------------------------------------- straight-through comms
# The quantized exchanges are linear maps in the flat limit; backward is the
# EXACT VJP of that flat map (all_gather ↔ sum-scatter), so quantization
# rounding never zeroes the gradient — the qdq_all_gather_st rule applied to
# expert dispatch.

@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _exchange_st(pdisp, sum_axes, ep_axes, n_ep, wire, gs):
    """Inside-shard_map dispatch reduce: fp psum over the non-expert token
    axes, then (quantized) all-to-all reduce over the ep axes — rank e ends
    with expert chunk e of the globally-summed [E, C, D] buffer."""
    r = pdisp
    if sum_axes:
        r = jax.lax.psum(r, sum_axes)
    if n_ep > 1:
        r = Q.all_to_all_quant_reduce(r, ep_axes, 0, n_ep, wire_format=wire,
                                      group_size=gs, mean=False)
    # the reduce primitive accumulates in f32; hand the expert compute its
    # own dtype back (bf16 models must not silently widen the [E, C, D]
    # buffer — 2x memory and a different numeric path than the flat einsum)
    return r.astype(pdisp.dtype)


def _exchange_st_fwd(pdisp, sum_axes, ep_axes, n_ep, wire, gs):
    return _exchange_st(pdisp, sum_axes, ep_axes, n_ep, wire, gs), None


def _exchange_st_bwd(sum_axes, ep_axes, n_ep, wire, gs, _, dy):
    g = dy
    if n_ep > 1:
        g = jax.lax.all_gather(g, ep_axes, axis=0, tiled=True)
    if sum_axes:
        g = jax.lax.psum(g, sum_axes)
    return (g, )


_exchange_st.defvjp(_exchange_st_fwd, _exchange_st_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _collect_st(local, ep_axes, n_ep, wire, gs):
    """Inside-shard_map return gather: (quantized) all-gather of the local
    expert outputs back to the full [E, C, D] buffer on every rank."""
    if n_ep <= 1:
        return local
    return Q.quantized_all_gather(local, ep_axes, 0, wire,
                                  gs).astype(local.dtype)


def _collect_st_fwd(local, ep_axes, n_ep, wire, gs):
    return _collect_st(local, ep_axes, n_ep, wire, gs), None


def _collect_st_bwd(ep_axes, n_ep, wire, gs, _, dy):
    if n_ep <= 1:
        return (dy, )
    return (jax.lax.psum_scatter(dy, ep_axes, scatter_dimension=0,
                                 tiled=True), )


_collect_st.defvjp(_collect_st_fwd, _collect_st_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _dispatch_a2a_st(pdisp, ep_axes, n_ep, wire, gs):
    """Manual-context dispatch exchange (reference ``_AllToAll``): split
    the expert dim across the ep group, concatenate each peer's capacity
    block along the slot dim — [E, C, D] → [E/ep, ep·C, D].  A permutation,
    never a sum: per-rank capacity blocks survive verbatim."""
    return Q.quantized_all_to_all(pdisp, ep_axes, 0, 1, n_ep,
                                  wire_format=wire, group_size=gs)


def _dispatch_a2a_st_fwd(pdisp, ep_axes, n_ep, wire, gs):
    return _dispatch_a2a_st(pdisp, ep_axes, n_ep, wire, gs), None


def _dispatch_a2a_st_bwd(ep_axes, n_ep, wire, gs, _, dy):
    # the exchange is a cross-rank permutation; its exact transpose is the
    # inverse all-to-all in full precision (straight-through)
    return (jax.lax.all_to_all(dy, ep_axes, split_axis=1, concat_axis=0,
                               tiled=True), )


_dispatch_a2a_st.defvjp(_dispatch_a2a_st_fwd, _dispatch_a2a_st_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _return_a2a_st(out, ep_axes, n_ep, wire, gs):
    """Manual-context return exchange: the inverse of
    :func:`_dispatch_a2a_st` — [E/ep, ep·C, D] → [E, C, D]."""
    return Q.quantized_all_to_all(out, ep_axes, 1, 0, n_ep,
                                  wire_format=wire, group_size=gs)


def _return_a2a_st_fwd(out, ep_axes, n_ep, wire, gs):
    return _return_a2a_st(out, ep_axes, n_ep, wire, gs), None


def _return_a2a_st_bwd(ep_axes, n_ep, wire, gs, _, dy):
    return (jax.lax.all_to_all(dy, ep_axes, split_axis=0, concat_axis=1,
                               tiled=True), )


_return_a2a_st.defvjp(_return_a2a_st_fwd, _return_a2a_st_bwd)


# ------------------------------------------------------ hierarchical helpers
def _hier_permute(x, n_out, n_in):
    """Pre-permute the E dim so the inner-major tiling the 2-hop
    reduce-scatter produces lands each expert chunk on its outer-major
    ``P("ep")`` rank: viewed as [n_out, n_in, eloc], swap the factors.
    Pure local reshape — no communication."""
    E = x.shape[0]
    eloc = E // (n_out * n_in)
    return x.reshape((n_out, n_in, eloc) + x.shape[1:]).swapaxes(0, 1) \
        .reshape(x.shape)


def _hier_unpermute_gathered(full, n_out, n_in):
    """Reassemble the 2-hop gather (inner gather outermost) into the
    canonical outer-major E order.  Pure local reshape."""
    E = full.shape[0]
    eloc = E // (n_out * n_in)
    return full.reshape((n_in, n_out, eloc) + full.shape[1:]) \
        .swapaxes(0, 1).reshape(full.shape)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6, 7))
def _hier_exchange_st(pdisp, sum_axes, out_ax, in_ax, n_out, n_in, wire, gs):
    """2-hop dispatch reduce: fp psum over the token axes, fp psum-scatter
    over the intra-node ep factor (ICI, full data), quantized all-to-all
    over the inter-node factor (DCN, 1/n_in of the data).  The pre-permute
    makes the result tile outer-major, i.e. exactly ``P((out, in))`` on the
    split mesh = ``P("ep")`` placement on the original device order."""
    r = pdisp
    if sum_axes:
        r = jax.lax.psum(r, sum_axes)
    r = _hier_permute(r, n_out, n_in)
    r = Q.hierarchical_quant_reduce_scatter(
        r, (in_ax, ), (out_ax, ), 0, n_in, n_out, wire_format=wire,
        group_size=gs, mean=False)
    return r.astype(pdisp.dtype)  # see _exchange_st: no silent widening


def _hier_exchange_st_fwd(pdisp, sum_axes, out_ax, in_ax, n_out, n_in, wire,
                          gs):
    return _hier_exchange_st(pdisp, sum_axes, out_ax, in_ax, n_out, n_in,
                             wire, gs), None


def _hier_exchange_st_bwd(sum_axes, out_ax, in_ax, n_out, n_in, wire, gs, _,
                          dy):
    # exact flat VJP: reassemble the full cotangent on every rank.  The
    # gather over (out, in) in axis-index order is outer-major = the
    # canonical chunk order, so no unpermute is needed.
    g = jax.lax.all_gather(dy, (out_ax, in_ax), axis=0, tiled=True)
    if sum_axes:
        g = jax.lax.psum(g, sum_axes)
    return (g, )


_hier_exchange_st.defvjp(_hier_exchange_st_fwd, _hier_exchange_st_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _hier_collect_st(local, out_ax, in_ax, n_out, n_in, wire, gs):
    """2-hop return gather: quantized all-gather over the inter-node factor
    (DCN, the small local block), fp all-gather over the intra-node factor
    (ICI), then a local reorder back to canonical expert order."""
    inter = Q.quantized_all_gather(local, (out_ax, ), 0, wire, gs)
    full = jax.lax.all_gather(inter, in_ax, axis=0, tiled=True)
    return _hier_unpermute_gathered(full, n_out, n_in).astype(local.dtype)


def _hier_collect_st_fwd(local, out_ax, in_ax, n_out, n_in, wire, gs):
    return _hier_collect_st(local, out_ax, in_ax, n_out, n_in, wire, gs), None


def _hier_collect_st_bwd(out_ax, in_ax, n_out, n_in, wire, gs, _, dy):
    # exact flat VJP of "gather my chunk to everyone": each rank keeps the
    # sum of all ranks' cotangent slices of its own (outer-major) chunk
    return (jax.lax.psum_scatter(dy, (out_ax, in_ax), scatter_dimension=0,
                                 tiled=True), )


_hier_collect_st.defvjp(_hier_collect_st_fwd, _hier_collect_st_bwd)


# ----------------------------------------------------------- manual regions
def _token_axes(mesh):
    """Mesh axes sharding the token dim of engine batches (dp_axes order,
    restricted to axes the mesh actually has — a guard for non-groups
    meshes, whose specs would otherwise name unknown axes)."""
    return tuple(a for a in groups.dp_axes() if a in mesh.shape)


def resolve_exchange(mesh, opts, ep_axis, payload_elems):
    """(wire, group_size, hierarchy-or-None, wire_bytes) for one dispatch
    exchange of ``payload_elems`` fp32 elements — the public view of what
    the dispatcher will put on the wire (ds_bench reports through it)."""
    gs = int(getattr(opts, "quantization_group_size", Q.DEFAULT_GROUP_SIZE))
    wire = dispatch_wire(payload_elems * 4, opts)
    h = None
    if wire != LADDER_FP:
        h = ep_hierarchy(mesh, opts, ep_axis)
        if h is not None and (len(h.outer_axes) != 1
                              or len(h.inner_axes) != 1):
            h = None  # only the single-axis split shape is implemented
        if h is not None and payload_elems % (h.outer_size * h.inner_size):
            h = None
    n_inner = h.inner_size if h is not None else 1
    return wire, gs, h, expert_dispatch_wire_bytes(payload_elems, wire, gs,
                                                   n_inner)


def _emit_dispatch_meta(variant, wire, wire_bytes, E, C, D, ep):
    if not _telemetry.enabled:
        return
    key = (variant, wire, E, C, D, ep)
    if key in _meta_emitted:
        return
    _meta_emitted.add(key)
    _telemetry.metadata("moe_dispatch", {
        "variant": variant, "wire_dtype": wire,
        "wire_bytes_per_exchange": int(wire_bytes),
        "experts": int(E), "capacity": int(C), "hidden": int(D),
        "ep": int(ep)})


def _manual_dispatch_combine(x, combine, dispatch, expert_fn, opts, mesh,
                             ep_axis):
    """Expert dispatch inside an ALREADY-manual region (the qgZ micro's
    shard_map body): tokens/masks are local shards, expert params are local
    ``P("ep")`` shards — issue the collectives directly (the GSPMD
    constraint path cannot run here: a nested ``with_sharding_constraint``
    inside a manual region is invalid).

    Reference semantics (``MOELayer.forward`` + ``_AllToAll``): gating and
    capacity are PER-RANK, the a2a exchanges each rank's capacity block —
    the expert buffer becomes [E/ep, ep·C, D], a concatenation, never a
    sum (summing distinct ranks' buffers would collide their slots).
    Tokens never cross the expert-data-parallel ("dp") rows: those rows
    run the same experts on different data, and the per-leaf ZeRO
    reduction (``reduce_leaf``) averages their expert grads."""
    st = groups.get_mesh_state()
    ep = st.ep
    dmask = jax.lax.stop_gradient(dispatch.astype(x.dtype))
    pdisp = jnp.einsum("tec,td->ecd", dmask, x)
    E = pdisp.shape[0]
    if ep > 1 and E % ep:
        raise ValueError(
            f"num_experts={E} must be divisible by ep={ep} "
            "(expert stacks shard their leading dim over the ep axis)")
    if opts is not None and getattr(opts, "quantized_dispatch", False):
        # ladder rung from the LOGICAL payload: pdisp here is a per-shard
        # [E, C_local, D] buffer, but the ladder (and the autotuner probes
        # that emitted it) key on the global message size — the same
        # convention as zeropp's per-leaf ladder resolution.  The global
        # capacity scales linearly with the token-group degree.
        n_tok = int(np.prod([mesh.shape.get(a, 1)
                             for a in _token_axes(mesh)]))
        wire = dispatch_wire(pdisp.size * n_tok * 4, opts)
    else:
        wire = LADDER_FP  # flat payload, same exchange schedule
    gs = int(getattr(opts, "quantization_group_size", Q.DEFAULT_GROUP_SIZE)
             if opts is not None else Q.DEFAULT_GROUP_SIZE)
    # hierarchy needs a reshaped mesh — not expressible inside an
    # already-manual region, so the manual-context path is always 1-hop
    if ep > 1:
        local = _dispatch_a2a_st(pdisp, (ep_axis, ), ep, wire, gs)
    else:
        local = pdisp
    out = expert_fn(local)
    if ep > 1:
        full = _return_a2a_st(out, (ep_axis, ), ep, wire, gs)
    else:
        full = out
    return jnp.einsum("tec,ecd->td", combine.astype(x.dtype), full)


def _quantized_dispatch_combine(x, combine, dispatch, expert_fn, opts, mesh,
                                ep_axis):
    """The manual-SPMD expert-dispatch path under a GSPMD program: two
    ``shard_map`` regions (dispatch reduce / return gather) around the
    untouched expert compute, each wrapped in a straight-through
    ``custom_vjp`` whose backward is the exact flat VJP expressed as plain
    GSPMD einsums (XLA inserts the fp backward collectives — the same
    wire the flat path's AD uses)."""
    ep = mesh.shape[ep_axis]
    E = combine.shape[1]
    if E % ep:
        raise ValueError(
            f"num_experts={E} must be divisible by ep={ep} "
            "(expert stacks shard their leading dim over the ep axis)")
    T = x.shape[0]
    C, D = combine.shape[2], x.shape[1]
    token_axes = _token_axes(mesh)
    n_tok = int(np.prod([mesh.shape[a] for a in token_axes]))
    if T % n_tok:
        logger.warning(
            "moe.quantized_dispatch: token count %d not divisible by the "
            "token mesh degree %d — falling back to the GSPMD constraint "
            "path for this call", T, n_tok)
        from .sharded_moe import dispatch_combine as _flat
        return _flat(x, combine, dispatch, expert_fn, ep_axis=ep_axis,
                     mesh=mesh)
    payload = E * C * D
    wire, gs, h, wire_bytes = resolve_exchange(mesh, opts, ep_axis, payload)
    sum_axes = tuple(a for a in token_axes if a != ep_axis
                     and mesh.shape.get(a, 1) > 1)
    dmask = jax.lax.stop_gradient(dispatch.astype(x.dtype))
    cmask = combine.astype(x.dtype)

    if h is not None:
        smesh = h.mesh
        out_ax, in_ax = h.outer_axes[0], h.inner_axes[0]
        n_out, n_in = h.outer_size, h.inner_size
        ep_entry = (out_ax, in_ax)
        # the split mesh spells the ep factor (ep_out, ep_in); same device
        # order, so the token tiling is unchanged
        token_entry = tuple(a for a in token_axes if a != ep_axis) \
            + (out_ax, in_ax)
        variant = f"hier_q_{wire}"

        def _disp_body(tok, dm):
            pdisp = jnp.einsum("tec,td->ecd", dm, tok)
            return _hier_exchange_st(pdisp, sum_axes, out_ax, in_ax, n_out,
                                     n_in, wire, gs)

        def _ret_body(loc, cm):
            full = _hier_collect_st(loc, out_ax, in_ax, n_out, n_in, wire,
                                    gs)
            return jnp.einsum("tec,ecd->td", cm, full)
    else:
        smesh = mesh
        ep_entry = ep_axis
        token_entry = tuple(token_axes)
        variant = f"q_{wire}" if wire != LADDER_FP else "manual_fp"

        def _disp_body(tok, dm):
            pdisp = jnp.einsum("tec,td->ecd", dm, tok)
            return _exchange_st(pdisp, sum_axes, (ep_axis, ), ep, wire, gs)

        def _ret_body(loc, cm):
            full = _collect_st(loc, (ep_axis, ), ep, wire, gs)
            return jnp.einsum("tec,ecd->td", cm, full)

    ecd_spec = P(ep_entry, None, None)
    tok_entry = token_entry if len(token_entry) > 1 else token_entry[0]
    tok_spec = P(tok_entry, None)
    tok3_spec = P(tok_entry, None, None)

    def _sm(body, in_specs, out_specs):
        return jax.shard_map(body, mesh=smesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)

    @jax.custom_vjp
    def _dispatch_region(tok, dm):
        return _sm(_disp_body, (tok_spec, tok3_spec), ecd_spec)(tok, dm)

    def _dispatch_fwd(tok, dm):
        return _dispatch_region(tok, dm), dm

    def _dispatch_bwd(dm, dy):
        # exact flat VJP under GSPMD: XLA gathers dy over ep in fp for the
        # token-side contraction; the mask is a stop_gradient input
        return jnp.einsum("tec,ecd->td", dm, dy), jnp.zeros_like(dm)

    _dispatch_region.defvjp(_dispatch_fwd, _dispatch_bwd)

    @jax.custom_vjp
    def _combine_region(loc, cm):
        return _sm(_ret_body, (ecd_spec, tok3_spec), tok_spec)(loc, cm)

    def _combine_fwd(loc, cm):
        return _combine_region(loc, cm), (loc, cm)

    def _combine_bwd(res, dy):
        loc, cm = res
        dloc = jnp.einsum("tec,td->ecd", cm, dy)
        dloc = jax.lax.with_sharding_constraint(
            dloc, NamedSharding(mesh, P(ep_axis, None, None)))
        dcm = jnp.einsum("td,ecd->tec", dy, loc)
        return dloc, dcm

    _combine_region.defvjp(_combine_fwd, _combine_bwd)

    _emit_dispatch_meta(variant, wire, wire_bytes, E, C, D, ep)
    local = _dispatch_region(x, dmask)
    out = expert_fn(local)
    return _combine_region(out, cmask)


def dispatch_combine(x, combine, dispatch, expert_fn,
                     ep_axis=groups.EP_AXIS, mesh=None):
    """THE expert-dispatch point ``moe/layer.py`` routes through.

    ``x`` [T, D] tokens; ``combine``/``dispatch`` [T, E, C] gate outputs;
    ``expert_fn`` [E, C, D] → [E, C, D].  Path selection:

    * inside a manual region (the qgZ micro) → direct collectives
      (:func:`_manual_dispatch_combine`);
    * ``moe.quantized_dispatch`` on an ep>1 mesh → the manual-SPMD
      (optionally hierarchical) quantized exchange;
    * otherwise → ``sharded_moe.dispatch_combine`` verbatim (bit-identical
      to the pre-engine program).
    """
    opts = _active
    if mesh is None:
        try:
            mesh = groups.get_global_mesh()
        except Exception:
            mesh = None
    from ..utils import jax_compat
    if mesh is not None and jax_compat.inside_axis_context():
        n_tok = int(np.prod([mesh.shape.get(a, 1)
                             for a in groups.dp_axes()]))
        if n_tok > 1:
            return _manual_dispatch_combine(x, combine, dispatch, expert_fn,
                                            opts, mesh, ep_axis)
        # single-rank token group: nothing to exchange, run locally
        from .sharded_moe import dispatch_combine as _flat
        return _flat(x, combine, dispatch, expert_fn, ep_axis=ep_axis,
                     mesh=None)
    if (opts is None or not getattr(opts, "quantized_dispatch", False)
            or mesh is None or mesh.shape.get(ep_axis, 1) <= 1):
        from .sharded_moe import dispatch_combine as _flat
        return _flat(x, combine, dispatch, expert_fn, ep_axis=ep_axis,
                     mesh=mesh)
    if mesh.shape.get("sp", 1) > 1 or mesh.shape.get("pp", 1) > 1:
        if "sp_pp_warned" not in _meta_emitted:
            _meta_emitted.add("sp_pp_warned")
            logger.warning(
                "moe.quantized_dispatch is ignored on sp/pp meshes (the "
                "manual dispatch regions assume tokens shard over "
                "(dp, ep) only); using the GSPMD constraint path")
        from .sharded_moe import dispatch_combine as _flat
        return _flat(x, combine, dispatch, expert_fn, ep_axis=ep_axis,
                     mesh=mesh)
    return _quantized_dispatch_combine(x, combine, dispatch, expert_fn,
                                       opts, mesh, ep_axis)


# --------------------------------------------------- routed-token accounting
def _stats_sink(layer, k, drop_fraction, overflow_tokens, load_imbalance,
                aux_loss, expert_util):
    """Host-side sink for the traced routing stats (jax.debug.callback
    target): per-layer ``moe/*`` metric families + the step record's
    ``moe`` section."""
    layer = str(layer)
    util = [float(u) for u in np.asarray(expert_util).reshape(-1)]
    stats = {
        "k": int(k),
        "drop_fraction": float(drop_fraction),
        "overflow_tokens": float(overflow_tokens),
        "load_imbalance": float(load_imbalance),
        "aux_loss": float(aux_loss),
        # per-expert capacity utilization (post-drop tokens / capacity C):
        # the raw signal a capacity-factor autotuner dimension needs —
        # a uniformly low vector says "shrink cf", a saturated one with
        # drops says "grow it" (ISSUE-15 satellite / ROADMAP MoE (c))
        "expert_util": util,
    }
    _telemetry.record_moe_stats(layer, stats)
    g = _telemetry.gauge(f"moe/{layer}/drop_fraction",
                         help="fraction of routed assignments dropped at "
                         "capacity")
    if g is not None:
        g.set(stats["drop_fraction"])
        _telemetry.gauge(f"moe/{layer}/load_imbalance",
                         help="max/mean tokens per expert").set(
                             stats["load_imbalance"])
        _telemetry.gauge(f"moe/{layer}/aux_loss",
                         help="load-balance aux loss").set(stats["aux_loss"])
        if util:
            _telemetry.gauge(
                f"moe/{layer}/expert_util",
                help="mean per-expert capacity utilization "
                "(post-drop tokens / capacity)").set(
                    sum(util) / len(util))
            _telemetry.gauge(
                f"moe/{layer}/expert_util_max",
                help="max per-expert capacity utilization").set(max(util))
        c = _telemetry.counter(f"moe/{layer}/overflow_tokens",
                               help="token assignments dropped at capacity")
        if stats["overflow_tokens"] > 0:
            c.inc(stats["overflow_tokens"])


def record_routing(layer, k, combine, dispatch, exp_counts, l_aux):
    """Emit one MoE layer's routed-token accounting onto the telemetry
    spine: drop-fraction (dropped assignments / T·k), overflow token count,
    expert-load imbalance (max/mean tokens per expert, post-drop) and the
    aux loss.  Zero overhead while telemetry is off (one attribute read);
    inside manual regions the values would be per-shard, so recording is
    skipped there."""
    if not _telemetry.enabled:
        return
    from ..utils import jax_compat
    if jax_compat.inside_axis_context():
        return  # per-shard values; the GSPMD path records the global view
    T = dispatch.shape[0]
    kept = jnp.sum(dispatch.astype(jnp.float32))
    total = jnp.float32(max(1, T * k))
    drop = 1.0 - kept / total
    overflow = total - kept
    counts = exp_counts.astype(jnp.float32)
    mean = jnp.maximum(jnp.mean(counts), 1e-9)
    imbalance = jnp.max(counts) / mean
    # per-expert capacity utilization: the POST-DROP slot occupancy of
    # each expert's [C] buffer (dispatch sums per expert / C) — counts may
    # exceed C pre-drop, occupancy cannot
    C = max(1, dispatch.shape[-1])
    occupancy = jnp.sum(dispatch.astype(jnp.float32), axis=(0, 2)) / C
    jax.debug.callback(_stats_sink, layer, k, drop, overflow, imbalance,
                       jnp.asarray(l_aux, jnp.float32), occupancy)

