"""``ds_io`` / ``ds_nvme_tune`` — aio parameter sweep.

Reference ``deepspeed/nvme/perf_run_sweep.py``: benchmark read/write GB/s
across (block_size, queue_depth, thread_count) and report the best config
for the swap subsystem.
"""

import argparse
import itertools
import json
import os
import tempfile
import time

import numpy as np

from ..utils.logging import logger


def _bench_config(path, size_mb, block_size, queue_depth, threads,
                  engine="threads", o_direct=False):
    from ..ops.aio import AIOHandle, aio_aligned_empty
    h = AIOHandle(block_size=block_size, queue_depth=queue_depth,
                  thread_count=threads, engine=engine, o_direct=o_direct)
    data = aio_aligned_empty((size_mb << 20, ), np.uint8)
    data[:] = np.random.default_rng(0).integers(
        0, 255, size_mb << 20, dtype=np.uint8)
    t0 = time.perf_counter()
    h.write(data, path)
    t_write = time.perf_counter() - t0
    buf = aio_aligned_empty((size_mb << 20, ), np.uint8)
    t0 = time.perf_counter()
    h.read(buf, path)
    t_read = time.perf_counter() - t0
    assert (buf[:1024] == data[:1024]).all()
    gb = size_mb / 1024
    return {"engine": h.engine, "block_size": block_size,
            "queue_depth": queue_depth, "threads": threads,
            "o_direct": o_direct, "write_gbps": gb / t_write,
            "read_gbps": gb / t_read}


def run_sweep(nvme_dir=None, size_mb=64,
              block_sizes=(256 << 10, 1 << 20, 8 << 20),
              queue_depths=(8, 32), thread_counts=(2, 4, 8),
              engine="all", o_direct=False):
    """Sweep (engine, block_size, queue_depth, threads).  ``engine="all"``
    covers the io_uring engine (when the kernel allows) AND the thread
    pool — the reference's perf_run_sweep sweeps single_submit/
    overlap_events the same way; here the engine axis replaces those."""
    from ..ops.aio import uring_available
    nvme_dir = nvme_dir or tempfile.gettempdir()
    path = os.path.join(nvme_dir, "ds_io_sweep.bin")
    if engine == "all":
        engines = ["threads"] + (["uring"] if uring_available() else [])
    elif engine == "auto":
        # resolve before the sweep so the uring thread-axis dedup below
        # sees the literal engine name
        engines = ["uring" if uring_available() else "threads"]
    else:
        engines = [engine]
    results = []
    try:
        for eng, bs, qd, tc in itertools.product(engines, block_sizes,
                                                 queue_depths,
                                                 thread_counts):
            if eng == "uring" and tc != thread_counts[0]:
                continue  # uring has no thread axis; sweep it once
            r = _bench_config(path, size_mb, bs, qd, tc, engine=eng,
                              o_direct=o_direct)
            results.append(r)
            logger.info("aio sweep: %s", r)
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
    best = max(results, key=lambda r: r["read_gbps"] + r["write_gbps"])
    return {"results": results, "best": best}


def sweep_main():
    parser = argparse.ArgumentParser(description="aio/NVMe perf sweep")
    parser.add_argument("--nvme_dir", default=None)
    parser.add_argument("--size_mb", type=int, default=64)
    parser.add_argument("--engine", default="all",
                        choices=("all", "uring", "threads", "auto"))
    parser.add_argument("--o_direct", action="store_true")
    parser.add_argument("--full", action="store_true",
                        help="print every config, not just the best")
    args = parser.parse_args()
    out = run_sweep(args.nvme_dir, args.size_mb, engine=args.engine,
                    o_direct=args.o_direct)
    print(json.dumps(out if args.full else out["best"], indent=2))


if __name__ == "__main__":
    sweep_main()
