"""``ds_io`` / ``ds_nvme_tune`` — aio parameter sweep.

Reference ``deepspeed/nvme/perf_run_sweep.py``: benchmark read/write GB/s
across (block_size, queue_depth, thread_count) and report the best config
for the swap subsystem.
"""

import argparse
import itertools
import json
import os
import tempfile
import time

import numpy as np

from ..utils.logging import logger


def _bench_config(path, size_mb, block_size, queue_depth, threads):
    from ..ops.aio import AIOHandle
    h = AIOHandle(block_size=block_size, queue_depth=queue_depth,
                  thread_count=threads)
    data = np.random.default_rng(0).integers(
        0, 255, size_mb << 20, dtype=np.uint8)
    t0 = time.perf_counter()
    h.write(data, path)
    t_write = time.perf_counter() - t0
    buf = np.empty_like(data)
    t0 = time.perf_counter()
    h.read(buf, path)
    t_read = time.perf_counter() - t0
    assert (buf[:1024] == data[:1024]).all()
    gb = size_mb / 1024
    return {"block_size": block_size, "queue_depth": queue_depth,
            "threads": threads, "write_gbps": gb / t_write,
            "read_gbps": gb / t_read}


def run_sweep(nvme_dir=None, size_mb=64,
              block_sizes=(256 << 10, 1 << 20, 8 << 20),
              queue_depths=(8, 32), thread_counts=(2, 4, 8)):
    nvme_dir = nvme_dir or tempfile.gettempdir()
    path = os.path.join(nvme_dir, "ds_io_sweep.bin")
    results = []
    try:
        for bs, qd, tc in itertools.product(block_sizes, queue_depths,
                                            thread_counts):
            r = _bench_config(path, size_mb, bs, qd, tc)
            results.append(r)
            logger.info("aio sweep: %s", r)
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
    best = max(results, key=lambda r: r["read_gbps"] + r["write_gbps"])
    return {"results": results, "best": best}


def sweep_main():
    parser = argparse.ArgumentParser(description="aio/NVMe perf sweep")
    parser.add_argument("--nvme_dir", default=None)
    parser.add_argument("--size_mb", type=int, default=64)
    args = parser.parse_args()
    out = run_sweep(args.nvme_dir, args.size_mb)
    print(json.dumps(out["best"], indent=2))


if __name__ == "__main__":
    sweep_main()
