from .perf_sweep import run_sweep, sweep_main
