"""CPU accelerator (JAX CPU backend).

Analog of reference ``accelerator/cpu_accelerator.py:19``.  Used for unit tests
(virtual 8-device CPU mesh via ``--xla_force_host_platform_device_count``) and
for BASELINE config 1 (BERT-base ZeRO-0 fp32 CPU).
"""

import os

from .abstract_accelerator import DeepSpeedAccelerator


class CPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "gloo"
        self._compile_backend = "xla"
        self._current_device_index = 0
        self._initial_seed = 42

    def _jax(self):
        import jax
        return jax

    def _local_devices(self):
        jax = self._jax()
        return [d for d in jax.local_devices() if d.platform == "cpu"] or jax.local_devices()

    # ------------------------------------------------------------------ device
    def is_synchronized_device(self):
        return False

    def device_name(self, device_index=None):
        return "cpu" if device_index is None else f"cpu:{device_index}"

    def device(self, device_index=None):
        devs = self._local_devices()
        return devs[self._current_device_index if device_index is None else device_index]

    def set_device(self, device_index):
        self._current_device_index = device_index

    def current_device(self):
        return self._current_device_index

    def current_device_name(self):
        return f"cpu:{self._current_device_index}"

    def device_count(self):
        return len(self._local_devices())

    def global_device_count(self):
        return self._jax().device_count()

    def synchronize(self, device_index=None):
        (self._jax().device_put(0.0) + 0).block_until_ready()

    # --------------------------------------------------------------------- RNG
    def random_key(self, seed):
        return self._jax().random.PRNGKey(seed)

    def manual_seed(self, seed):
        self._initial_seed = seed

    def initial_seed(self):
        return self._initial_seed

    # ------------------------------------------------------------------ memory
    def memory_stats(self, device_index=None):
        try:
            import psutil
            vm = psutil.virtual_memory()
            return {"bytes_in_use": vm.used, "bytes_limit": vm.total}
        except Exception:
            return {}

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def reset_peak_memory_stats(self, device_index=None):
        return None

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    def empty_cache(self):
        return None

    # ---------------------------------------------------------------- dtypes
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16]

    def preferred_dtype(self):
        import jax.numpy as jnp
        return jnp.float32

    # ------------------------------------------------------------------- comm
    def communication_backend_name(self):
        return self._communication_backend_name

    # -------------------------------------------------------------- op builder
    def create_op_builder(self, op_name):
        builder = self.get_op_builder(op_name)
        return builder() if builder is not None else None

    def get_op_builder(self, op_name):
        from ..ops.op_builder import get_op_builder_class
        return get_op_builder_class(op_name, accelerator_name=self._name)

    # ------------------------------------------------------------------- misc
    def is_available(self):
        return True

    def range_push(self, msg):
        return None

    def range_pop(self):
        return None

    def visible_devices_envs(self):
        return []
