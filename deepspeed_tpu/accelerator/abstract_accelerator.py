"""Accelerator abstraction (L0).

TPU-first re-design of the reference's ``accelerator/abstract_accelerator.py:10``
(``DeepSpeedAccelerator`` ABC).  Every other layer acquires hardware services
through :func:`deepspeed_tpu.accelerator.get_accelerator` — device handles, RNG,
memory statistics, dtype support, communication backend name, and kernel
("op builder") availability.

Differences from the reference, by design:
  * no streams/events API — XLA owns scheduling; we expose ``synchronize()``
    (block_until_ready) and async semantics come from jax dispatch;
  * tensor-factory helpers return jax arrays, and ``device()`` returns
    ``jax.Device`` objects;
  * ``communication_backend_name()`` is "ici" on TPU, "gloo" on CPU — the comm
    layer maps both onto mesh collectives.
"""

import abc
from abc import ABC


class DeepSpeedAccelerator(ABC):
    """Surface mirroring reference ``accelerator/abstract_accelerator.py``."""

    def __init__(self):
        self._name = None
        self._communication_backend_name = None
        self._compile_backend = None

    # ------------------------------------------------------------------ device
    @abc.abstractmethod
    def is_synchronized_device(self):
        ...

    @abc.abstractmethod
    def device_name(self, device_index=None):
        ...

    @abc.abstractmethod
    def device(self, device_index=None):
        ...

    @abc.abstractmethod
    def set_device(self, device_index):
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    @abc.abstractmethod
    def current_device_name(self):
        ...

    @abc.abstractmethod
    def device_count(self):
        ...

    @abc.abstractmethod
    def global_device_count(self):
        ...

    @abc.abstractmethod
    def synchronize(self, device_index=None):
        ...

    # --------------------------------------------------------------------- RNG
    @abc.abstractmethod
    def random_key(self, seed):
        """Return a jax PRNG key for ``seed`` (replaces torch RNG state APIs)."""
        ...

    @abc.abstractmethod
    def manual_seed(self, seed):
        ...

    @abc.abstractmethod
    def initial_seed(self):
        ...

    # ------------------------------------------------------------------ memory
    @abc.abstractmethod
    def memory_stats(self, device_index=None):
        ...

    @abc.abstractmethod
    def memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def reset_peak_memory_stats(self, device_index=None):
        ...

    @abc.abstractmethod
    def total_memory(self, device_index=None):
        ...

    @abc.abstractmethod
    def available_memory(self, device_index=None):
        ...

    @abc.abstractmethod
    def empty_cache(self):
        ...

    # ---------------------------------------------------------------- dtypes
    @abc.abstractmethod
    def is_bf16_supported(self):
        ...

    @abc.abstractmethod
    def is_fp16_supported(self):
        ...

    @abc.abstractmethod
    def supported_dtypes(self):
        ...

    @abc.abstractmethod
    def preferred_dtype(self):
        ...

    # ------------------------------------------------------------------- comm
    @abc.abstractmethod
    def communication_backend_name(self):
        ...

    # -------------------------------------------------------------- op builder
    @abc.abstractmethod
    def create_op_builder(self, op_name):
        ...

    @abc.abstractmethod
    def get_op_builder(self, op_name):
        ...

    # ------------------------------------------------------------------- misc
    @abc.abstractmethod
    def is_available(self):
        ...

    @abc.abstractmethod
    def range_push(self, msg):
        ...

    @abc.abstractmethod
    def range_pop(self):
        ...

    @abc.abstractmethod
    def visible_devices_envs(self):
        ...

    def set_visible_devices_envs(self, current_env, local_accelerator_ids):
        """Reference ``abstract_accelerator.py:297`` — used by the launcher to
        pin each spawned process to its chips."""
        for env in self.visible_devices_envs():
            current_env[env] = ",".join(map(str, local_accelerator_ids))
