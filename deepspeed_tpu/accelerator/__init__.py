from .abstract_accelerator import DeepSpeedAccelerator
from .real_accelerator import get_accelerator, set_accelerator, set_accelerator_name

__all__ = [
    "DeepSpeedAccelerator",
    "get_accelerator",
    "set_accelerator",
    "set_accelerator_name",
]
