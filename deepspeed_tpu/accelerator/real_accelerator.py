"""Accelerator selection.

Analog of reference ``accelerator/real_accelerator.py:51`` (``get_accelerator``):
explicit override via ``DS_ACCELERATOR`` env var, else auto-detect (TPU if jax
sees TPU devices, else CPU).
"""

import os

from ..utils.logging import logger

_accelerator = None

_ACCELERATOR_NAMES = ("tpu", "cpu")


def _validate_accelerator_name(name):
    if name not in _ACCELERATOR_NAMES:
        raise ValueError(
            f"DS_ACCELERATOR must be one of {_ACCELERATOR_NAMES}, got {name!r}")


def get_accelerator():
    global _accelerator
    if _accelerator is not None:
        return _accelerator

    name = os.environ.get("DS_ACCELERATOR")
    if name is not None:
        _validate_accelerator_name(name)
    else:
        # Auto-detect: prefer TPU when jax is on a TPU platform.  JAX_PLATFORMS
        # is honored implicitly because jax.devices() reflects it.
        try:
            import jax
            platforms = {d.platform for d in jax.devices()}
            name = "tpu" if "tpu" in platforms else "cpu"
        except Exception:
            name = "cpu"

    set_accelerator_name(name)
    return _accelerator


def set_accelerator_name(name):
    """Install the accelerator singleton by name (test hook)."""
    global _accelerator
    _validate_accelerator_name(name)
    if name == "tpu":
        from .tpu_accelerator import TPU_Accelerator
        _accelerator = TPU_Accelerator()
    else:
        from .cpu_accelerator import CPU_Accelerator
        _accelerator = CPU_Accelerator()
    logger.debug(f"Setting accelerator to {name}")
    return _accelerator


def set_accelerator(accel):
    global _accelerator
    _accelerator = accel
    return _accelerator
