"""TPU accelerator (JAX/XLA backed).

The TPU answer to the reference's ``accelerator/cuda_accelerator.py:24``
(``CUDA_Accelerator``).  Memory statistics come from
``jax.Device.memory_stats()``; RNG is jax's functional PRNG; the communication
backend name is "ici" (intra-slice interconnect), consumed by
``deepspeed_tpu.comm`` the way the reference consumes "nccl"
(``abstract_accelerator.py:201``).
"""

import os

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "ici"
        self._compile_backend = "xla"
        self._current_device_index = 0
        self._initial_seed = 42

    # Lazy jax import so that accelerator selection never forces TPU runtime
    # bring-up (mirrors how the reference guards torch.cuda calls).
    def _jax(self):
        import jax
        return jax

    def _local_devices(self):
        jax = self._jax()
        return jax.local_devices()

    # ------------------------------------------------------------------ device
    def is_synchronized_device(self):
        # jax dispatch is async; arrays must be waited on explicitly.
        return False

    def device_name(self, device_index=None):
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index=None):
        devs = self._local_devices()
        return devs[self._current_device_index if device_index is None else device_index]

    def set_device(self, device_index):
        self._current_device_index = device_index

    def current_device(self):
        return self._current_device_index

    def current_device_name(self):
        return f"tpu:{self._current_device_index}"

    def device_count(self):
        return len(self._local_devices())

    def global_device_count(self):
        return self._jax().device_count()

    def synchronize(self, device_index=None):
        # Block until all outstanding XLA work on this process is complete.
        jax = self._jax()
        (jax.device_put(0.0) + 0).block_until_ready()

    # --------------------------------------------------------------------- RNG
    def random_key(self, seed):
        jax = self._jax()
        return jax.random.PRNGKey(seed)

    def manual_seed(self, seed):
        self._initial_seed = seed

    def initial_seed(self):
        return self._initial_seed

    # ------------------------------------------------------------------ memory
    def memory_stats(self, device_index=None):
        dev = self.device(device_index)
        stats = dev.memory_stats()
        return stats if stats is not None else {}

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("peak_bytes_in_use", 0)

    def reset_peak_memory_stats(self, device_index=None):
        # XLA does not expose a reset; callers diff snapshots instead.
        return None

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    def empty_cache(self):
        return None

    # ---------------------------------------------------------------- dtypes
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        # TPUs compute natively in bf16; fp16 storage is supported, and the
        # fp16 dynamic-loss-scale path is kept for config parity.
        return True

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.float8_e4m3fn]

    def preferred_dtype(self):
        import jax.numpy as jnp
        return jnp.bfloat16

    # ------------------------------------------------------------------- comm
    def communication_backend_name(self):
        return self._communication_backend_name

    # -------------------------------------------------------------- op builder
    def create_op_builder(self, op_name):
        builder = self.get_op_builder(op_name)
        return builder() if builder is not None else None

    def get_op_builder(self, op_name):
        from ..ops.op_builder import get_op_builder_class
        return get_op_builder_class(op_name, accelerator_name=self._name)

    # ------------------------------------------------------------------- misc
    def is_available(self):
        try:
            jax = self._jax()
            return any(d.platform == "tpu" for d in jax.devices())
        except Exception:
            return False

    def range_push(self, msg):
        # Nested ranges form a stack (reference nvtx semantics).
        stack = getattr(self, "_trace_stack", None)
        if stack is None:
            stack = []
            self._trace_stack = stack
        try:
            import jax.profiler
            ctx = jax.profiler.TraceAnnotation(msg)
            ctx.__enter__()
            stack.append(ctx)
        except Exception:
            stack.append(None)

    def range_pop(self):
        stack = getattr(self, "_trace_stack", None)
        if stack:
            ctx = stack.pop()
            if ctx is not None:
                ctx.__exit__(None, None, None)

    def visible_devices_envs(self):
        return ["TPU_VISIBLE_CHIPS"]
