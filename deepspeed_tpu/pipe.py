"""Back-compat import path — reference tutorials spell
``from deepspeed.pipe import PipelineModule, LayerSpec``
(``deepspeed/pipe/__init__.py`` re-exports from ``runtime.pipe``)."""

from .runtime.pipe import (LayerSpec, PipelineModule,  # noqa: F401
                           TiedLayerSpec)
