from .monitor import MonitorMaster
