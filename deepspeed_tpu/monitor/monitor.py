"""Monitoring — analog of reference ``monitor/monitor.py:30`` (MonitorMaster
fan-out to TensorBoard / W&B / CSV).  Backends are optional; missing packages
degrade to disabled with a warning (reference behavior)."""

import csv
import os

from ..utils.logging import logger


class Monitor:

    def __init__(self, config):
        self.config = config
        self.enabled = getattr(config, "enabled", False)
        self._warned_non_scalar = set()

    def write_events(self, event_list):
        raise NotImplementedError

    def _scalarize(self, name, value):
        """Coerce an event value to float, or None with a LOUD warning (once
        per event name) — a stray tensor/string in an event list must not
        raise mid-train and kill the run it is observing."""
        try:
            return float(value)
        except (TypeError, ValueError):
            pass
        try:
            import numpy as np
            arr = np.asarray(value)
            if arr.size == 1:
                return float(arr.reshape(()))
        except Exception:
            pass
        if name not in self._warned_non_scalar:
            self._warned_non_scalar.add(name)
            logger.warning(
                "monitor: event %r has non-scalar value %r (%s); dropping "
                "it (and further values for this name silently)", name,
                value, type(value).__name__)
        return None


class TensorBoardMonitor(Monitor):

    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        self._writer_failed = False
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter  # noqa: F401
            except ImportError:
                logger.warning("tensorboard not available; disabling TB monitor")
                self.enabled = False

    def _ensure_writer(self):
        """Create the SummaryWriter (and its output directories) on first
        write, not at construction — a bad/unwritable ``output_path`` then
        degrades this backend instead of crashing engine bring-up."""
        if self.summary_writer is not None or self._writer_failed:
            return self.summary_writer
        try:
            from torch.utils.tensorboard import SummaryWriter
            out = os.path.join(self.config.output_path or "./runs",
                               self.config.job_name)
            os.makedirs(out, exist_ok=True)
            self.summary_writer = SummaryWriter(log_dir=out)
        except (ImportError, OSError) as e:
            logger.warning("tensorboard writer unavailable (%s: %s); "
                           "disabling TB monitor", type(e).__name__, e)
            self._writer_failed = True
            self.enabled = False
        return self.summary_writer

    def write_events(self, event_list, flush=True):
        if not self.enabled or self._ensure_writer() is None:
            return
        for name, value, step in event_list:
            value = self._scalarize(name, value)
            if value is not None:
                self.summary_writer.add_scalar(name, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, config):
        super().__init__(config)
        if self.enabled:
            try:
                import wandb
                wandb.init(project=config.project, group=config.group,
                           entity=config.team)
                self._wandb = wandb
            except ImportError:
                logger.warning("wandb not available; disabling wandb monitor")
                self.enabled = False

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            value = self._scalarize(name, value)
            if value is not None:
                self._wandb.log({name: value}, step=step)


class CometMonitor(Monitor):
    """Reference ``monitor/monitor.py`` CometMonitor: comet_ml experiment
    logging (optional dependency, degrades to disabled)."""

    def __init__(self, config):
        super().__init__(config)
        self.experiment = None
        if self.enabled:
            try:
                import comet_ml
                self.experiment = comet_ml.Experiment(
                    api_key=getattr(config, "api_key", None),
                    project_name=getattr(config, "project", None),
                    workspace=getattr(config, "workspace", None))
                name = getattr(config, "experiment_name", None)
                if name:
                    self.experiment.set_name(name)
            except Exception as e:
                # Experiment() also raises on bad/missing API keys or no
                # connectivity — a monitoring misconfig must not kill the
                # training run
                logger.warning("Comet monitor unavailable (%s: %s); "
                               "disabling", type(e).__name__, e)
                self.enabled = False

    def write_events(self, event_list):
        if self.experiment is None:
            return
        for name, value, step in event_list:
            value = self._scalarize(name, value)
            if value is not None:
                self.experiment.log_metric(name, value, step=step)


class csv_monitor(Monitor):

    def __init__(self, config):
        super().__init__(config)
        self._dir_ready = False
        if self.enabled:
            self.output_path = os.path.join(config.output_path or "./csv_logs",
                                            config.job_name)
            self._files = {}

    def write_events(self, event_list):
        if not self.enabled:
            return
        if not self._dir_ready:
            # first write, not __init__: an unwritable output_path degrades
            # this backend with a warning instead of crashing bring-up
            try:
                os.makedirs(self.output_path, exist_ok=True)
            except OSError as e:
                logger.warning("csv monitor output_path %r unusable (%s); "
                               "disabling", self.output_path, e)
                self.enabled = False
                return
            self._dir_ready = True
        for name, value, step in event_list:
            value = self._scalarize(name, value)
            if value is None:
                continue
            fname = os.path.join(self.output_path,
                                 name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


#: event-name prefix for the resilience subsystem's telemetry (skipped
#: poisoned steps, checkpoint rollbacks, watchdog restarts)
RESILIENCE_EVENT_PREFIX = "Train/Resilience/"


class MonitorMaster(Monitor):
    """Reference ``monitor/monitor.py:30``: dispatch to enabled backends."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard)
        self.wandb_monitor = WandbMonitor(monitor_config.wandb)
        self.comet_monitor = CometMonitor(monitor_config.comet)
        self.csv_monitor = csv_monitor(monitor_config.csv_monitor)
        self.enabled = (self.tb_monitor.enabled or self.wandb_monitor.enabled
                        or self.comet_monitor.enabled
                        or self.csv_monitor.enabled)

    def write_events(self, event_list):
        if self.tb_monitor.enabled:
            self.tb_monitor.write_events(event_list)
        if self.wandb_monitor.enabled:
            self.wandb_monitor.write_events(event_list)
        if self.comet_monitor.enabled:
            self.comet_monitor.write_events(event_list)
        if self.csv_monitor.enabled:
            self.csv_monitor.write_events(event_list)

    def write_resilience_events(self, pairs, step):
        """Resilience telemetry — ``pairs``: [(short_name, value), ...]
        written under ``Train/Resilience/`` so availability incidents
        (skipped poisoned steps, checkpoint rollbacks, watchdog kills) land
        on the same dashboards as the loss curve."""
        self.write_events([(RESILIENCE_EVENT_PREFIX + name, value, step)
                           for name, value in pairs])
