from .profiler import FlopsProfiler, get_model_profile, jaxpr_flops
