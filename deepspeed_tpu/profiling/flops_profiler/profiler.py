"""Flops profiler — TPU rebuild of reference
``profiling/flops_profiler/profiler.py`` (``FlopsProfiler`` :30,
``print_model_profile`` :286, analytic per-op flops :518+).

The reference patches ~50 torch functions and installs module hooks to count
MACs per submodule.  Under XLA the program is a jaxpr, so the profiler walks
the jaxpr instead: exact static shapes, no patching, and scan/remat bodies
are counted with their trip counts.  Two complementary sources:

* **analytic** — per-equation flop formulas (dot_general/conv/elementwise),
  grouped by the function name-stack → a per-module tree like the reference's
  module profile;
* **compiled** — ``jit(fn).lower().compile().cost_analysis()`` gives XLA's
  own flops + bytes-accessed estimate for the optimized HLO (post-fusion),
  the number the MFU/TFLOPS report should use.

Latency comes from timing the compiled step like ``ThroughputTimer``.
"""

import time
from collections import defaultdict

import numpy as np

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------- analytic
_ELEMENTWISE_1 = {
    "add", "sub", "mul", "div", "max", "min", "pow", "and", "or", "xor",
    "neg", "abs", "floor", "ceil", "round", "sign", "select_n",
    "clamp", "rem", "nextafter",
}
_ELEMENTWISE_TRANSCENDENTAL = {
    "exp", "log", "log1p", "expm1", "sin", "cos", "tan", "tanh", "logistic",
    "erf", "erfc", "erf_inv", "rsqrt", "sqrt", "cbrt", "atan2", "sigmoid",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "argmax", "argmin", "cumsum",
           "cumlogsumexp", "cummax", "cummin", "cumprod"}


def _out_size(eqn):
    if not eqn.outvars:
        return 0
    v = eqn.outvars[0]
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    return int(np.prod(aval.shape)) if aval.shape else 1


def _dot_general_flops(eqn):
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    batch = int(np.prod([a.shape[i] for i in lb])) if lb else 1
    contract = int(np.prod([a.shape[i] for i in lc])) if lc else 1
    m = int(np.prod([a.shape[i] for i in range(a.ndim)
                     if i not in set(lc) | set(lb)]))
    n = int(np.prod([b.shape[i] for i in range(b.ndim)
                     if i not in set(rc) | set(rb)]))
    return 2 * batch * m * n * contract


def _conv_flops(eqn):
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    out = eqn.outvars[0].aval
    fgc = eqn.params.get("feature_group_count", 1)
    # out_elems * (2 * kernel_spatial * in_channels/groups)
    kernel_elems = int(np.prod(rhs.shape[2:])) if rhs.ndim > 2 else 1
    # rhs layout: (out_c, in_c/g, *spatial) in dimension_numbers-normalized form
    in_c_per_group = rhs.shape[1] if rhs.ndim > 1 else 1
    return 2 * int(np.prod(out.shape)) * kernel_elems * in_c_per_group


def _eqn_flops(eqn):
    """(flops, macs) for one jaxpr equation."""
    prim = eqn.primitive.name
    if prim == "dot_general":
        f = _dot_general_flops(eqn)
        return f, f // 2
    if prim in ("conv_general_dilated", ):
        f = _conv_flops(eqn)
        return f, f // 2
    if prim in _ELEMENTWISE_1:
        return _out_size(eqn), 0
    if prim in _ELEMENTWISE_TRANSCENDENTAL:
        return 4 * _out_size(eqn), 0  # transcendental ≈ several flops each
    if prim in _REDUCE:
        size = eqn.invars[0].aval
        n = int(np.prod(size.shape)) if hasattr(size, "shape") and size.shape else 1
        return n, 0
    if prim == "integer_pow":
        return _out_size(eqn), 0
    return 0, 0


def _walk_jaxpr(jaxpr, scale=1, scope="", acc=None):
    """Recursively accumulate (flops, macs) per scope from a jaxpr."""
    if acc is None:
        acc = defaultdict(lambda: [0, 0])
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        # nested jaxprs
        if prim == "scan":
            inner = eqn.params["jaxpr"].jaxpr
            _walk_jaxpr(inner, scale * eqn.params.get("length", 1),
                        scope, acc)
            continue
        if prim == "while":
            inner = eqn.params["body_jaxpr"].jaxpr
            _walk_jaxpr(inner, scale, scope, acc)  # trip count unknown: 1×
            continue
        if prim == "cond":
            branches = eqn.params.get("branches", ())
            if branches:  # count the largest branch
                best = defaultdict(lambda: [0, 0])
                for br in branches:
                    tmp = _walk_jaxpr(br.jaxpr, scale, scope,
                                      defaultdict(lambda: [0, 0]))
                    if sum(v[0] for v in tmp.values()) > \
                            sum(v[0] for v in best.values()):
                        best = tmp
                for k, v in best.items():
                    acc[k][0] += v[0]
                    acc[k][1] += v[1]
            continue
        if prim in ("pjit", "closed_call", "custom_jvp_call",
                    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                    "checkpoint", "custom_partitioning", "shard_map"):
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
                or eqn.params.get("fun_jaxpr")
            if sub is not None:
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                name = eqn.params.get("name", "")
                sub_scope = f"{scope}/{name}" if name and name != "<lambda>" \
                    else scope
                _walk_jaxpr(inner, scale, sub_scope, acc)
            continue
        f, m = _eqn_flops(eqn)
        if f:
            # group by name stack when present (flax module scopes)
            st = str(eqn.source_info.name_stack) if hasattr(
                eqn.source_info, "name_stack") else ""
            key = f"{scope}/{st}" if st else (scope or "/")
            acc[key][0] += f * scale
            acc[key][1] += m * scale
    return acc


def jaxpr_flops(fn, *args, **kwargs):
    """(total_flops, total_macs, per_scope dict) for fn(*args) by analytic
    jaxpr walk."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    acc = _walk_jaxpr(closed.jaxpr)
    total_f = sum(v[0] for v in acc.values())
    total_m = sum(v[1] for v in acc.values())
    return total_f, total_m, {k: tuple(v) for k, v in acc.items()}


def _count_params(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "shape"))


def _num_fmt(n, suffix=""):
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}{suffix}"
    return f"{n:.2f} {suffix}"


class FlopsProfiler:
    """Profile a jitted step function (reference ``FlopsProfiler`` :30).

    Usage (module-style, mirrors reference start/stop API)::

        prof = FlopsProfiler(engine_or_fn)
        prof.start_profile()
        out = fn(*args)             # or engine.forward(...)
        prof.stop_profile(fn, args) # analyses the traced program
        prof.print_model_profile()
    """

    def __init__(self, target=None, ds_engine=None):
        self.target = target if target is not None else ds_engine
        self.flops = 0
        self.macs = 0
        self.params = 0
        self.latency = 0.0
        self.per_scope = {}
        self.xla_flops = None
        self.xla_bytes = None
        self.step_flops = None  # fused fwd+bwd+update count, when profiled
        self._started = None

    # -- reference API shape
    def start_profile(self, ignore_list=None):
        self._started = time.perf_counter()

    def stop_profile(self, fn=None, args=(), kwargs=None):
        if self._started is not None:
            self.latency = time.perf_counter() - self._started
            self._started = None
        if fn is not None:
            self.profile(fn, *args, **(kwargs or {}))

    def end_profile(self):
        pass

    def reset_profile(self):
        self.__init__(self.target)

    # -- core
    def profile(self, fn, *args, compile_xla=True, **kwargs):
        """Analytic jaxpr walk of ``fn`` (forward counts); ``compile_xla``
        additionally compiles for XLA's own post-fusion estimate — skip it
        when a compiled executable already exists (the engine path does)."""
        self.flops, self.macs, self.per_scope = jaxpr_flops(fn, *args, **kwargs)
        params = kwargs.get("params") if kwargs else None
        if params is None and args and isinstance(args[0], dict):
            params = args[0]
        self.params = _count_params(params) if params is not None else 0
        if compile_xla:
            try:
                compiled = jax.jit(fn).lower(*args, **kwargs).compile()
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                self.xla_flops = ca.get("flops")
                self.xla_bytes = ca.get("bytes accessed")
            except Exception:
                self.xla_flops = None
        return self.flops, self.macs, self.params

    def measure_latency(self, fn, *args, iters=3, **kwargs):
        compiled = jax.jit(fn)
        out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        self.latency = (time.perf_counter() - t0) / iters
        return self.latency

    # -- device trace capture (round-1 review item 10: the analytic walk
    # has no per-module *latency* tree; the TPU answer is an xplane trace —
    # flax module names survive into XLA metadata, so xprof/tensorboard
    # shows the per-module time breakdown the reference builds from hooks)
    def start_trace(self, trace_dir):
        import jax.profiler
        jax.profiler.start_trace(trace_dir)
        self._trace_dir = trace_dir
        return trace_dir

    def stop_trace(self):
        import jax.profiler
        jax.profiler.stop_trace()
        return getattr(self, "_trace_dir", None)

    def get_total_flops(self, as_string=False):
        return _num_fmt(self.flops, "FLOPs") if as_string else self.flops

    def get_total_macs(self, as_string=False):
        return _num_fmt(self.macs, "MACs") if as_string else self.macs

    def get_total_params(self, as_string=False):
        return _num_fmt(self.params, "") if as_string else self.params

    def get_total_duration(self, as_string=False):
        return f"{self.latency * 1e3:.2f} ms" if as_string else self.latency

    def print_model_profile(self, profile_step=None, module_depth=-1,
                            top_modules=10, detailed=True, output_file=None):
        """Reference ``print_model_profile`` :286 — summary + top scopes."""
        lines = ["", "-" * 70,
                 "DeepSpeed-TPU Flops Profiler",
                 "-" * 70]
        if profile_step is not None:
            lines.append(f"profile step:              {profile_step}")
        lines += [
            f"params:                    {self.get_total_params(True)}",
            f"fwd MACs (analytic):       {self.get_total_macs(True)}",
            f"fwd flops (analytic):      {self.get_total_flops(True)}",
        ]
        if self.step_flops:
            lines.append(f"train step flops (f+b+u):  {_num_fmt(self.step_flops, 'FLOPs')}")
        if self.xla_flops:
            lines.append(f"flops (XLA optimized):     {_num_fmt(self.xla_flops, 'FLOPs')}")
        if self.xla_bytes:
            lines.append(f"HBM bytes (XLA):           {_num_fmt(self.xla_bytes, 'B')}")
        if self.latency:
            lines.append(f"latency:                   {self.get_total_duration(True)}")
            tput = self.flops / self.latency if self.latency else 0
            lines.append(f"throughput:                {_num_fmt(tput, 'FLOPS')}")
        if detailed and self.per_scope:
            lines += ["", f"top {top_modules} scopes by flops:"]
            ranked = sorted(self.per_scope.items(), key=lambda kv: -kv[1][0])
            for scope, (f, m) in ranked[:top_modules]:
                lines.append(f"  {_num_fmt(f, 'FLOPs'):>14}  {scope}")
        lines.append("-" * 70)
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as fh:
                fh.write(text)
        else:
            print(text)
        return text


def get_model_profile(model, args=(), kwargs=None, print_profile=True,
                      detailed=True, warm_up=1, as_string=False,
                      output_file=None, ignore_modules=None):
    """Reference module-level ``get_model_profile`` — returns
    (flops, macs, params) for ``model(*args)``."""
    prof = FlopsProfiler(model)
    kwargs = kwargs or {}
    flops, macs, params = prof.profile(model, *args, **kwargs)
    try:
        prof.measure_latency(model, *args, **kwargs)
    except Exception:
        pass
    if print_profile:
        prof.print_model_profile(detailed=detailed, output_file=output_file)
    if as_string:
        return (prof.get_total_flops(True), prof.get_total_macs(True),
                prof.get_total_params(True))
    return flops, macs, params
