"""Flops profiler — the user-facing façade over ``profiling/cost_model``
(TPU rebuild of reference ``profiling/flops_profiler/profiler.py``:
``FlopsProfiler`` :30, ``print_model_profile`` :286).

The reference patches ~50 torch functions and installs module hooks to
count MACs per submodule.  Under XLA the program is a jaxpr/HLO, so the
canonical machinery lives in :mod:`deepspeed_tpu.profiling.cost_model`
since PR 14 and this module is its presentation layer.  Two sources:

* **analytic** (``cost_model.jaxpr_flops``) — per-equation flop formulas
  grouped by the flax name-stack → the per-module tree the reference
  builds from hooks;
* **compiled** (``cost_model.analyze_fn``) — XLA's own ``cost_analysis``
  (post-fusion flops + bytes accessed) and ``memory_analysis`` (static
  peak-HBM estimate) of the optimized executable — the numbers the
  MFU/TFLOPS report should use.  Absent on a backend → analytic fallback
  with a once-per-process warning (never raises).

Latency comes from timing the compiled step like ``ThroughputTimer``.
"""

import time

import numpy as np

import jax

# canonical home: cost_model (re-exported here for the public API and the
# engine's profile hook)
from ..cost_model import analyze_fn, jaxpr_flops  # noqa: F401


def _count_params(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "shape"))


def _num_fmt(n, suffix=""):
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}{suffix}"
    return f"{n:.2f} {suffix}"


class FlopsProfiler:
    """Profile a jitted step function (reference ``FlopsProfiler`` :30).

    Usage (module-style, mirrors reference start/stop API)::

        prof = FlopsProfiler(engine_or_fn)
        prof.start_profile()
        out = fn(*args)             # or engine.forward(...)
        prof.stop_profile(fn, args) # analyses the traced program
        prof.print_model_profile()
    """

    def __init__(self, target=None, ds_engine=None):
        self.target = target if target is not None else ds_engine
        self.flops = 0
        self.macs = 0
        self.params = 0
        self.latency = 0.0
        self.per_scope = {}
        self.xla_flops = None
        self.xla_bytes = None
        self.xla_peak_hbm = None
        self.step_flops = None  # fused fwd+bwd+update count, when profiled
        self._started = None

    # -- reference API shape
    def start_profile(self, ignore_list=None):
        self._started = time.perf_counter()

    def stop_profile(self, fn=None, args=(), kwargs=None):
        if self._started is not None:
            self.latency = time.perf_counter() - self._started
            self._started = None
        if fn is not None:
            self.profile(fn, *args, **(kwargs or {}))

    def end_profile(self):
        pass

    def reset_profile(self):
        self.__init__(self.target)

    # -- core
    def profile(self, fn, *args, compile_xla=True, **kwargs):
        """Analytic jaxpr walk of ``fn`` (forward counts); ``compile_xla``
        additionally compiles for XLA's own post-fusion estimate — skip it
        when a compiled executable already exists (the engine path does:
        its programs land in ``cost_model.registry()`` at compile time)."""
        self.flops, self.macs, self.per_scope = jaxpr_flops(fn, *args, **kwargs)
        params = kwargs.get("params") if kwargs else None
        if params is None and args and isinstance(args[0], dict):
            params = args[0]
        self.params = _count_params(params) if params is not None else 0
        if compile_xla:
            analysis = analyze_fn(fn, *args, **kwargs)
            self.xla_flops = analysis.get("flops")
            self.xla_bytes = analysis.get("bytes_accessed")
            self.xla_peak_hbm = analysis.get("peak_hbm_bytes")
        return self.flops, self.macs, self.params

    def measure_latency(self, fn, *args, iters=3, **kwargs):
        compiled = jax.jit(fn)
        out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        self.latency = (time.perf_counter() - t0) / iters
        return self.latency

    # -- device trace capture (round-1 review item 10: the analytic walk
    # has no per-module *latency* tree; the TPU answer is an xplane trace —
    # flax module names survive into XLA metadata, so xprof/tensorboard
    # shows the per-module time breakdown the reference builds from hooks)
    def start_trace(self, trace_dir):
        import jax.profiler
        jax.profiler.start_trace(trace_dir)
        self._trace_dir = trace_dir
        return trace_dir

    def stop_trace(self):
        import jax.profiler
        jax.profiler.stop_trace()
        return getattr(self, "_trace_dir", None)

    def get_total_flops(self, as_string=False):
        return _num_fmt(self.flops, "FLOPs") if as_string else self.flops

    def get_total_macs(self, as_string=False):
        return _num_fmt(self.macs, "MACs") if as_string else self.macs

    def get_total_params(self, as_string=False):
        return _num_fmt(self.params, "") if as_string else self.params

    def get_total_duration(self, as_string=False):
        return f"{self.latency * 1e3:.2f} ms" if as_string else self.latency

    def print_model_profile(self, profile_step=None, module_depth=-1,
                            top_modules=10, detailed=True, output_file=None):
        """Reference ``print_model_profile`` :286 — summary + top scopes."""
        lines = ["", "-" * 70,
                 "DeepSpeed-TPU Flops Profiler",
                 "-" * 70]
        if profile_step is not None:
            lines.append(f"profile step:              {profile_step}")
        lines += [
            f"params:                    {self.get_total_params(True)}",
            f"fwd MACs (analytic):       {self.get_total_macs(True)}",
            f"fwd flops (analytic):      {self.get_total_flops(True)}",
        ]
        if self.step_flops:
            lines.append(f"train step flops (f+b+u):  {_num_fmt(self.step_flops, 'FLOPs')}")
        if self.xla_flops:
            lines.append(f"flops (XLA optimized):     {_num_fmt(self.xla_flops, 'FLOPs')}")
        if self.xla_bytes:
            lines.append(f"HBM bytes (XLA):           {_num_fmt(self.xla_bytes, 'B')}")
        if self.xla_peak_hbm:
            lines.append(f"static peak HBM (XLA):     {_num_fmt(self.xla_peak_hbm, 'B')}")
        if self.latency:
            lines.append(f"latency:                   {self.get_total_duration(True)}")
            tput = self.flops / self.latency if self.latency else 0
            lines.append(f"throughput:                {_num_fmt(tput, 'FLOPS')}")
        if detailed and self.per_scope:
            lines += ["", f"top {top_modules} scopes by flops:"]
            ranked = sorted(self.per_scope.items(), key=lambda kv: -kv[1][0])
            for scope, (f, m) in ranked[:top_modules]:
                lines.append(f"  {_num_fmt(f, 'FLOPs'):>14}  {scope}")
        lines.append("-" * 70)
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as fh:
                fh.write(text)
        else:
            print(text)
        return text


def get_model_profile(model, args=(), kwargs=None, print_profile=True,
                      detailed=True, warm_up=1, as_string=False,
                      output_file=None, ignore_modules=None):
    """Reference module-level ``get_model_profile`` — returns
    (flops, macs, params) for ``model(*args)``."""
    prof = FlopsProfiler(model)
    kwargs = kwargs or {}
    flops, macs, params = prof.profile(model, *args, **kwargs)
    try:
        prof.measure_latency(model, *args, **kwargs)
    except Exception:
        pass
    if print_profile:
        prof.print_model_profile(detailed=detailed, output_file=output_file)
    if as_string:
        return (prof.get_total_flops(True), prof.get_total_macs(True),
                prof.get_total_params(True))
    return flops, macs, params
