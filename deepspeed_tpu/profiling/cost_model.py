"""Compiled-cost observability: XLA's own cost model on the telemetry spine.

On TPU the two numbers every training report leads with — model-FLOPs
utilization and HBM headroom — are free: the compiled executable already
knows them.  ``jit(fn).lower(...).compile()`` exposes

* ``cost_analysis()`` — XLA's post-fusion flop and bytes-accessed estimate
  of the optimized per-device program (the number MFU should use, not an
  analytic pre-fusion walk);
* ``memory_analysis()`` — argument / output / temp / generated-code bytes
  of the per-device program, i.e. a **static peak-HBM estimate** available
  at compile time, before the first step can OOM.

This module captures both **once per compile** for every program the stack
owns (training micro-step and its overlap/prefetch/qgZ variants, the
boundary apply-update, serving prefill/decode) into a process-wide
:class:`CostModelRegistry`, with zero steady-state overhead: nothing runs
per step, only per compile.  The engine feeds the registry into the
telemetry spine (``mfu`` on step records, the compiled-programs table in
``tools/trace_report.py``) and a loud once-per-program OOM-margin warning
fires when the static estimate approaches ``total_memory()``.

Degradation contract (tier-1 runs on the pinned CPU jaxlib): when
``cost_analysis()`` / ``memory_analysis()`` are absent or raise, the
capture falls back to the analytic jaxpr flop walk below (the pre-PR-14
``flops_profiler`` machinery, now canonically homed here) with a
once-per-process warning — it never raises into a training step.

``flops_profiler/`` is a façade over this module since PR 14.
"""

import os
import time
from collections import defaultdict

import numpy as np

from ..utils.logging import logger

# --------------------------------------------------------------- peak FLOPS
#: per-chip peak dense FLOP/s by device kind (bf16 matmul peak — the MFU
#: convention of TPU training reports).  Matched by lowercase substring,
#: longest match wins; override with DS_TPU_PEAK_FLOPS (float, FLOP/s).
PEAK_FLOPS_BY_KIND = (
    ("tpu v6", 918e12),      # Trillium / v6e
    ("tpu v5p", 459e12),
    ("tpu v5 lite", 197e12),  # v5e device_kind spelling
    ("tpu v5e", 197e12),
    ("tpu v5", 459e12),
    ("tpu v4", 275e12),
    ("tpu v3", 123e12),
    ("tpu v2", 46e12),
    # nominal host-CPU figure so CPU smoke runs report a *finite* MFU; a
    # few AVX cores land within an order of magnitude of this.  Not a
    # benchmarking claim — set DS_TPU_PEAK_FLOPS to calibrate.
    ("cpu", 1e11),
)

PEAK_FLOPS_ENV = "DS_TPU_PEAK_FLOPS"

_DEFAULT_PEAK = 1e12   # unknown accelerator: nominal 1 TFLOP/s, warned once
_peak_warned = False


def peak_flops_per_chip():
    """Per-chip peak FLOP/s from the device table, ``DS_TPU_PEAK_FLOPS``
    winning over it.  Unknown device kinds get a nominal figure with a
    once-per-process warning (MFU stays finite, never garbage-infinite)."""
    global _peak_warned
    env = os.environ.get(PEAK_FLOPS_ENV)
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
        logger.warning("%s=%r is not a positive float — falling back to "
                       "the device table", PEAK_FLOPS_ENV, env)
    import jax
    dev = jax.devices()[0]
    kind = f"{dev.platform} {getattr(dev, 'device_kind', '')}".lower()
    best, best_len = None, -1
    for frag, peak in PEAK_FLOPS_BY_KIND:
        if frag in kind and len(frag) > best_len:
            best, best_len = peak, len(frag)
    if best is not None:
        return best
    if not _peak_warned:
        _peak_warned = True
        logger.warning(
            "no peak-FLOPS table entry for device kind %r — MFU uses a "
            "nominal %g FLOP/s; set %s for a calibrated figure",
            kind, _DEFAULT_PEAK, PEAK_FLOPS_ENV)
    return _DEFAULT_PEAK


# ------------------------------------------------------ analytic jaxpr walk
# (moved here from flops_profiler/profiler.py — the fallback when the
# compiled cost model is unavailable, and the per-scope module breakdown)
_ELEMENTWISE_1 = {
    "add", "sub", "mul", "div", "max", "min", "pow", "and", "or", "xor",
    "neg", "abs", "floor", "ceil", "round", "sign", "select_n",
    "clamp", "rem", "nextafter",
}
_ELEMENTWISE_TRANSCENDENTAL = {
    "exp", "log", "log1p", "expm1", "sin", "cos", "tan", "tanh", "logistic",
    "erf", "erfc", "erf_inv", "rsqrt", "sqrt", "cbrt", "atan2", "sigmoid",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "argmax", "argmin", "cumsum",
           "cumlogsumexp", "cummax", "cummin", "cumprod"}


def _out_size(eqn):
    if not eqn.outvars:
        return 0
    v = eqn.outvars[0]
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    return int(np.prod(aval.shape)) if aval.shape else 1


def _dot_general_flops(eqn):
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    batch = int(np.prod([a.shape[i] for i in lb])) if lb else 1
    contract = int(np.prod([a.shape[i] for i in lc])) if lc else 1
    m = int(np.prod([a.shape[i] for i in range(a.ndim)
                     if i not in set(lc) | set(lb)]))
    n = int(np.prod([b.shape[i] for i in range(b.ndim)
                     if i not in set(rc) | set(rb)]))
    return 2 * batch * m * n * contract


def _conv_flops(eqn):
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    # out_elems * (2 * kernel_spatial * in_channels/groups); rhs layout
    # (out_c, in_c/g, *spatial) in dimension_numbers-normalized form
    kernel_elems = int(np.prod(rhs.shape[2:])) if rhs.ndim > 2 else 1
    in_c_per_group = rhs.shape[1] if rhs.ndim > 1 else 1
    return 2 * int(np.prod(out.shape)) * kernel_elems * in_c_per_group


def _eqn_flops(eqn):
    """(flops, macs) for one jaxpr equation."""
    prim = eqn.primitive.name
    if prim == "dot_general":
        f = _dot_general_flops(eqn)
        return f, f // 2
    if prim in ("conv_general_dilated", ):
        f = _conv_flops(eqn)
        return f, f // 2
    if prim in _ELEMENTWISE_1:
        return _out_size(eqn), 0
    if prim in _ELEMENTWISE_TRANSCENDENTAL:
        return 4 * _out_size(eqn), 0  # transcendental ≈ several flops each
    if prim in _REDUCE:
        size = eqn.invars[0].aval
        n = int(np.prod(size.shape)) if hasattr(size, "shape") and size.shape else 1
        return n, 0
    if prim == "integer_pow":
        return _out_size(eqn), 0
    return 0, 0


def _walk_jaxpr(jaxpr, scale=1, scope="", acc=None):
    """Recursively accumulate (flops, macs) per scope from a jaxpr."""
    if acc is None:
        acc = defaultdict(lambda: [0, 0])
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        # nested jaxprs
        if prim == "scan":
            inner = eqn.params["jaxpr"].jaxpr
            _walk_jaxpr(inner, scale * eqn.params.get("length", 1),
                        scope, acc)
            continue
        if prim == "while":
            inner = eqn.params["body_jaxpr"].jaxpr
            _walk_jaxpr(inner, scale, scope, acc)  # trip count unknown: 1×
            continue
        if prim == "cond":
            branches = eqn.params.get("branches", ())
            if branches:  # count the largest branch
                best = defaultdict(lambda: [0, 0])
                for br in branches:
                    tmp = _walk_jaxpr(br.jaxpr, scale, scope,
                                      defaultdict(lambda: [0, 0]))
                    if sum(v[0] for v in tmp.values()) > \
                            sum(v[0] for v in best.values()):
                        best = tmp
                for k, v in best.items():
                    acc[k][0] += v[0]
                    acc[k][1] += v[1]
            continue
        if prim in ("pjit", "closed_call", "custom_jvp_call",
                    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                    "checkpoint", "custom_partitioning", "shard_map"):
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
                or eqn.params.get("fun_jaxpr")
            if sub is not None:
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                name = eqn.params.get("name", "")
                sub_scope = f"{scope}/{name}" if name and name != "<lambda>" \
                    else scope
                _walk_jaxpr(inner, scale, sub_scope, acc)
            continue
        f, m = _eqn_flops(eqn)
        if f:
            # group by name stack when present (flax module scopes)
            st = str(eqn.source_info.name_stack) if hasattr(
                eqn.source_info, "name_stack") else ""
            key = f"{scope}/{st}" if st else (scope or "/")
            acc[key][0] += f * scale
            acc[key][1] += m * scale
    return acc


def jaxpr_flops(fn, *args, **kwargs):
    """(total_flops, total_macs, per_scope dict) for fn(*args) by analytic
    jaxpr walk — the fallback flop counter and the per-module breakdown
    (XLA's cost model has no module tree; flax name stacks do)."""
    import jax
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    acc = _walk_jaxpr(closed.jaxpr)
    total_f = sum(v[0] for v in acc.values())
    total_m = sum(v[1] for v in acc.values())
    return total_f, total_m, {k: tuple(v) for k, v in acc.items()}


# ------------------------------------------------------------ compiled cost
_absence_warned = set()   # which degradation classes warned already


def _warn_absent(what, err=None):
    """Once-per-process (per degradation class) note that the compiled cost
    model is unavailable — the flop-counting fallback takes over."""
    if what in _absence_warned:
        return
    _absence_warned.add(what)
    logger.warning(
        "compiled cost model: %s unavailable on this backend%s — "
        "falling back to analytic flop counting (MFU/HBM figures degrade "
        "to estimates or None; expected on older jaxlib/CPU pins)",
        what, f" ({err})" if err else "")


def analyze_compiled(compiled):
    """Extract {flops, bytes_accessed, *_bytes, peak_hbm_bytes} from a
    ``Compiled`` object.  Per-DEVICE numbers (the compiled executable is
    the per-partition SPMD program).  Missing pieces come back None; never
    raises."""
    out = {"flops": None, "bytes_accessed": None, "argument_bytes": None,
           "output_bytes": None, "temp_bytes": None,
           "generated_code_bytes": None, "alias_bytes": None,
           "peak_hbm_bytes": None, "source": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            f = ca.get("flops")
            if f is not None and f >= 0:
                out["flops"] = float(f)
                out["source"] = "xla"
            b = ca.get("bytes accessed")
            if b is not None and b >= 0:
                out["bytes_accessed"] = float(b)
    except Exception as e:
        _warn_absent("cost_analysis()", e)
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = int(getattr(ma, "argument_size_in_bytes", 0))
            outb = int(getattr(ma, "output_size_in_bytes", 0))
            tmp = int(getattr(ma, "temp_size_in_bytes", 0))
            gen = int(getattr(ma, "generated_code_size_in_bytes", 0))
            alias = int(getattr(ma, "alias_size_in_bytes", 0))
            out.update(argument_bytes=arg, output_bytes=outb,
                       temp_bytes=tmp, generated_code_bytes=gen,
                       alias_bytes=alias)
            # static peak estimate: everything resident at once, minus
            # donated outputs that alias their argument buffers
            out["peak_hbm_bytes"] = max(0, arg + outb + tmp + gen - alias)
    except Exception as e:
        _warn_absent("memory_analysis()", e)
    return out


# --------------------------------------------------------------- the registry
class CompiledProgram:
    """One captured program: its XLA cost/memory analysis + call count."""

    __slots__ = ("name", "analysis", "flops", "peak_hbm_bytes", "calls",
                 "meta", "captured_at")

    def __init__(self, name, analysis, meta=None):
        self.name = name
        self.analysis = dict(analysis)
        self.flops = self.analysis.get("flops")
        self.peak_hbm_bytes = self.analysis.get("peak_hbm_bytes")
        self.calls = 0
        self.meta = dict(meta or {})
        self.captured_at = time.time()

    def describe(self):
        d = {"name": self.name, "calls": int(self.calls)}
        d.update({k: self.analysis.get(k) for k in
                  ("flops", "bytes_accessed", "argument_bytes",
                   "output_bytes", "temp_bytes", "generated_code_bytes",
                   "peak_hbm_bytes", "source")})
        if self.meta:
            d["meta"] = self.meta
        return d


class CostModelRegistry:
    """Process-wide table of captured programs.  ``version`` bumps on every
    record so consumers (trace metadata refresh) can diff cheaply."""

    def __init__(self):
        self._programs = {}
        self.version = 0

    def record(self, name, analysis, meta=None):
        entry = CompiledProgram(name, analysis, meta=meta)
        self._programs[name] = entry
        self.version += 1
        return entry

    def get(self, name):
        return self._programs.get(name)

    def programs(self):
        return list(self._programs.values())

    def describe(self):
        """JSON-safe list, insertion-ordered — the compiled-programs table
        trace_report renders from the chrome trace's otherData."""
        return [p.describe() for p in self._programs.values()]

    def total_flops_executed(self):
        """Σ flops × calls over programs with a known flop count (the
        serve_bench MFU numerator)."""
        total = 0.0
        any_known = False
        for p in self._programs.values():
            if p.flops is not None and p.calls:
                total += p.flops * p.calls
                any_known = True
        return total if any_known else None

    def max_peak_hbm_bytes(self):
        peaks = [p.peak_hbm_bytes for p in self._programs.values()
                 if p.peak_hbm_bytes]
        return max(peaks) if peaks else None

    def reset(self):
        self._programs = {}
        self.version += 1


_registry = CostModelRegistry()


def registry():
    return _registry


def reset():
    """Test hook: clear captured programs + once-per-process warn state."""
    _registry.reset()
    _absence_warned.clear()
    _oom_warned.clear()


# --------------------------------------------------------------- OOM margin
#: static-estimate fraction of total_memory() past which the once-per-
#: program warning fires (override: DS_TPU_OOM_MARGIN, a fraction)
OOM_MARGIN_FRACTION = 0.9
_oom_warned = set()


def check_oom_margin(name, peak_hbm_bytes):
    """Loud once-per-program warning when the static peak-HBM estimate
    approaches the device memory limit — the point of a compile-time
    estimate is hearing about the OOM before the first step hits it."""
    if not peak_hbm_bytes or name in _oom_warned:
        return False
    try:
        from ..accelerator import get_accelerator
        total = get_accelerator().total_memory()
    except Exception:
        return False
    if not total:
        return False
    try:
        frac = float(os.environ.get("DS_TPU_OOM_MARGIN",
                                    OOM_MARGIN_FRACTION))
    except ValueError:
        frac = OOM_MARGIN_FRACTION
    if peak_hbm_bytes >= frac * total:
        _oom_warned.add(name)
        logger.warning(
            "HBM MARGIN: compiled program %r statically needs ~%.2f GiB of "
            "%.2f GiB device memory (%.0f%% ≥ %.0f%% margin) — this config "
            "is at OOM risk; consider a higher ZeRO stage, smaller "
            "micro-batch, or offload (see python -m "
            "deepspeed_tpu.profiling.mem_estimator)",
            name, peak_hbm_bytes / 2**30, total / 2**30,
            100.0 * peak_hbm_bytes / total, 100.0 * frac)
        return True
    return False


# -------------------------------------------------------------- capture API
#: force-capture switch for tools that want the registry populated without
#: enabling the full telemetry spine (serve_bench); telemetry.enabled also
#: arms capture at the opt-in call sites (serving) — the training engine
#: captures unconditionally because its AOT path costs no extra compile.
_force_capture = False


def enable_capture(on=True):
    global _force_capture
    _force_capture = bool(on)


def capturing():
    """Should opt-in call sites (which pay an extra analysis compile)
    capture right now?"""
    if _force_capture:
        return True
    from .. import telemetry
    return telemetry.enabled


class GuardedProgram:
    """An AOT-compiled executable with a jit fallback.

    The engine compiles its programs ahead-of-time (``lower().compile()``)
    so the cost model reads the *exact* executable that trains — same
    single compile as ``jit`` would do.  AOT calls validate input layouts
    strictly; if a later call ever mismatches (re-placed state after an
    offload round-trip on an exotic backend), this wrapper logs once and
    permanently falls back to the plain jitted function rather than
    killing the step.  Only pre-dispatch VALIDATION failures
    (TypeError/ValueError) are absorbed — they fire before any donated
    buffer is consumed, so the fallback re-call is safe.  Execution-time
    errors (a real RESOURCE_EXHAUSTED OOM, runtime faults) propagate:
    by then donated inputs may be gone, and re-running the fallback
    would mask the true error behind a deleted-buffer traceback."""

    __slots__ = ("compiled", "fallback", "name", "_failed")

    def __init__(self, compiled, fallback, name):
        self.compiled = compiled
        self.fallback = fallback
        self.name = name
        self._failed = False

    def __call__(self, *args):
        if not self._failed:
            try:
                return self.compiled(*args)
            except (TypeError, ValueError) as e:
                self._failed = True
                logger.warning(
                    "cost model: AOT executable %r rejected a call (%s: "
                    "%s) — re-dispatching through jit from now on",
                    self.name, type(e).__name__, e)
        return self.fallback(*args)


def capture_jit(name, jitted, args=(), kwargs=None, fallback_flops=None,
                meta=None):
    """AOT-compile ``jitted`` for ``args`` and record its cost entry.

    Returns ``(callable, entry)`` — the callable is the compiled
    executable wrapped in :class:`GuardedProgram` (one compile total, the
    same one jit would have done lazily), or the plain ``jitted`` when
    lowering/compiling through the AOT path fails.  ``fallback_flops`` is
    a zero-arg callable returning an analytic flop count used when (or for
    backends where) ``cost_analysis`` has no answer."""
    kwargs = kwargs or {}
    analysis = None
    fn = jitted
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        analysis = analyze_compiled(compiled)
        fn = GuardedProgram(compiled, jitted, name)
    except Exception as e:
        _warn_absent("ahead-of-time lower/compile", e)
    if analysis is None:
        analysis = {"flops": None, "peak_hbm_bytes": None, "source": None}
    if analysis.get("flops") is None and fallback_flops is not None:
        try:
            analysis["flops"] = float(fallback_flops())
            analysis["source"] = "analytic"
        except Exception as e:
            _warn_absent("analytic flop fallback", e)
    entry = _registry.record(name, analysis, meta=meta)
    check_oom_margin(name, entry.peak_hbm_bytes)
    return fn, entry


def capture_jit_call(name, jitted, args=(), kwargs=None, meta=None):
    """Record the cost entry for a call signature of an existing jitted
    function WITHOUT replacing the callable (the serving engines keep
    jit's own static-argument dispatch).  Costs one extra analysis compile
    per distinct ``name`` — only do this under :func:`capturing`.  Always
    returns the (possibly pre-existing) entry; increments its call count."""
    entry = _registry.get(name)
    if entry is None:
        analysis = None
        try:
            compiled = jitted.lower(*args, **(kwargs or {})).compile()
            analysis = analyze_compiled(compiled)
        except Exception as e:
            _warn_absent("ahead-of-time lower/compile", e)
        if analysis is None:
            analysis = {"flops": None, "peak_hbm_bytes": None,
                        "source": None}
        entry = _registry.record(name, analysis, meta=meta)
        check_oom_margin(name, entry.peak_hbm_bytes)
    entry.calls += 1
    return entry


def analyze_fn(fn, *args, **kwargs):
    """One-shot analysis of ``fn(*args, **kwargs)`` (jitted here if not
    already a jit wrapper).  Returns the analysis dict (values None when
    the backend has no answer) — the flops_profiler façade and the bench
    candidate rows use this."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        return analyze_compiled(compiled)
    except Exception as e:
        _warn_absent("ahead-of-time lower/compile", e)
        return {"flops": None, "bytes_accessed": None,
                "peak_hbm_bytes": None, "source": None}


def mfu(flops_per_chip_per_second, peak=None):
    """Model-FLOPs utilization: achieved per-chip FLOP/s ÷ per-chip peak.
    None in → None out (refuse, don't fabricate)."""
    if flops_per_chip_per_second is None:
        return None
    peak = peak if peak is not None else peak_flops_per_chip()
    if not peak or peak <= 0:
        return None
    return float(flops_per_chip_per_second) / float(peak)
