"""Decoder-op fusion analysis — the measurement behind "XLA replaces the
inference kernel suite".

The reference ships hand-written decoder kernels (``csrc/transformer/
inference/csrc/``: fused rms_norm.cu, apply_rotary_pos_emb.cu, softmax.cu,
gelu.cu, pointwise_ops.cu) because in eager torch each of those ops is a
separate kernel launch reading/writing HBM.  Under XLA the whole decoder
layer is one program, and the compiler fuses elementwise/reduction ops into
their matmul/attention neighbors — so the parity question is not "do we have
a rotary kernel" but "does the compiled layer contain any *standalone*
rotary/norm/activation kernel that a fused CUDA op would have eliminated".

This module measures exactly that, two ways:

* :func:`fusion_report` — compile a representative decode layer and count
  executable kernels: total fusions, plus whether rms-norm / rotary /
  activation ops appear as their own kernels or inside larger fusions.
* :func:`stage_timing` — wall-clock the fused layer vs the same math split
  into per-op jits (the eager-torch execution model the reference's kernels
  compete against); the ratio is the measured fusion win.

Run as a script for one JSON line per result:

    python -m deepspeed_tpu.profiling.kernel_bench [--dim 2048] [--seq 1024]
"""

import json
import math
import re
import time

import jax
import jax.numpy as jnp
import numpy as np


def _rms_norm(x, w, eps=1e-5):
    v = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(v + eps)).astype(x.dtype) \
        * w


def _rotary(x, cos, sin):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1).astype(x.dtype)


def _make_layer(D, H, S, dtype=jnp.bfloat16):
    """A llama-style decode layer on [B=1, S, D] with weights closed over —
    the shapes the reference's inference-v1 kernel suite serves."""
    Dh = D // H
    I = int(D * 8 / 3 // 128 * 128)
    rng = np.random.default_rng(0)
    r = lambda *s: jnp.asarray(rng.standard_normal(s) * 0.02, dtype)
    w = dict(ln1=jnp.ones((D,), dtype), ln2=jnp.ones((D,), dtype),
             wq=r(D, D), wk=r(D, D), wv=r(D, D), wo=r(D, D),
             wg=r(D, I), wu=r(D, I), wd=r(I, D))
    cos, sin = (jnp.asarray(np.cos(np.outer(np.arange(S), 1.0 / 10000 ** (
        np.arange(0, Dh, 2) / Dh))), jnp.float32),
        jnp.asarray(np.sin(np.outer(np.arange(S), 1.0 / 10000 ** (
            np.arange(0, Dh, 2) / Dh))), jnp.float32))

    def stages(x):
        """Returns list of (name, fn) staged ops — the unfused decomposition."""
        def attn(args):
            q, k, v = args
            q = q.reshape(1, S, H, Dh)
            k = k.reshape(1, S, H, Dh)
            v = v.reshape(1, S, H, Dh)
            q = _rotary(q, cos[None, :, None, :], sin[None, :, None, :])
            k = _rotary(k, cos[None, :, None, :], sin[None, :, None, :])
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(Dh)
            mask = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(mask, s.astype(jnp.float32), -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(1, S, D)
        return [
            ("rms_norm", lambda x: _rms_norm(x, w["ln1"])),
            ("qkv_gemm", lambda h: (h @ w["wq"], h @ w["wk"], h @ w["wv"])),
            ("attention", attn),
            ("o_gemm+residual", lambda a: x + a @ w["wo"]),
            ("rms_norm2", lambda x2: _rms_norm(x2, w["ln2"])),
            ("mlp_gemm+silu+mul",
             lambda h: jax.nn.silu(h @ w["wg"]) * (h @ w["wu"])),
            ("down_gemm", lambda g: g @ w["wd"]),
        ]

    def fused(x):
        h = x
        for _, fn in stages(x):
            h = fn(h)
        return h + 0 * x  # keep residual structure honest

    return fused, stages


def fusion_report(D=1024, H=8, S=512, dtype=jnp.bfloat16):
    """Compile the fused decode layer, return kernel-structure stats.

    ``standalone_*`` counts kernels whose ONLY content is that op family —
    the thing the reference's fused CUDA kernels exist to avoid."""
    fused, _ = _make_layer(D, H, S, dtype)
    x = jnp.zeros((1, S, D), dtype)
    compiled = jax.jit(fused).lower(x).compile()
    hlo = compiled.as_text()
    fusions = re.findall(r"^\s*fusion(?:\.\d+)?\s*=|^\s*%?fused_", hlo,
                         re.M)
    # top-level kernels = computations invoked from ENTRY (approximation:
    # count fusion + custom-call + dot ops at entry)
    entry = hlo.split("ENTRY")[-1]
    kernels = len(re.findall(r"(?:fusion|custom-call|dot|convolution)\(",
                             entry)) or len(fusions)
    standalone = {}
    bodies = re.split(r"\n\n", hlo)
    for fam, pat in (("rsqrt(norm)", r"rsqrt"), ("rotary(sin/cos mul)",
                                                 r"sine|cosine"),
                     ("softmax(exp)", r"exponential"),
                     ("silu(logistic)", r"logistic")):
        # a family is "standalone" if some fusion contains it but no dot —
        # crude but effective: look at each fused computation body
        alone = sum(1 for b in bodies
                    if re.search(pat, b) and "fused" in b.split("{")[0]
                    and " dot(" not in b and "custom-call" not in b)
        standalone[fam] = alone
    return {"entry_kernels_approx": kernels, "fusions": len(fusions),
            "standalone": standalone, "backend": jax.default_backend()}


def stage_timing(D=1024, H=8, S=512, dtype=jnp.bfloat16, iters=20):
    """Fused layer vs per-op dispatch (the eager execution model)."""
    fused, stages = _make_layer(D, H, S, dtype)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((1, S, D)),
                    dtype)
    jf = jax.jit(fused)
    jf(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jf(x)
    out.block_until_ready()
    fused_t = (time.perf_counter() - t0) / iters

    # unfused: each stage its own jit → each materializes to HBM
    staged = [(n, jax.jit(f)) for n, f in stages(x)]

    def run_staged():
        h = x
        for _, f in staged:
            h = f(h)
        return h
    jax.block_until_ready(run_staged())
    t0 = time.perf_counter()
    for _ in range(iters):
        h = run_staged()
    jax.block_until_ready(h)
    staged_t = (time.perf_counter() - t0) / iters
    return {"fused_ms": round(fused_t * 1e3, 3),
            "staged_ms": round(staged_t * 1e3, 3),
            "fusion_speedup": round(staged_t / fused_t, 3),
            "backend": jax.default_backend()}


def bias_attention_timing(B=2, N=8, L=512, H=4, D=32, iters=10):
    """Pallas bias-operand flash (dBias in-kernel) vs the chunked-XLA
    evoformer path — value+grad step on a pair-biased MSA attention
    (VERDICT r3 item 4 microbench)."""
    import os
    from ..ops.deepspeed4science.evoformer_attn import (
        DS4Sci_EvoformerAttention)
    rng = np.random.default_rng(0)
    Q, K, V = (jnp.asarray(rng.standard_normal((B, N, L, H, D)),
                           jnp.float32) for _ in range(3))
    pair = jnp.asarray(rng.standard_normal((B, 1, H, L, L)),
                       jnp.float32) * 0.3

    def loss(q, pb):
        return jnp.sum(DS4Sci_EvoformerAttention(q, K, V, [pb]) ** 2)

    results = {}
    saved = os.environ.get("DS_TPU_EVOFORMER_FLASH")
    try:
        # the route falls back (with a warning) on kernel-construction
        # failure — probe it first so the A/B can't silently time the
        # chunked path twice and report speedup ≈ 1.0 as a kernel result
        from ..ops.deepspeed4science.evoformer_attn import _flash_bias_route
        os.environ["DS_TPU_EVOFORMER_FLASH"] = "1"
        if _flash_bias_route(Q, K, V, [pair]) is None:
            os.environ.pop("DS_TPU_EVOFORMER_FLASH", None)
            return {"error": "flash-bias kernel route unavailable on this "
                             "backend (fell back to chunked XLA)",
                    "backend": jax.default_backend()}
        for name, flag in (("flash_kernel", "1"), ("chunked_xla", "0")):
            os.environ["DS_TPU_EVOFORMER_FLASH"] = flag
            g = jax.jit(jax.grad(loss, argnums=(0, 1)))
            out = g(Q, pair)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = g(Q, pair)
            jax.block_until_ready(out)
            results[name + "_ms"] = round(
                (time.perf_counter() - t0) / iters * 1e3, 3)
    finally:  # restore (not delete) any pre-existing operator setting
        if saved is None:
            os.environ.pop("DS_TPU_EVOFORMER_FLASH", None)
        else:
            os.environ["DS_TPU_EVOFORMER_FLASH"] = saved
    results["speedup"] = round(results["chunked_xla_ms"] /
                               results["flash_kernel_ms"], 3)
    results["backend"] = jax.default_backend()
    return results


def gmm_timing(T=4096, D=1024, I=3584, E=8, iters=10, dtype=jnp.bfloat16):
    """Pallas grouped GEMM vs XLA ragged_dot on the MoE expert-FFN shape
    (the A/B that decides DS_TPU_MOE_GMM on real hardware)."""
    import numpy as np
    from ..ops.pallas.grouped_matmul import gmm
    r = np.random.default_rng(0)
    sizes = np.full(E, T // E, np.int32)
    x = jnp.asarray(r.standard_normal((T, D)), dtype)
    w = jnp.asarray(r.standard_normal((E, D, I)) * 0.05, dtype)
    gs = jnp.asarray(sizes)

    def timeit(f):
        y = f(x, w, gs)
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        for _ in range(iters):
            y = f(x, w, gs)
        jax.block_until_ready(y)
        return (time.perf_counter() - t0) / iters

    t_ragged = timeit(jax.jit(jax.lax.ragged_dot))
    t_gmm = timeit(jax.jit(lambda x, w, g: gmm(x, w, g)))
    return {"ragged_dot_ms": round(t_ragged * 1e3, 3),
            "pallas_gmm_ms": round(t_gmm * 1e3, 3),
            "speedup": round(t_ragged / t_gmm, 3),
            "shape": f"T={T} D={D} I={I} E={E} {jnp.dtype(dtype).name}",
            "backend": jax.default_backend()}


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--bias-attn", action="store_true",
                   help="also run the evoformer bias-kernel A/B")
    p.add_argument("--gmm", action="store_true",
                   help="also run the MoE grouped-GEMM A/B")
    args = p.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    rep = fusion_report(args.dim, args.heads, args.seq)
    print(json.dumps({"metric": "decoder_fusion_report", **rep}))
    tim = stage_timing(args.dim, args.heads, args.seq)
    print(json.dumps({"metric": "decoder_fusion_timing", **tim}))
    if args.bias_attn:
        bt = bias_attention_timing()
        print(json.dumps({"metric": "evoformer_bias_attention_timing",
                          **bt}))
    if args.gmm:
        gt = gmm_timing()
        print(json.dumps({"metric": "moe_grouped_gemm_timing", **gt}))


if __name__ == "__main__":
    main()
