from .flops_profiler import FlopsProfiler, get_model_profile
from . import cost_model, mem_estimator  # noqa: F401
