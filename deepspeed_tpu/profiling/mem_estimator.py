"""Static per-chip HBM planner for ZeRO model states.

Reference semantics: ``deepspeed.runtime.zero.stage{1,2,3}``'s
``estimate_zero*_model_states_mem_needs`` helpers answer "will this model
fit at this stage before you burn a trial finding out".  Two layers here:

* **formula planner** (:func:`estimate_zero_states`) — the closed-form
  per-chip bytes for Ψ params at stage s over N-way ZeRO with a
  ``K``-byte optimizer-state factor (Adam mixed precision: 2Ψ params +
  ``grad_bytes``Ψ grads + (4+8)Ψ master+moments — the reference's
  16Ψ/(stage-dependent N) ladder), extended with the expert-parallel
  split: expert params are MODEL parallelism over ``ep`` (resident Ψₑ/ep
  per chip) whose ZeRO group is the expert-DP ``dp`` factor only — the
  ``ZeroPartitionPlan.leaf_zero_axes`` rule made executable as arithmetic;
* **plan-derived estimator** (:func:`estimate_from_plan`) — the exact
  per-leaf accounting: walk the real parameter tree through the live
  :class:`~deepspeed_tpu.runtime.zero.partition.ZeroPartitionPlan`'s
  param/master/grad specs and sum per-device shard bytes, so tp rules,
  rule-claimed MoE axes, persistence thresholds and hpZ/MiCS factorings
  are all priced exactly as the engine will shard them.

Neither counts activations — that is what the compiled
``memory_analysis()`` capture (:mod:`.cost_model`) measures; the
``trace_report`` planner-vs-measured delta closes the loop between the
two.  The autotuner uses the formula planner as a memory-feasibility
filter (reject candidates whose states alone exceed HBM before spending a
trial).

CLI::

    python -m deepspeed_tpu.profiling.mem_estimator --params 1.3e9 \
        --dp 64 [--ep 8 --expert-params 8e8] [--dtypes bf16,fp32]

prints the stage 0/1/2/3 × dtype table with the per-chip HBM needs.
"""

import argparse
import sys

import numpy as np

DTYPE_BYTES = {"fp32": 4, "float32": 4, "bf16": 2, "bfloat16": 2,
               "fp16": 2, "float16": 2}

#: fp32 master + Adam moments, bytes per parameter (reference K=12 for
#: mixed precision: 4 master + 4 momentum + 4 variance)
ADAM_STATE_BYTES = 12
#: master only (SGD-like optimizers without moments)
MASTER_ONLY_BYTES = 4


def _dtype_bytes(dtype):
    if isinstance(dtype, (int, float)):
        return int(dtype)
    b = DTYPE_BYTES.get(str(dtype).lower())
    if b is None:
        raise ValueError(f"unknown dtype {dtype!r} "
                         f"(have {sorted(set(DTYPE_BYTES))})")
    return b


# ------------------------------------------------------------ formula planner
def estimate_zero_states(num_params, stage, dp, ep=1, expert_params=0,
                         compute_dtype="bf16", grad_bytes=4,
                         optimizer_state_bytes=ADAM_STATE_BYTES):
    """Per-chip model-state bytes for ``num_params`` at ZeRO ``stage``.

    ``dp`` is the expert-data-parallel factor (the mesh's "dp" axis); the
    dense ZeRO group is ``dp·ep`` (dense params replicate over no axis —
    groups.dp_axes() is ("dp", "ep")), while ``expert_params`` shard over
    "ep" as model parallelism and ZeRO-shard over "dp" only.  Returns a
    dict with the per-class and total bytes."""
    if stage not in (0, 1, 2, 3):
        raise ValueError(f"stage must be 0..3, got {stage}")
    if dp < 1 or ep < 1:
        raise ValueError(f"dp/ep must be >= 1 (got dp={dp}, ep={ep})")
    expert_params = int(expert_params)
    dense = int(num_params) - expert_params
    if dense < 0:
        raise ValueError(
            f"expert_params ({expert_params}) exceeds num_params "
            f"({num_params})")
    cb = _dtype_bytes(compute_dtype)

    def _per_chip(psi, zero_n, model_n=1):
        """bytes for psi params whose ZeRO group is zero_n wide and whose
        model-parallel residency divides by model_n (experts over ep)."""
        p = psi / model_n          # resident copies before ZeRO
        params = p * cb / (zero_n if stage >= 3 else 1)
        grads = p * grad_bytes / (zero_n if stage >= 2 else 1)
        states = p * optimizer_state_bytes / (zero_n if stage >= 1 else 1)
        return params, grads, states

    dzp, dzg, dzs = _per_chip(dense, dp * ep)
    ezp, ezg, ezs = _per_chip(expert_params, dp, model_n=ep)
    out = {
        "stage": stage, "dp": int(dp), "ep": int(ep),
        "num_params": int(num_params),
        "expert_params": expert_params,
        "compute_dtype_bytes": cb,
        "params_bytes": dzp + ezp,
        "grads_bytes": dzg + ezg,
        "optimizer_bytes": dzs + ezs,
    }
    out["total_bytes"] = (out["params_bytes"] + out["grads_bytes"]
                          + out["optimizer_bytes"])
    return out


# reference-API-parity wrappers (per-chip bytes; the reference prints
# CPU+GPU pairs for its offload variants — offload here is a sharding
# policy, docs/zero.md)
def estimate_zero1_model_states_mem_needs(total_params, num_chips, **kw):
    return estimate_zero_states(total_params, 1, num_chips, **kw)[
        "total_bytes"]


def estimate_zero2_model_states_mem_needs(total_params, num_chips, **kw):
    return estimate_zero_states(total_params, 2, num_chips, **kw)[
        "total_bytes"]


def estimate_zero3_model_states_mem_needs(total_params, num_chips, **kw):
    return estimate_zero_states(total_params, 3, num_chips, **kw)[
        "total_bytes"]


# ------------------------------------------------------- plan-derived planner
def _shard_elems(shape, spec, mesh):
    """Per-device element count of ``shape`` sharded as ``spec`` over
    ``mesh`` (divisibility already guaranteed by the plan's spec
    builders)."""
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    div = 1
    if spec is not None:
        for entry in spec:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry, )):
                div *= int(mesh.shape.get(ax, 1))
    return n // max(1, div)


def estimate_from_plan(params, plan, compute_dtype_bytes=4, grad_bytes=4,
                       optimizer_moments=2, include_master=True):
    """Exact per-chip model-state bytes for a real parameter tree under a
    live :class:`ZeroPartitionPlan` — per-leaf specs price tp rules,
    rule-claimed MoE "ep" axes, the persistence threshold and hpZ/MiCS
    exactly as the engine shards them.

    ``optimizer_moments``: fp32 moment tensors per param (Adam/LAMB 2,
    Lion/momentum-SGD 1, plain SGD 0); ``include_master`` adds the fp32
    master copy (mixed precision or stage ≥ 1)."""
    import jax
    from ..runtime.zero.partition import path_str

    totals = {"params_bytes": 0.0, "grads_bytes": 0.0, "master_bytes": 0.0,
              "optimizer_bytes": 0.0, "num_params": 0}

    def one(kp, x):
        shape = tuple(getattr(x, "shape", ()))
        path = path_str(kp)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        totals["num_params"] += n
        totals["params_bytes"] += compute_dtype_bytes * _shard_elems(
            shape, plan.param_spec(shape, path), plan.param_mesh)
        master_elems = _shard_elems(shape, plan.master_spec(shape, path),
                                    plan.state_mesh)
        if include_master:
            totals["master_bytes"] += 4 * master_elems
        totals["optimizer_bytes"] += 4 * optimizer_moments * master_elems
        totals["grads_bytes"] += grad_bytes * _shard_elems(
            shape, plan.grad_spec(shape, path), plan.state_mesh)

    jax.tree_util.tree_map_with_path(one, params)
    totals["total_bytes"] = (totals["params_bytes"] + totals["grads_bytes"]
                             + totals["master_bytes"]
                             + totals["optimizer_bytes"])
    totals["stage"] = plan.stage
    return totals


# --------------------------------------------------------------------- table
def planner_table(num_params, dp, ep=1, expert_params=0,
                  dtypes=("bf16", "fp32"), grad_bytes=4,
                  optimizer_state_bytes=ADAM_STATE_BYTES,
                  hbm_bytes=None):
    """Rows for every stage × compute dtype; ``hbm_bytes`` (per-chip HBM)
    adds a fits/OOM verdict column."""
    rows = []
    for dtype in dtypes:
        for stage in (0, 1, 2, 3):
            est = estimate_zero_states(
                num_params, stage, dp, ep=ep, expert_params=expert_params,
                compute_dtype=dtype, grad_bytes=grad_bytes,
                optimizer_state_bytes=optimizer_state_bytes)
            est["compute_dtype"] = dtype
            if hbm_bytes:
                est["fits"] = est["total_bytes"] <= hbm_bytes
            rows.append(est)
    return rows


def _fmt_gib(b):
    return f"{b / 2**30:8.2f}"


def render_table(rows, hbm_bytes=None, print_fn=print):
    print_fn(f"{'dtype':>6}{'stage':>6}{'params':>10}{'grads':>10}"
             f"{'optim':>10}{'total_GiB':>11}"
             + (f"{'fits':>6}" if hbm_bytes else ""))
    for r in rows:
        line = (f"{r['compute_dtype']:>6}{r['stage']:>6}"
                f"{_fmt_gib(r['params_bytes']):>10}"
                f"{_fmt_gib(r['grads_bytes']):>10}"
                f"{_fmt_gib(r['optimizer_bytes']):>10}"
                f"{_fmt_gib(r['total_bytes']):>11}")
        if hbm_bytes:
            line += f"{'yes' if r['fits'] else 'OOM':>6}"
        print_fn(line)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mem_estimator",
        description="per-chip HBM needs of ZeRO model states "
        "(reference estimate_zero*_model_states_mem_needs; "
        "docs/observability.md MFU & HBM)")
    ap.add_argument("--params", type=float, required=True,
                    help="total parameter count (e.g. 1.3e9)")
    ap.add_argument("--dp", type=int, required=True,
                    help="expert-data-parallel factor (the mesh dp axis)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel factor (default 1)")
    ap.add_argument("--expert-params", type=float, default=0,
                    help="parameters living in expert stacks (shard over "
                    "ep as model parallelism; ZeRO over dp only)")
    ap.add_argument("--dtypes", default="bf16,fp32",
                    help="comma-separated compute dtypes (default "
                    "bf16,fp32)")
    ap.add_argument("--grad-bytes", type=int, default=4,
                    help="gradient accumulator bytes/param (default 4 = "
                    "fp32 accumulation)")
    ap.add_argument("--optimizer-bytes", type=int,
                    default=ADAM_STATE_BYTES,
                    help="optimizer-state bytes/param incl. fp32 master "
                    "(default 12 = Adam mixed precision)")
    ap.add_argument("--hbm-gib", type=float, default=None,
                    help="per-chip HBM in GiB — adds a fits/OOM verdict "
                    "column (e.g. 16 for v3, 32 for v4)")
    args = ap.parse_args(argv)
    hbm = int(args.hbm_gib * 2**30) if args.hbm_gib else None
    rows = planner_table(
        int(args.params), args.dp, ep=args.ep,
        expert_params=int(args.expert_params),
        dtypes=tuple(args.dtypes.split(",")), grad_bytes=args.grad_bytes,
        optimizer_state_bytes=args.optimizer_bytes, hbm_bytes=hbm)
    print(f"# per-chip ZeRO model-state HBM needs: Ψ={args.params:g} "
          f"dp={args.dp} ep={args.ep}"
          + (f" expert Ψ={args.expert_params:g}" if args.expert_params
             else ""))
    print("# states only — activations/temp come from the compiled "
          "memory_analysis() capture (trace_report compiled-programs "
          "table)")
    render_table(rows, hbm_bytes=hbm)
    return 0


if __name__ == "__main__":
    sys.exit(main())
