"""Runtime utilities — analog of reference ``runtime/utils.py:1103``
(clip_grad_norm_, see_memory_usage, partition helpers)."""

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger


def global_grad_norm(grads):
    """L2 norm over a gradient pytree.  Under pjit, sharded leaves still
    produce the *global* norm (GSPMD reduces across shards) — this replaces
    the reference's mpu-aware ``clip_grad_norm_`` (runtime/utils.py)."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads) if g is not None]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))


def clip_grads_by_global_norm(grads, max_norm, norm=None):
    """Scale grads so that global norm ≤ max_norm; returns (grads, norm).
    Non-finite norms leave grads unscaled (overflow path handles skipping)."""
    if norm is None:
        norm = global_grad_norm(grads)
    clip_coef = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    clip_coef = jnp.where(jnp.isfinite(clip_coef), clip_coef, 1.0)
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * clip_coef).astype(g.dtype), grads), norm


def partition_uniform(num_items, num_parts):
    """Reference ``partition_uniform``: balanced contiguous split boundaries."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts


def partition_balanced(weights, num_parts):
    """Reference ``partition_balanced``: split so max part weight is minimized
    (prefix-sum + binary search).  Weights should be positive integers (the
    limit search is integral) — scale float weights up first."""
    n = len(weights)
    prefix = np.concatenate([[0], np.cumsum(weights)])

    def can(limit):
        parts, last, count = [0], 0, 0
        for i in range(1, n + 1):
            if prefix[i] - prefix[last] > limit:
                if i - 1 == last:
                    return None
                parts.append(i - 1)
                last = i - 1
                count += 1
                if count >= num_parts:
                    return None
        parts.append(n)
        return parts if len(parts) <= num_parts + 1 else None

    lo = max(weights) if n else 0
    hi = int(prefix[-1]) or 1
    best = None
    while lo <= hi:
        mid = (lo + hi) // 2
        p = can(mid)
        if p is not None:
            best = p
            hi = mid - 1
        else:
            lo = mid + 1
    if best is None:
        return partition_uniform(n, num_parts)
    # pad to exactly num_parts+1 boundaries
    while len(best) < num_parts + 1:
        best.append(n)
    if n >= num_parts:
        # The greedy packer may use fewer parts than requested, leaving
        # empty trailing parts (repeated boundaries) — an empty PIPELINE
        # STAGE downstream.  Borrow one item from the left neighbor for
        # each empty part, back to front: the shrunken neighbor can only
        # get lighter and the new 1-item part weighs ≤ max(weights) ≤ the
        # found bottleneck, so optimality is preserved.
        for i in range(num_parts - 1, 0, -1):
            if best[i] >= best[i + 1]:
                best[i] = best[i + 1] - 1
    return best


def memory_usage_snapshot():
    """The accelerator ``memory_stats()`` dict distilled to the figures
    the HBM accounting reports everywhere (step records, gauges,
    :func:`see_memory_usage`): live/peak/limit bytes plus a fragmentation
    estimate — 1 − largest_free_block / free when the backend exposes the
    largest contiguous block (XLA's BFC allocator does), else None."""
    from ..accelerator import get_accelerator
    stats = get_accelerator().memory_stats() or {}
    live = int(stats.get("bytes_in_use", 0))
    peak = int(stats.get("peak_bytes_in_use", live))
    limit = int(stats.get("bytes_limit", 0))
    free = max(0, limit - live)
    largest = stats.get("largest_free_block_bytes")
    frag = None
    if largest is not None and free > 0:
        frag = max(0.0, 1.0 - float(largest) / free)
    return {"live_bytes": live, "peak_bytes": peak, "limit_bytes": limit,
            "free_bytes": free, "fragmentation": frag}


def see_memory_usage(message, force=False):
    """Reference ``see_memory_usage``: device memory snapshot — live,
    peak, limit and fragmentation (bytes_in_use vs bytes_limit via the
    largest free block) from the accelerator ``memory_stats()`` dict, not
    just the two raw allocation fields.  Routed through the telemetry
    metrics registry when the spine is enabled."""
    if not force:
        return
    snap = memory_usage_snapshot()
    gib = 1024**3
    frag = (f" frag: {snap['fragmentation']:.1%}"
            if snap["fragmentation"] is not None else "")
    limit = (f" limit: {snap['limit_bytes'] / gib:.2f}GB "
             f"free: {snap['free_bytes'] / gib:.2f}GB"
             if snap["limit_bytes"] else "")
    logger.info(f"{message} | device alloc: {snap['live_bytes'] / gib:.2f}GB "
                f"peak: {snap['peak_bytes'] / gib:.2f}GB{limit}{frag}")
    from .. import telemetry
    if telemetry.enabled:
        for key in ("live_bytes", "peak_bytes", "limit_bytes"):
            g = telemetry.gauge(f"hbm/{key}",
                                help="see_memory_usage device snapshot")
            if g is not None:
                g.set(snap[key])
        if snap["fragmentation"] is not None:
            g = telemetry.gauge("hbm/fragmentation",
                                help="1 - largest_free_block / free")
            if g is not None:
                g.set(snap["fragmentation"])
    return snap


def count_parameters(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def ensure_directory_exists(filename):
    import os
    os.makedirs(os.path.dirname(os.path.abspath(filename)), exist_ok=True)


def make_scaled_loss_fn(apply_fn, gas):
    """The one loss-scaling convention shared by every micro-step variant
    (GSPMD, qgZ manual-SPMD, 1-bit local-grad): scale for fp16, divide by GAS
    (reference engine.backward :2023), return (scaled, raw) for has_aux."""

    def loss_fn(params, scale, inputs):
        out = apply_fn(params, *inputs)
        loss = out[0] if isinstance(out, (tuple, list)) else out
        return loss.astype(jnp.float32) * scale / gas, loss

    return loss_fn


def batch_input_specs(inputs, axes, n_replicated_tail=0):
    """shard_map in_specs for a micro-step's batch inputs: leading dim
    sharded over the dp ``axes``, except the last ``n_replicated_tail``
    inputs which are REPLICATED (engine-appended extras that aren't
    per-sample data — e.g. PLD's theta scalar and rng key)."""
    from jax.sharding import PartitionSpec as P
    n = len(inputs)
    return tuple(
        P() if i >= n - n_replicated_tail
        else P(*([axes] + [None] * (x.ndim - 1)))
        for i, x in enumerate(inputs))


def load_16bit_npz(path):
    """Reload a :meth:`DeepSpeedEngine.save_16bit_model` export: bf16 leaves
    (stored as uint16 raw views, names under ``__bf16__``) come back as
    ml_dtypes.bfloat16 arrays; everything else as saved."""
    import ml_dtypes
    import numpy as onp
    with onp.load(path) as data:
        bf16 = (set(str(n) for n in data["__bf16__"])
                if "__bf16__" in data.files else set())
        return {n: (data[n].view(ml_dtypes.bfloat16) if n in bf16
                    else data[n])
                for n in data.files if n != "__bf16__"}
