"""LR schedules — analog of reference ``runtime/lr_schedules.py`` (LRRangeTest
``:273``, OneCycle ``:371``, WarmupLR ``:633``, WarmupDecayLR ``:723``,
WarmupCosineLR ``:774``).

Each scheduler is a small object with ``get_lr(step) -> float`` (jit-traceable:
jnp ops only) plus the reference's stateful ``step()/get_last_lr()`` surface so
user loops written against DeepSpeed still work.  The engine feeds ``get_lr``
into the optimizer as ``lr_fn`` so the schedule is evaluated *inside* the
compiled update (no host sync per step).
"""

import math

import jax.numpy as jnp

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


class _LRSchedule:
    def __init__(self, optimizer=None):
        self.optimizer = optimizer
        self.last_batch_iteration = -1
        self._last_lr = None

    def get_lr(self, step):
        raise NotImplementedError

    # reference-compatible stateful API ------------------------------------
    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = [float(self.get_lr(jnp.asarray(last_batch_iteration)))]

    def get_last_lr(self):
        if self._last_lr is None:
            self.step(0)
        return self._last_lr

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR(_LRSchedule):
    """Reference ``lr_schedules.py:633``: warmup then constant."""

    def __init__(self, optimizer=None, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type=WARMUP_LOG_RATE,
                 last_batch_iteration=-1):
        super().__init__(optimizer)
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)
        self.last_batch_iteration = last_batch_iteration

    def _warmup_factor(self, step):
        step = jnp.maximum(step, 1)
        if self.warmup_type == WARMUP_LOG_RATE:
            return jnp.minimum(1.0, self.inverse_log_warm_up *
                               jnp.log(step.astype(jnp.float32)))
        return jnp.minimum(1.0, step / self.warmup_num_steps)

    def get_lr(self, step):
        f = self._warmup_factor(step)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * f


class WarmupDecayLR(WarmupLR):
    """Reference ``:723``: warmup then linear decay to 0 at total_num_steps."""

    def __init__(self, optimizer=None, total_num_steps=10000, **kw):
        super().__init__(optimizer, **kw)
        self.total_num_steps = total_num_steps

    def get_lr(self, step):
        warm = self._warmup_factor(step)
        decay = jnp.clip(
            (self.total_num_steps - step) /
            jnp.maximum(1.0, self.total_num_steps - self.warmup_num_steps),
            0.0, 1.0)
        f = jnp.where(step < self.warmup_num_steps, warm, decay)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * f


class WarmupCosineLR(_LRSchedule):
    """Reference ``:774``: linear warmup then cosine decay to cos_min_ratio."""

    def __init__(self, optimizer=None, total_num_steps=10000,
                 warmup_min_ratio=0.0, warmup_num_steps=1000,
                 cos_min_ratio=0.0001, warmup_type=WARMUP_LINEAR_RATE,
                 last_batch_iteration=-1, warmup_max_lr=0.001):
        super().__init__(optimizer)
        self.total_num_steps = total_num_steps
        self.warmup_min_ratio = warmup_min_ratio
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.cos_min_ratio = cos_min_ratio
        self.warmup_max_lr = warmup_max_lr
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self, step):
        warm = self.warmup_min_ratio + (1.0 - self.warmup_min_ratio) * \
            jnp.minimum(1.0, step / self.warmup_num_steps)
        progress = jnp.clip(
            (step - self.warmup_num_steps) /
            jnp.maximum(1, self.total_num_steps - self.warmup_num_steps), 0.0, 1.0)
        cosine = self.cos_min_ratio + (1 - self.cos_min_ratio) * 0.5 * \
            (1.0 + jnp.cos(jnp.pi * progress))
        ratio = jnp.where(step < self.warmup_num_steps, warm, cosine)
        return self.warmup_max_lr * ratio


class OneCycle(_LRSchedule):
    """Reference ``:371``: cycle lr between min and max then decay."""

    def __init__(self, optimizer=None, cycle_min_lr=1e-5, cycle_max_lr=1e-3,
                 decay_lr_rate=0.0, cycle_first_step_size=2000,
                 cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0,
                 last_batch_iteration=-1, **unused):
        super().__init__(optimizer)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first = cycle_first_step_size
        self.second = cycle_second_step_size or cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self, step):
        total = self.first + self.second
        in_cycle = step < total
        up = jnp.clip(step / self.first, 0.0, 1.0)
        down = jnp.clip((step - self.first) / self.second, 0.0, 1.0)
        frac = jnp.where(step < self.first, up, 1.0 - down)
        cycle_lr = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * frac
        decay_steps = jnp.maximum(0.0, step - total)
        if self.decay_step_size > 0:
            decay_steps = jnp.floor(decay_steps / self.decay_step_size)
        decay_lr = self.cycle_min_lr / (1.0 + self.decay_lr_rate * decay_steps)
        return jnp.where(in_cycle, cycle_lr, decay_lr)


class LRRangeTest(_LRSchedule):
    """Reference ``:273``: sweep lr for tuning."""

    def __init__(self, optimizer=None, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__(optimizer)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self, step):
        interval = (jnp.floor(step / self.step_size) if self.staircase
                    else step / self.step_size)
        return self.min_lr * (1.0 + self.step_rate * interval)


VALID_LR_SCHEDULES = {
    "LRRangeTest": LRRangeTest,
    "OneCycle": OneCycle,
    "WarmupLR": WarmupLR,
    "WarmupDecayLR": WarmupDecayLR,
    "WarmupCosineLR": WarmupCosineLR,
}


def get_lr_scheduler(name, params, optimizer=None):
    if name not in VALID_LR_SCHEDULES:
        raise ValueError(f"unknown lr schedule {name!r}; valid: "
                         f"{sorted(VALID_LR_SCHEDULES)}")
    return VALID_LR_SCHEDULES[name](optimizer=optimizer, **(params or {}))


def add_tuning_arguments(parser):
    """Reference ``lr_schedules.py:60``: argparse surface for LR-schedule
    tuning from the command line (LR range test, OneCycle phases, warmup).
    Values collected here feed :func:`get_config_from_args`."""
    group = parser.add_argument_group("Convergence Tuning",
                                      "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    # LR range test
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    # OneCycle
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_momentum", type=bool, default=False)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    # Warmup
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default="log")
    return parser


_SCHED_ARG_PREFIXES = {
    "LRRangeTest": ("lr_range_test_", ),
    "OneCycle": ("cycle_", "decay_"),
    "WarmupLR": ("warmup_", ),
    "WarmupDecayLR": ("warmup_", ),
    "WarmupCosineLR": ("warmup_", ),
}


def get_config_from_args(args):
    """Reference ``lr_schedules.py:208``: build the scheduler config dict
    from parsed args; returns ``(config, None)`` or ``(None, reason)``."""
    if not hasattr(args, "lr_schedule") or args.lr_schedule is None:
        return None, "--lr_schedule not specified on command line"
    if args.lr_schedule not in VALID_LR_SCHEDULES:
        return None, (f"{args.lr_schedule!r} is not a valid LR schedule "
                      f"(valid: {sorted(VALID_LR_SCHEDULES)})")
    params = {}
    prefixes = _SCHED_ARG_PREFIXES[args.lr_schedule]
    for key, value in vars(args).items():
        if any(key.startswith(p) for p in prefixes):
            params[key] = value
    return {"type": args.lr_schedule, "params": params}, None


def get_lr_from_config(config):
    """Reference ``lr_schedules.py:229``: the schedule's headline lr."""
    if "type" not in config:
        return None, "no type (LR schedule name) specified in config"
    name, params = config["type"], config.get("params", {})
    if name not in VALID_LR_SCHEDULES:
        return None, f"{name!r} is not a valid LR schedule"
    if name == "LRRangeTest":
        return params.get("lr_range_test_min_lr", 0.001), ""
    if name == "OneCycle":
        return params.get("cycle_max_lr", 0.1), ""
    return params.get("warmup_max_lr", 0.001), ""
