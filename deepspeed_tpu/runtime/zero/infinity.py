"""ZeRO-Infinity parameter streaming — host/NVMe-resident parameters fed to
the chip one transformer block at a time.

Reference: ``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:37``
(``AsyncPartitionedParameterSwapper``) + the fetch/release coordinator
``deepspeed/runtime/zero/partitioned_param_coordinator.py:276`` + host-side
optimization ``csrc/adam/cpu_adam_impl.cpp``.

TPU-native shape (NOT a hook translation): the model exposes itself as
``embed → L homogeneous blocks → head`` (:class:`StreamingSpec`); the engine
drives per-block *jitted* calls while this module keeps every block's state
host-resident:

* fp32 master + optimizer moments + a wire-dtype (bf16) parameter cache live
  in host RAM — or on NVMe via the aio thread pool — as ONE flat contiguous
  vector per (block, kind), so a block's optimizer update is a single native
  SIMD kernel call (``ops/cpu_optimizers.py``) and a block's NVMe swap is one
  file stream.
* ``start_fetch``/``finish_fetch`` double-buffer: NVMe→RAM via async aio
  reads, RAM→HBM via (async) ``jax.device_put`` of zero-copy views into the
  flat vector.
* gradients arrive as device arrays per block; ``accumulate_grads`` copies
  them into a host stash (wire dtype at gas=1, fp32 when accumulating), and
  ``optimizer_sweep`` runs the host Adam/Adagrad/Lion kernel block-by-block —
  emitting the updated bf16 cache in the same pass (``bf16_out``), so updated
  params never round-trip through HBM (VERDICT r3 missing #2).

HBM never holds more than the executor's working set of blocks (the
:class:`~deepspeed_tpu.runtime.infinity_engine.InfinityEngine` keeps ≤ 3:
current + prefetch, tracked and asserted in tests).
"""

import os
import tempfile
from typing import Callable, NamedTuple

import numpy as np
import ml_dtypes

import jax

from ...utils.logging import log_dist

BF16 = ml_dtypes.bfloat16


class StreamingSpec(NamedTuple):
    """How a model exposes its block structure to the streaming executor.

    ``block_keys``   ordered top-level parameter-tree keys, one per block —
                     every block must share one pytree structure so a single
                     compiled ``block_apply`` serves all of them.
    ``resident_keys``  top-level keys of the embed/norm/head group (fetched
                     once per step, resident for the whole step).
    ``embed_apply``  ``(resident_params, *batch) -> activations``
    ``block_apply``  ``(block_params, activations) -> activations``
    ``head_apply``   ``(resident_params, activations, *batch) -> loss`` (or
                     logits when the batch carries no labels)
    ``init_block``   ``(rng, key, activations) -> host block params``
    ``init_resident``  ``(rng, *batch) -> host resident params``
    """
    block_keys: tuple
    resident_keys: tuple
    embed_apply: Callable
    block_apply: Callable
    head_apply: Callable
    init_block: Callable
    init_resident: Callable


def _flatten_f32(tree):
    """Host pytree → (one C-contiguous fp32 vector, leaf metadata)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrs = [np.asarray(l, dtype=np.float32) for l in leaves]
    sizes = [a.size for a in arrs]
    flat = np.empty(sum(sizes), np.float32)
    off = 0
    shapes = []
    for a in arrs:
        flat[off:off + a.size] = a.ravel()
        shapes.append(a.shape)
        off += a.size
    return flat, (treedef, shapes, sizes)


def _views(flat, meta):
    """Zero-copy pytree view of a flat vector."""
    treedef, shapes, sizes = meta
    out, off = [], 0
    for shape, n in zip(shapes, sizes):
        out.append(flat[off:off + n].reshape(shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


class _FetchHandle:
    """In-flight block fetch: optional aio read → device_put."""

    def __init__(self, key):
        self.key = key
        self.aio_handle = None
        self.device_tree = None


class BlockStore:
    """Host/NVMe residency manager for per-block parameters and optimizer
    state (flat-vector layout, see module docstring).

    ``param_device`` / ``state_device``: "cpu" (host RAM) or "nvme".
    ``optimizer``: adam | adamw | fusedadam | adagrad | lion — mapped onto
    the native host kernels.
    """

    KINDS = {"adam": ("m", "v"), "adamw": ("m", "v"), "fusedadam": ("m", "v"),
             "adagrad": ("sum", ), "lion": ("m", )}

    def __init__(self, param_device="cpu", state_device="cpu", nvme_path=None,
                 optimizer="adam", opt_params=None, wire_dtype=BF16,
                 grad_accum_fp32=False):
        if optimizer not in self.KINDS:
            raise ValueError(
                f"host optimizer {optimizer!r} is not supported for "
                f"ZeRO-Infinity param streaming (have: "
                f"{sorted(self.KINDS)}); the native LAMB has no host kernel")
        self.param_device = param_device
        self.state_device = state_device
        self.optimizer = optimizer
        p = dict(opt_params or {})
        self.lr = p.get("lr", 1e-3)
        self.betas = tuple(p.get("betas", (0.9, 0.999) if "adam" in optimizer
                                 else (0.9, 0.99)))
        self.eps = p.get("eps", 1e-8)
        self.weight_decay = p.get("weight_decay", 0.0)
        self.adamw_mode = optimizer in ("adamw", "fusedadam") or \
            p.get("adam_w_mode", False)
        self.wire_dtype = np.dtype(wire_dtype)
        self.grad_accum_fp32 = grad_accum_fp32
        self.step_count = 0
        self._kernels = None

        self._meta = {}      # key → (treedef, shapes, sizes)
        self._master = {}    # key → flat fp32 (cpu mode)
        self._state = {}     # key → {kind: flat fp32} (cpu mode)
        self._cache = {}     # key → flat wire-dtype param cache (cpu mode)
        self._grads = {}     # key → flat stash (allocated on first arrival)
        self._swapper = None
        if "nvme" in (param_device, state_device):
            from ..swap_tensor import AsyncTensorSwapper
            base = nvme_path or os.path.join(tempfile.gettempdir(),
                                             "ds_tpu_infinity")
            swap_dir = os.path.join(str(base), "param_stream",
                                    f"rank{jax.process_index()}")
            self._swapper = AsyncTensorSwapper(swap_dir)
            log_dist(f"ZeRO-Infinity param streaming → {swap_dir}", ranks=[0])

    # ------------------------------------------------------------ install
    def install_group(self, key, host_tree):
        """Adopt a block's fp32 params; allocates moments + wire cache."""
        flat, meta = _flatten_f32(host_tree)
        self._meta[key] = meta
        cache = flat.astype(self.wire_dtype) \
            if self.wire_dtype != np.float32 else flat
        state = {k: np.zeros_like(flat) for k in self.KINDS[self.optimizer]}
        if self.state_device == "nvme":
            self._swapper.swap_out(f"{key}:master", flat)
            for k, s in state.items():
                self._swapper.swap_out(f"{key}:{k}", s)
        else:
            self._master[key] = flat
            self._state[key] = state
        if self.param_device == "nvme":
            self._swapper.swap_out(f"{key}:cache", cache)
            if self.wire_dtype == np.float32:
                # cache aliases master in RAM mode only; on NVMe they are
                # separate files, so nothing further to do
                pass
        else:
            self._cache[key] = cache

    def keys(self):
        return tuple(self._meta)

    def param_bytes(self, key):
        return sum(self._meta[key][2]) * self.wire_dtype.itemsize

    # ------------------------------------------------------------ fetch
    def start_fetch(self, key):
        h = _FetchHandle(key)
        if self.param_device == "nvme":
            h.aio_handle = self._swapper.swap_in(f"{key}:cache")
        return h

    def finish_fetch(self, handle, sharding=None):
        """Complete a fetch: host flat vector → device pytree (async put).
        ``sharding``: one jax Sharding applied to every leaf (the executor
        passes mesh-replicated so multi-device steps don't re-broadcast the
        block on every use).  Multi-process meshes assemble through
        ``make_array_from_callback`` — every host holds the same store
        bytes, so each process serves its addressable shards locally."""
        key = handle.key
        if handle.device_tree is not None:
            return handle.device_tree
        flat = (handle.aio_handle.wait() if handle.aio_handle is not None
                else self._cache[key])
        views = _views(flat, self._meta[key])
        if sharding is None:
            put = jax.device_put
        elif jax.process_count() > 1:
            put = (lambda v: jax.make_array_from_callback(
                v.shape, sharding, lambda idx: v[idx]))
        else:
            put = (lambda v: jax.device_put(v, sharding))
        tree = jax.tree_util.tree_map(put, views)
        handle.device_tree = tree
        return tree

    # ------------------------------------------------------------ grads
    def accumulate_grads(self, key, dev_grads):
        """Device grad pytree → host stash (one flat vector per block).
        Multi-process: grads are replicated post-GSPMD-reduce, but each
        process only addresses its shard of the replication — allgather
        them to full host values so every host steps identically."""
        if jax.process_count() > 1 and any(
                not getattr(l, "is_fully_replicated", True)
                for l in jax.tree_util.tree_leaves(dev_grads)):
            # GSPMD normally leaves block grads fully replicated (directly
            # addressable); anything else must gather to full host values
            from jax.experimental import multihost_utils
            dev_grads = multihost_utils.process_allgather(dev_grads)
        leaves = jax.tree_util.tree_leaves(dev_grads)
        for l in leaves:   # start all D2H copies before blocking on any
            if hasattr(l, "copy_to_host_async"):
                l.copy_to_host_async()
        treedef, shapes, sizes = self._meta[key]
        stash = self._grads.get(key)
        first = stash is None
        if first:
            dt = np.float32 if self.grad_accum_fp32 else self.wire_dtype
            stash = self._grads[key] = np.empty(sum(sizes), dt)
        off = 0
        for l, n in zip(leaves, sizes):
            host = np.asarray(l).ravel()
            if first:
                stash[off:off + n] = host
            else:
                # accumulate in the stash dtype (fp32 when gas > 1)
                stash[off:off + n] += host.astype(stash.dtype)
            off += n

    def grad_sq_norm(self):
        """Σ ‖g‖² over every stash (native kernel on an fp32 transient)."""
        from ...ops.cpu_optimizers import cpu_sq_norm
        total = 0.0
        for key, stash in self._grads.items():
            g = stash if stash.dtype == np.float32 else \
                np.ascontiguousarray(stash, dtype=np.float32)
            total += cpu_sq_norm(g)
        return total

    # ------------------------------------------------------------ step
    def _get_kernels(self):
        if self._kernels is None:
            from ...ops import cpu_optimizers as k
            if self.optimizer == "adagrad":
                self._kernels = k.DeepSpeedCPUAdagrad(
                    lr=self.lr, eps=self.eps, weight_decay=self.weight_decay)
            elif self.optimizer == "lion":
                self._kernels = k.DeepSpeedCPULion(
                    lr=self.lr, betas=self.betas,
                    weight_decay=self.weight_decay)
            else:
                self._kernels = k.DeepSpeedCPUAdam(
                    lr=self.lr, betas=self.betas, eps=self.eps,
                    weight_decay=self.weight_decay,
                    adamw_mode=self.adamw_mode)
        return self._kernels

    def optimizer_sweep(self, lr=None, grad_scale=None):
        """One host optimizer step over every block that received gradients.

        ``grad_scale``: optional multiplier folded into the grads (global-norm
        clip coefficient and/or 1/gas averaging).  Updates the wire-dtype
        cache in the same kernel pass (``bf16_out``) — the next device fetch
        streams the new weights without any HBM round-trip.
        """
        kern = self._get_kernels()
        self.step_count += 1
        for key in list(self._grads):
            stash = self._grads.pop(key)
            grad = stash if stash.dtype == np.float32 else \
                np.ascontiguousarray(stash, dtype=np.float32)
            if grad_scale is not None and grad_scale != 1.0:
                grad *= np.float32(grad_scale)
            if self.state_device == "nvme":
                master = self._swapper.swap_in(f"{key}:master",
                                               async_op=False).wait()
                state = {k: self._swapper.swap_in(f"{key}:{k}",
                                                  async_op=False).wait()
                         for k in self.KINDS[self.optimizer]}
            else:
                master, state = self._master[key], self._state[key]
            if self.wire_dtype == BF16:
                if self.param_device == "nvme":
                    cache = np.empty(master.size, BF16)
                else:
                    cache = self._cache[key]
                out = cache.view(np.uint16)
            else:
                cache, out = master, None   # fp32 wire: cache aliases master
            # the kernel wrapper auto-increments per CALL; every block of one
            # sweep must share ONE bias-correction step
            kern.step_count = self.step_count - 1
            if self.optimizer == "adagrad":
                kern.step(master, grad, state["sum"], bf16_out=out, lr=lr)
            elif self.optimizer == "lion":
                kern.step(master, grad, state["m"], bf16_out=out, lr=lr)
            else:
                kern.step(master, grad, state["m"], state["v"], bf16_out=out,
                          lr=lr)
            if self.state_device == "nvme":
                self._swapper.swap_out(f"{key}:master", master)
                for k, s in state.items():
                    self._swapper.swap_out(f"{key}:{k}", s)
            if self.param_device == "nvme":
                if self.wire_dtype == np.float32:
                    cache = master
                self._swapper.swap_out(f"{key}:cache", cache)
            elif self.wire_dtype == np.float32 and \
                    master is not self._cache.get(key):
                # fp32 wire + RAM param cache + NVMe state: the kernel
                # updated the freshly-swapped-in master, not the RAM cache
                # the next fetch reads — copy it back or training silently
                # freezes the device weights
                self._cache[key][:] = master
        if self._swapper is not None:
            # writes must be durable before the next step's reads
            self._swapper.synchronize()

    # ------------------------------------------------- checkpoint interface
    def export_master(self):
        """{key: fp32 host pytree} — consumed by checkpointing."""
        out = {}
        for key, meta in self._meta.items():
            if self.state_device == "nvme":
                flat = self._swapper.swap_in(f"{key}:master",
                                             async_op=False).wait()
            else:
                flat = self._master[key]
            out[key] = jax.tree_util.tree_map(np.copy, _views(flat, meta))
        return out

    def export_state(self):
        out = {"step_count": self.step_count, "kinds": {}}
        for key, meta in self._meta.items():
            if self.state_device == "nvme":
                st = {k: self._swapper.swap_in(f"{key}:{k}",
                                               async_op=False).wait()
                      for k in self.KINDS[self.optimizer]}
            else:
                st = self._state[key]
            out["kinds"][key] = {k: np.copy(v) for k, v in st.items()}
        return out

    def import_master(self, trees):
        for key, tree in trees.items():
            flat, meta = _flatten_f32(tree)
            self._meta[key] = meta
            cache = flat.astype(self.wire_dtype) \
                if self.wire_dtype != np.float32 else flat
            if self.state_device == "nvme":
                self._swapper.swap_out(f"{key}:master", flat)
            else:
                self._master[key] = flat
            if self.param_device == "nvme":
                self._swapper.swap_out(f"{key}:cache", cache)
            else:
                self._cache[key] = cache
        if self._swapper is not None:
            self._swapper.synchronize()

    def import_state(self, state):
        self.step_count = int(state["step_count"])
        for key, kinds in state["kinds"].items():
            flat_state = {k: np.ascontiguousarray(v, dtype=np.float32).ravel()
                          for k, v in kinds.items()}
            if self.state_device == "nvme":
                for k, v in flat_state.items():
                    self._swapper.swap_out(f"{key}:{k}", v)
            else:
                self._state[key] = flat_state
        if self._swapper is not None:
            self._swapper.synchronize()
