"""ZeRO++ — quantized ZeRO communication (qwZ / qgZ / hpZ).

TPU-native re-design of the reference's ZeRO++ stack (wiring at
``runtime/zero/stage3.py:123`` + ``runtime/engine.py:906-913``, kernels in
``csrc/quantization``, collectives in
``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce``):

* **qwZ** (quantized weight all-gather): the stage-3 forward/backward param
  all-gather moves int8 + per-group scales instead of bf16 — ~2× gather
  traffic reduction.  Implemented as a ``shard_map`` wrapper around each
  dp-sharded leaf: quantize local shard → ``lax.all_gather`` the int8 payload
  → dequantize → reassemble.  Composes with TP sharding (only the ZeRO axes
  are gathered).
* **qgZ** (quantized gradient reduce): gradients are reduced with a single
  quantized all-to-all + local sum (int8 payload, fp32 accumulation).  The
  reference needs a *hierarchical* 2-hop (intra-node all-to-all, dequant-
  reduce, inter-node all-to-all with ``swizzled_quantize``) because NCCL
  all-to-all crosses nodes at full fan-out; on a TPU torus the single
  mesh-axis all-to-all already rides ICI neighbor links, so the 1-hop scheme
  gets the same 4× volume reduction with ONE quantization error instead of
  two.  When the ZeRO group spans a genuine hierarchy (dp×ep, hpZ's
  zp_outer×zp) and ``comm_optimizations.hierarchical_allreduce`` is on, the
  reduction upgrades to the true 2-hop scheme from
  ``comm/collectives/quantized.py``: full-precision reduce-scatter on the
  intra axes, quantized all-to-all across the inter axes on 1/n of the data.
* **hpZ** (secondary partition) is a *sharding policy*, not a collective:
  ``ZeroPartitionPlan(hpz_mesh=...)`` shards params over the intra-host "zp"
  mesh factor only (see ``partition.py``).

The quantized collective primitives themselves live in
``comm/collectives/quantized.py`` (shared with the eager ``dist.*`` engine
and ``ds_bench``); this module owns the ZeRO-side orchestration.

qgZ requires taking over the gradient reduction from GSPMD.  Since
ISSUE 15 the DEFAULT vehicle for that is the GSPMD-first micro
(``runtime/zero/gspmd.py``): one jit with per-leaf codec+collective
islands, XLA scheduling everything around them.  The full-manual
(``shard_map``-everything) micro below — :func:`build_manual_dp_micro` —
remains for the compositions the islands cannot express yet (tp>1 via
PARTIAL-manual shard_map, hpZ/MiCS reshaped meshes, MoE's manual-context
dispatch, dp×ep hierarchies) and for ``comm_optimizations.zero_mode:
"flat_manual"`` (the ``ds_bench --zero-mode`` baseline lane); sp/pp are
rejected loudly (their collectives interleave with the reduction being
replaced).

With ``comm_optimizations.overlap`` enabled the manual reduction runs the
bucketed two-stage pipeline from ``runtime/zero/overlap.py`` — intra-node
psum_scatter of bucket *k* overlapping the quantized inter-node
all-to-all of bucket *k−1* (docs/overlap.md).  With
``comm_optimizations.overlap.prefetch`` enabled the forward param
all-gather is the mirror image: ``pipelined_gather`` issues bucket *k+1*'s
(quantized, when qwZ) gather while bucket *k*'s layers compute, with a
``max_inflight`` window clamped by ``stage3_max_live_parameters``.
"""

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

# canonical quantized-collective primitives (also the back-compat import
# surface: tests and user code import these names from here)
from ...comm.collectives.quantized import (DEFAULT_GROUP_SIZE,
                                           all_to_all_quant_reduce,
                                           hierarchical_quant_reduce_scatter,
                                           qdq_all_gather_st,
                                           quantized_all_gather)
from .partition import (gathered_spec as _gathered_spec,
                        zero_dim as _zero_dim)


def _entry_names(entry):
    """Spec entry → tuple of axis names (shared normalize for the spec
    rewriters below)."""
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry, )


def _collapse(names):
    """Axis-name tuple → spec entry (len-collapse inverse of _entry_names)."""
    return names if len(names) > 1 else (names[0] if names else None)


def quantized_weight_gather(params, plan, wire_format="int8",
                            group_size=DEFAULT_GROUP_SIZE, prefetch=None):
    """qwZ in GSPMD mode: explicitly gather every ZeRO-sharded param with a
    quantized payload; XLA sees already-replicated (over dp) values and
    inserts no further gather.  Differentiable (straight-through; backward is
    the standard reduce-scatter).  Usable both outside and inside
    ``jax.jit``.

    ``prefetch`` (a dict from ``overlap.resolve_prefetch``) pipelines the
    per-leaf gathers bucket by bucket in forward-layer order with a bounded
    in-flight window (``overlap.pipelined_gather``) — the stage-3 prefetch
    coordinator over the quantized wire.  Persistent leaves are excluded
    from the pipeline (the gather below is the identity for them anyway).
    """
    from .partition import path_str
    mesh = plan.param_mesh

    def gather_one(path, x):
        spec = plan.param_spec(x.shape, path)
        # per-leaf axes: a rule-claimed axis (the expert "ep" dim, tp) is
        # model parallelism — never gathered here
        leaf_axes = plan.leaf_zero_axes(path, plan.param_axes)
        dim, axes = _zero_dim(spec, leaf_axes)
        if dim is None:
            return x
        out_spec = _gathered_spec(spec, leaf_axes)
        # per-leaf wire through the autotuned size ladder — x is the
        # GLOBAL array in GSPMD mode, so x.size is the logical (gathered)
        # message size the probes/dispatch key on; "fp32" rungs take the
        # plain gather inside the same straight-through wrapper
        fmt = plan.wire_for_size(wire_format,
                                 x.size * x.dtype.itemsize)
        # positional call: custom_vjp rejects kwargs for nondiff argnums.
        # The island is a gspmd_region (ISSUE 15): entered/exited through
        # straight-through sharding constraints so GSPMD resumes
        # propagation from the declared layout WITHOUT the constraint's
        # transpose forcing the gather's cotangent replicated.
        from ...comm.collectives.engine import gspmd_region
        fn = gspmd_region(
            lambda t: qdq_all_gather_st(t, axes, dim, fmt, group_size),
            mesh=mesh, in_specs=(spec, ), out_specs=out_spec,
            grad_transparent=True)
        return fn(x)

    if prefetch is not None:
        from .overlap import pipelined_gather, prefetch_buckets_for
        buckets, window, _ = prefetch_buckets_for(params, plan, prefetch)
        if buckets:
            return pipelined_gather(params, buckets, gather_one, window)
    return jax.tree_util.tree_map_with_path(
        lambda kp, x: gather_one(path_str(kp), x), params)


def build_manual_dp_micro(engine):
    """Manual-SPMD micro-step for the qgZ path.

    The GSPMD micro-step lets XLA insert the DP gradient reduction (bf16/f32);
    to quantize that traffic we compute grads per-shard under ``shard_map``
    and reduce them ourselves:

        per device:  local loss/grad on the local batch shard
        qwZ (opt.):  int8 param all-gather for stage-3 sharded params
        qgZ:         int8 all-to-all reduce-scatter into the master partition
                     (2-hop hierarchical when the group spans dp×ep / hpZ
                     axes and comm_optimizations asks for hierarchy)

    Returns ``micro(params, scale, inputs) -> (loss, grads)`` with grads in
    the master (ZeRO) sharding — drop-in for the engine's compiled micro fn.
    """
    plan = engine.plan
    zc = engine._config.zero_config
    co = engine._config.comm_optimizations_config
    gas = engine.gradient_accumulation_steps()
    apply_fn = engine._effective_apply_fn()
    grad_dtype = engine.grad_accum_dtype
    if engine.seq_parallel_world_size > 1 or engine.pp_world_size > 1:
        raise ValueError(
            "zero_quantized_gradients supports dp/ep (+tp) meshes only — "
            "sp/pp interleave their own collectives with the DP gradient "
            "reduction this path replaces; disable "
            "zero_quantized_gradients or drop the sp/pp axes")
    # tp > 1 runs in PARTIAL-manual mode: shard_map is manual over the dp
    # axes (where the quantized collectives live) while "tp" stays an auto
    # axis — GSPMD keeps inserting the tensor-parallel collectives inside
    # the body exactly as in the normal micro-step.
    manual_only = engine.mp_world_size > 1
    if manual_only:
        from ...utils import jax_compat
        if jax_compat.is_legacy_shard_map():
            # this jaxlib's SPMD partitioner CHECK-fails (native abort, takes
            # the whole process) lowering partial-manual programs with
            # collectives inside — refuse cleanly instead
            raise ValueError(
                "zero_quantized_gradients with tp > 1 needs the modern "
                "jax.shard_map partial-manual lowering; this jax only has "
                "the legacy experimental shard_map, whose partitioner "
                "aborts on manual-subgroup sharding. Upgrade jax, or "
                "disable zero_quantized_gradients / drop the tp axis")
    # With hpZ/MiCS the manual step runs over the reshaped hpz mesh, whose
    # (zp_outer, zp) axes tile the same device order as (dp, ep) on the
    # global mesh — full-dp specs are translated axis-for-axis.
    hpz_active = (plan.param_mesh is not plan.mesh or
                  plan.state_mesh is not plan.mesh)
    if hpz_active:
        from ...utils.groups import ZP_AXIS, ZP_OUTER_AXIS
        mesh = plan.param_mesh
        dp_axes = (ZP_OUTER_AXIS, ZP_AXIS)

        def _translate(spec):
            out = []
            for entry in spec:
                names = _entry_names(entry)
                if any(a in ("dp", "ep") for a in names):
                    names = tuple(a for a in names
                                  if a not in ("dp", "ep")) + dp_axes
                out.append(_collapse(names))
            return P(*out)
    else:
        mesh = plan.mesh
        dp_axes = plan.zero_axes
        _translate = lambda spec: spec
    qw = zc.zero_quantized_weights or (
        getattr(co, "enabled", False) and getattr(co, "quantized_weights",
                                                  False))
    qw_fmt, qw_gs = plan.param_wire(zc.zero_quantized_weights_format)
    qg_fmt, qg_gs = plan.grad_wire()

    def _grad_leaf_fmt(g):
        # per-leaf wire through the autotuned size ladder; inside the
        # manual body g carries the FULL gradient shape (each rank reduces
        # its whole-gradient copy), so g.size is the logical message size
        # — the same quantity the eager dispatch and the probes key on
        return plan.wire_for_size(qg_fmt, g.size * g.dtype.itemsize)
    hier = plan.hierarchical_reduce()
    # bucketed overlap scheduler: pipeline the quantized inter-node hop of
    # bucket k with the intra-node work of bucket k+1 (docs/overlap.md)
    from .overlap import overlap_opts, prefetch_opts, resolve_prefetch
    ov = overlap_opts(co)
    overlap_on = ov is not None
    # forward-direction prefetch: pipeline the stage-3 param all-gather
    # bucket by bucket under the early layers' compute (docs/overlap.md
    # forward-prefetch section); a no-op below stage 3 where every leaf is
    # persistent and the bucket list comes back empty
    pf = prefetch_opts(co)
    pf_resolved = resolve_prefetch(pf, zc) if pf is not None else None

    from .partition import path_str
    from ..utils import make_scaled_loss_fn
    loss_fn = make_scaled_loss_fn(apply_fn, gas)

    manual_axes = frozenset(
        a for a in (dp_axes if isinstance(dp_axes, tuple) else (dp_axes, )))

    def _manual_spec(spec):
        """Project a spec onto the manual axes (partial-manual shard_map
        in/out specs may reference ONLY the manual axis names; auto-axis
        sharding rides on the operands themselves)."""
        return P(*[_collapse(tuple(a for a in _entry_names(e)
                                   if a in manual_axes)) for e in spec])

    def _leaf_hier(spec, leaf_axes=None):
        """(dim, outer_axes, inner_axes) when this leaf's reduction should
        run the 2-hop scheme, else None.  Mesh axis order is major→minor, so
        the FIRST effective axis crosses the slower fabric.  ``leaf_axes``
        restricts the search to the leaf's OWN reducible axes (expert
        leaves exclude their claimed "ep" dim)."""
        if not hier:
            return None
        dim, axes = _zero_dim(spec, dp_axes if leaf_axes is None
                              else leaf_axes)
        if dim is None:
            return None
        eff = tuple(a for a in axes if mesh.shape[a] > 1)
        if len(eff) < 2:
            return None
        return dim, eff[:1], eff[1:]

    def _hier_spec(spec, leaf_axes=None):
        """Reorder a hier leaf's zero-dim axes to the inner-major tiling the
        2-hop reduce-scatter produces (see
        ``hierarchical_quant_reduce_scatter``); the apply step reshards to
        the canonical master layout at the gas boundary."""
        info = _leaf_hier(spec, leaf_axes)
        if info is None:
            return spec
        dim, outer, inner = info
        entry = _entry_names(spec[dim])
        z = set(outer + inner)
        new_z = iter(inner + outer)
        new_entry = tuple(next(new_z) if a in z else a for a in entry)
        out = list(spec)
        out[dim] = _collapse(new_entry)
        return P(*out)

    def _claimed_divisor(leaf_axes):
        n = 1
        for a in dp_axes:
            if a not in leaf_axes:
                n *= mesh.shape[a]
        return n

    def _finish_reduce(out, reduced_axes, leaf_axes):
        """Close a leaf's reduction: mean over the leaf's remaining
        reducible axes, then the extra divisor for claimed (model-parallel)
        axes — those ranks' loss terms already arrived through the forward
        collectives' transposes (the expert dispatch), but the global-mean
        loss normalization still counts them."""
        rest = tuple(a for a in leaf_axes if a not in reduced_axes)
        if rest:
            out = jax.lax.pmean(out, rest)
        extra = _claimed_divisor(leaf_axes)
        if extra > 1:
            out = out / extra
        return out

    def _unsharded_reduce(g, leaf_axes):
        """Reduction of a leaf with no reducible sharded dim.  The common
        (no claimed axes) case keeps the exact historical pmean; claimed
        leaves sum over their own group only and divide by the full loss
        normalization."""
        if tuple(leaf_axes) == tuple(dp_axes):
            return jax.lax.pmean(g, dp_axes)
        out = jax.lax.pmean(g, leaf_axes) if leaf_axes else g
        extra = _claimed_divisor(leaf_axes)
        if extra > 1:
            out = out / extra
        return out

    def micro(params, scale, inputs):
        # specs must come from the GLOBAL shapes, captured here where params
        # are still global arrays — inside the shard_map body the leaves are
        # local shards (params) and spec inference from their shapes picks
        # the wrong dim (e.g. a (16,16) param sharded to (2,16) looks
        # dim-1-shardable); grads keep global shapes today (they come from
        # the gathered full params) but get the same treatment so the body
        # never depends on in-body shapes.
        gather_specs = {}
        reduce_specs = {}
        # per-leaf reducible/gatherable axes: rule-claimed model axes (the
        # expert stack's "ep", tp dims) are NOT ZeRO shards — expert params
        # must stay local to their ep rank through the gather, and expert
        # grads reduce over the expert-DP ("dp") group only (reference
        # engine.py:2510 _reduce_expert_gradients)
        gather_axes = {}
        reduce_axes = {}

        def _record(kp, x):
            p = path_str(kp)
            claimed = plan.rule_claimed_axes(p)
            if hpz_active and any(a in ("dp", "ep") for a in claimed):
                raise ValueError(
                    f"hpZ/MiCS shard groups cannot compose with a tp rule "
                    f"claiming the dp/ep axes (leaf {p!r} claims "
                    f"{claimed}): the zp translation would fold the expert "
                    "axis into the shard group; drop "
                    "zero_hpz_partition_size/mics_shard_size or the rule")
            gather_specs[p] = plan.param_spec(x.shape, p)
            gather_axes[p] = plan.leaf_zero_axes(p, plan.param_axes)
            spec = _translate(plan.master_spec(x.shape, p))
            if manual_only:
                spec = _manual_spec(spec)
            reduce_specs[p] = spec
            reduce_axes[p] = plan.leaf_zero_axes(p, dp_axes)

        jax.tree_util.tree_map_with_path(_record, params)
        param_specs = jax.tree_util.tree_map(_translate,
                                             plan.param_specs(params),
                                             is_leaf=lambda x: isinstance(
                                                 x, P))
        master_specs = jax.tree_util.tree_map(_translate,
                                              plan.master_specs(params),
                                              is_leaf=lambda x: isinstance(
                                                  x, P))
        if manual_only:
            param_specs = jax.tree_util.tree_map(
                _manual_spec, param_specs,
                is_leaf=lambda x: isinstance(x, P))
            master_specs = jax.tree_util.tree_map(
                _manual_spec, master_specs,
                is_leaf=lambda x: isinstance(x, P))
        # hier leaves come out of the 2-hop reduce tiled inner-major
        grad_out_specs = jax.tree_util.tree_map_with_path(
            lambda kp, s: _hier_spec(s, reduce_axes.get(path_str(kp))),
            master_specs, is_leaf=lambda x: isinstance(x, P))
        from ..utils import batch_input_specs
        batch_specs = batch_input_specs(inputs, dp_axes,
                                        engine._n_replicated_batch_tail)
        # prefetch buckets from GLOBAL shapes (same reason as the specs
        # above: inside the shard_map body the leaves are local shards and
        # both sizes and spec inference would be wrong)
        pf_buckets, pf_window = (), 1
        if pf_resolved is not None:
            from .overlap import prefetch_buckets_for
            pf_buckets, pf_window, _ = prefetch_buckets_for(
                params, plan, pf_resolved)

        def _overlapped_reduce(grads):
            """Per-bucket two-stage reduction, same math as reduce_leaf:
            stage1 = full-precision intra-node psum_scatter (hier leaves
            only), stage2 = quantized inter-node all-to-all reduce +
            trailing pmean/cast.  The pipeline fences bucket k's stage2
            behind bucket k−max_inflight's output so the DCN hop of one
            bucket overlaps the ICI hop of the next."""
            from .overlap import (bucket_bytes_of, pipelined_bucket_reduce,
                                  tree_buckets)
            buckets, _, _ = tree_buckets(grads, bucket_bytes_of(ov))
            # ladder formats key on the FULL leaf size stage1 sees, not the
            # intra-scattered piece stage2 receives for hier leaves
            from .partition import path_str as _ps
            fmt_by_path = {
                _ps(kp): _grad_leaf_fmt(g)
                for kp, g in
                jax.tree_util.tree_flatten_with_path(grads)[0]}

            def stage1(path, g):
                info = _leaf_hier(reduce_specs[path], reduce_axes[path])
                if info is None:
                    return g
                dim, _, inner = info
                part = g
                for a in inner:
                    part = jax.lax.psum_scatter(part, a,
                                                scatter_dimension=dim,
                                                tiled=True)
                return part

            def stage2(path, h):
                spec = reduce_specs[path]
                leaf_axes = reduce_axes[path]
                dim, axes = _zero_dim(spec, leaf_axes)
                if dim is None:
                    return _unsharded_reduce(h, leaf_axes).astype(grad_dtype)
                fmt = fmt_by_path[path]
                info = _leaf_hier(spec, leaf_axes)
                if info is not None:
                    _, outer, inner = info
                    n_out = 1
                    for a in outer:
                        n_out *= mesh.shape[a]
                    n_in = 1
                    for a in inner:
                        n_in *= mesh.shape[a]
                    out = all_to_all_quant_reduce(h, outer, dim, n_out,
                                                  wire_format=fmt,
                                                  group_size=qg_gs,
                                                  mean=False)
                    out = out / (n_in * n_out)
                else:
                    n = 1
                    for a in axes:
                        n *= mesh.shape[a]
                    out = all_to_all_quant_reduce(h, axes, dim, n,
                                                  wire_format=fmt,
                                                  group_size=qg_gs)
                return _finish_reduce(out, axes, leaf_axes).astype(
                    grad_dtype)

            return pipelined_bucket_reduce(
                grads, buckets, stage1, stage2,
                max_inflight=getattr(ov, "max_inflight", 2))

        def body(params, inputs):
            # stage-3: reassemble full params from local shards (int8 when qwZ)
            def gather_one(path, x):
                spec = gather_specs[path]
                # per-leaf axes: rule-claimed model axes (the expert "ep"
                # dim) are NOT ZeRO shards — expert params stay local to
                # their ep rank and the dispatch a2a moves tokens instead
                dim, axes = _zero_dim(spec, gather_axes[path])
                if dim is None:
                    return x
                if qw:
                    # per-leaf ladder keys on the GATHERED (logical) size —
                    # x here is this rank's 1/n shard
                    n_g = 1
                    for a in axes:
                        n_g *= mesh.shape[a]
                    fmt = plan.wire_for_size(
                        qw_fmt, x.size * n_g * x.dtype.itemsize)
                    return quantized_all_gather(x, axes, dim, fmt, qw_gs)
                return jax.lax.all_gather(x, axes, axis=dim, tiled=True)

            if pf_buckets:
                # forward prefetch: per-bucket gathers with a bounded
                # in-flight window instead of one up-front tree gather
                from .overlap import pipelined_gather
                full = pipelined_gather(params, pf_buckets, gather_one,
                                        pf_window)
            else:
                full = jax.tree_util.tree_map_with_path(
                    lambda kp, x: gather_one(path_str(kp), x), params)
            (_, loss), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(full, scale, inputs)
            loss = jax.lax.pmean(loss, dp_axes)

            def reduce_leaf(kp, g):
                # translated spec lives in manual-mode axis space (dp_axes ∪
                # zp), so searching dp_axes covers plain/hpZ/MiCS alike;
                # per-leaf axes keep expert ("ep"-claimed) leaves on their
                # expert-DP reduction group
                p = path_str(kp)
                spec = reduce_specs[p]
                leaf_axes = reduce_axes[p]
                dim, axes = _zero_dim(spec, leaf_axes)
                if dim is None:
                    return _unsharded_reduce(g, leaf_axes).astype(grad_dtype)
                fmt = _grad_leaf_fmt(g)
                info = _leaf_hier(spec, leaf_axes)
                if info is not None:
                    _, outer, inner = info
                    n_out = 1
                    for a in outer:
                        n_out *= mesh.shape[a]
                    n_in = 1
                    for a in inner:
                        n_in *= mesh.shape[a]
                    out = hierarchical_quant_reduce_scatter(
                        g, inner, outer, dim, n_in, n_out,
                        wire_format=fmt, group_size=qg_gs)
                else:
                    n = 1
                    for a in axes:
                        n *= mesh.shape[a]
                    out = all_to_all_quant_reduce(g, axes, dim, n,
                                                  wire_format=fmt,
                                                  group_size=qg_gs)
                return _finish_reduce(out, axes, leaf_axes).astype(
                    grad_dtype)

            if overlap_on:
                grads = _overlapped_reduce(grads)
            else:
                grads = jax.tree_util.tree_map_with_path(reduce_leaf, grads)
            return loss, grads

        kw = dict(mesh=mesh, in_specs=(param_specs, batch_specs),
                  out_specs=(P(), grad_out_specs), check_vma=False)
        if manual_only:
            kw["axis_names"] = manual_axes  # tp stays auto (GSPMD)
        fn = shard_map(body, **kw)
        return fn(params, inputs)

    return micro
