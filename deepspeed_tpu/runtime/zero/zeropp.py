"""ZeRO++ — quantized ZeRO communication (qwZ / qgZ / hpZ).

TPU-native re-design of the reference's ZeRO++ stack (wiring at
``runtime/zero/stage3.py:123`` + ``runtime/engine.py:906-913``, kernels in
``csrc/quantization``, collectives in
``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce``):

* **qwZ** (quantized weight all-gather): the stage-3 forward/backward param
  all-gather moves int8 + per-group scales instead of bf16 — ~2× gather
  traffic reduction.  Implemented as a ``shard_map`` wrapper around each
  dp-sharded leaf: quantize local shard → ``lax.all_gather`` the int8 payload
  → dequantize → reassemble.  Composes with TP sharding (only the ZeRO axes
  are gathered).
* **qgZ** (quantized gradient reduce): gradients are reduced with a single
  quantized all-to-all + local sum (int8 payload, fp32 accumulation).  The
  reference needs a *hierarchical* 2-hop (intra-node all-to-all, dequant-
  reduce, inter-node all-to-all with ``swizzled_quantize``) because NCCL
  all-to-all crosses nodes at full fan-out; on a TPU torus the single
  mesh-axis all-to-all already rides ICI neighbor links, so the 1-hop scheme
  gets the same 4× volume reduction with ONE quantization error instead of
  two.
* **hpZ** (secondary partition) is a *sharding policy*, not a collective:
  ``ZeroPartitionPlan(hpz_mesh=...)`` shards params over the intra-host "zp"
  mesh factor only (see ``partition.py``).

qgZ requires taking over the gradient reduction from GSPMD, so the engine
switches its micro-step to a manual-SPMD (``shard_map``) variant — see
:func:`build_manual_dp_micro`.  That path supports dp/ep meshes, and tp>1
via PARTIAL-manual shard_map (manual over the dp axes, "tp" left auto so
GSPMD keeps inserting the tensor-parallel collectives); sp/pp are rejected
loudly (their collectives interleave with the reduction being replaced).
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ...ops.pallas.quantizer import dequantize_blockwise, quantize_blockwise

DEFAULT_GROUP_SIZE = 2048


def _zero_dim(spec, zero_axes):
    """Locate the dim carrying ZeRO axes.  Returns (dim, axes_present) or
    (None, ())."""
    for i, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry, )
        present = tuple(a for a in names if a in zero_axes)
        if present:
            return i, present
    return None, ()


def _entry_names(entry):
    """Spec entry → tuple of axis names (shared normalize for the three
    spec rewriters below)."""
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry, )


def _collapse(names):
    """Axis-name tuple → spec entry (len-collapse inverse of _entry_names)."""
    return names if len(names) > 1 else (names[0] if names else None)


def _strip_axes(spec, dim, axes):
    """Remove ``axes`` from ``spec[dim]`` (gathered result keeps e.g. tp)."""
    entry = spec[dim]
    names = entry if isinstance(entry, tuple) else (entry, )
    kept = tuple(a for a in names if a not in axes)
    new = list(spec)
    new[dim] = kept if len(kept) > 1 else (kept[0] if kept else None)
    return P(*new)


# wire formats for qwZ payloads: name → (quantize, dequantize) closures.
# "int8"/"int4" ride the blockwise integer kernels; "fp8"/"fp6"/"fp12" the FP
# quantizer (reference csrc/fp_quantizer — fp6 packs 4 values → 3 bytes, so
# the allgather volume drops to 3/8 of bf16).
_FP_FORMATS = {"fp8": (8, 3), "fp6": (6, 2), "fp12": (12, 7)}


def _wire_codec(wire_format, group_size):
    if wire_format in ("int8", "int4"):
        bits = 8 if wire_format == "int8" else 4
        quant = lambda x: quantize_blockwise(x, num_bits=bits,
                                             group_size=group_size,
                                             use_pallas=False)
        dequant = lambda q, s, m: dequantize_blockwise(q, s, m,
                                                       use_pallas=False)
        return quant, dequant
    if wire_format in _FP_FORMATS:
        from ...ops.fp_quantizer import dequantize_fp, quantize_fp
        bits, man = _FP_FORMATS[wire_format]
        quant = lambda x: quantize_fp(x, q_bits=bits, mantissa_bits=man,
                                      group_size=group_size, use_pallas=False)
        return quant, dequantize_fp
    raise ValueError(f"unknown qwZ wire format {wire_format!r} "
                     f"(have int8, int4, {', '.join(_FP_FORMATS)})")


def quantized_all_gather(x, ax_names, dim, wire_format="int8",
                         group_size=DEFAULT_GROUP_SIZE):
    """Inside-shard_map: quantize-gather the local tile along mesh axes
    ``ax_names``, reassembling the full dim in axis-index order (matches GSPMD
    tiling order).  The wire payload is quantized values + one f32 scale per
    ``group_size`` elements (reference qwZ, csrc/quantization/quantize.cu;
    fp formats via csrc/fp_quantizer analog)."""
    quant, dequant = _wire_codec(wire_format, group_size)
    q, s, meta = quant(x)
    qg = jax.lax.all_gather(q, ax_names)
    sg = jax.lax.all_gather(s, ax_names)
    parts = jax.vmap(lambda qq, ss: dequant(qq, ss, meta))(qg, sg)
    return jnp.concatenate(list(parts), axis=dim)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _qdq_all_gather_st(x, ax_names, dim, wire_format, group_size):
    """Straight-through quantized gather: forward is the quantized gather;
    backward is the exact VJP of a plain all-gather (reduce-scatter of the
    cotangent) — the quantization rounding must not zero the gradient."""
    return quantized_all_gather(x, ax_names, dim, wire_format, group_size)


def _qdq_fwd(x, ax_names, dim, wire_format, group_size):
    return _qdq_all_gather_st(x, ax_names, dim, wire_format, group_size), None


def _qdq_bwd(ax_names, dim, wire_format, group_size, _, dy):
    return (jax.lax.psum_scatter(dy, ax_names, scatter_dimension=dim,
                                 tiled=True), )


_qdq_all_gather_st.defvjp(_qdq_fwd, _qdq_bwd)


def quantized_weight_gather(params, plan, wire_format="int8",
                            group_size=DEFAULT_GROUP_SIZE):
    """qwZ in GSPMD mode: explicitly gather every ZeRO-sharded param with a
    quantized payload; XLA sees already-replicated (over dp) values and
    inserts no further gather.  Differentiable (straight-through; backward is
    the standard reduce-scatter).  Usable both outside and inside
    ``jax.jit``."""
    from .partition import path_str
    mesh = plan.param_mesh

    def gather_leaf(kp, x):
        spec = plan.param_spec(x.shape, path_str(kp))
        dim, axes = _zero_dim(spec, plan.param_axes)
        if dim is None:
            return x
        out_spec = _strip_axes(spec, dim, axes)
        # positional call: custom_vjp rejects kwargs for nondiff argnums
        fn = shard_map(
            lambda t: _qdq_all_gather_st(t, axes, dim, wire_format,
                                         group_size),
            mesh=mesh, in_specs=(spec, ), out_specs=out_spec, check_vma=False)
        return fn(x)

    return jax.tree_util.tree_map_with_path(gather_leaf, params)


def all_to_all_quant_reduce(g, ax_names, dim, n, num_bits=8,
                            group_size=DEFAULT_GROUP_SIZE):
    """Inside-shard_map: quantized reduce-scatter of a (replicated) gradient:
    split along ``dim`` into ``n`` partitions, int8 all-to-all so rank i
    receives every rank's partition i, dequantize and average in fp32.
    Returns this rank's partition (reference ``all_to_all_quant_reduce``,
    runtime/comm/coalesced_collectives.py:31 — single-hop on ICI, see module
    docstring)."""
    chunks = jnp.stack(jnp.split(g, n, axis=dim))  # [n, ...chunk]

    def q_one(c):
        return quantize_blockwise(c, num_bits=num_bits, group_size=group_size,
                                  use_pallas=False)[:2]

    meta_shape = chunks.shape[1:]
    _, _, meta = quantize_blockwise(chunks[0], num_bits=num_bits,
                                    group_size=group_size, use_pallas=False)
    q, s = jax.vmap(q_one)(chunks)
    qx = jax.lax.all_to_all(q, ax_names, split_axis=0, concat_axis=0)
    sx = jax.lax.all_to_all(s, ax_names, split_axis=0, concat_axis=0)
    parts = jax.vmap(lambda qq, ss: dequantize_blockwise(
        qq, ss, (meta_shape, jnp.float32, meta[2]), use_pallas=False))(qx, sx)
    return jnp.sum(parts.astype(jnp.float32), axis=0) / n


def build_manual_dp_micro(engine):
    """Manual-SPMD micro-step for the qgZ path.

    The GSPMD micro-step lets XLA insert the DP gradient reduction (bf16/f32);
    to quantize that traffic we compute grads per-shard under ``shard_map``
    and reduce them ourselves:

        per device:  local loss/grad on the local batch shard
        qwZ (opt.):  int8 param all-gather for stage-3 sharded params
        qgZ:         int8 all-to-all reduce-scatter into the master partition

    Returns ``micro(params, scale, inputs) -> (loss, grads)`` with grads in
    the master (ZeRO) sharding — drop-in for the engine's compiled micro fn.
    """
    plan = engine.plan
    zc = engine._config.zero_config
    gas = engine.gradient_accumulation_steps()
    apply_fn = engine._effective_apply_fn()
    grad_dtype = engine.grad_accum_dtype
    if engine.seq_parallel_world_size > 1 or engine.pp_world_size > 1:
        raise ValueError(
            "zero_quantized_gradients supports dp/ep (+tp) meshes only — "
            "sp/pp interleave their own collectives with the DP gradient "
            "reduction this path replaces; disable "
            "zero_quantized_gradients or drop the sp/pp axes")
    # tp > 1 runs in PARTIAL-manual mode: shard_map is manual over the dp
    # axes (where the quantized collectives live) while "tp" stays an auto
    # axis — GSPMD keeps inserting the tensor-parallel collectives inside
    # the body exactly as in the normal micro-step.
    manual_only = engine.mp_world_size > 1
    # With hpZ/MiCS the manual step runs over the reshaped hpz mesh, whose
    # (zp_outer, zp) axes tile the same device order as (dp, ep) on the
    # global mesh — full-dp specs are translated axis-for-axis.
    hpz_active = (plan.param_mesh is not plan.mesh or
                  plan.state_mesh is not plan.mesh)
    if hpz_active:
        from ...utils.groups import ZP_AXIS, ZP_OUTER_AXIS
        mesh = plan.param_mesh
        dp_axes = (ZP_OUTER_AXIS, ZP_AXIS)

        def _translate(spec):
            out = []
            for entry in spec:
                names = _entry_names(entry)
                if any(a in ("dp", "ep") for a in names):
                    names = tuple(a for a in names
                                  if a not in ("dp", "ep")) + dp_axes
                out.append(_collapse(names))
            return P(*out)
    else:
        mesh = plan.mesh
        dp_axes = plan.zero_axes
        _translate = lambda spec: spec
    qw = zc.zero_quantized_weights

    from .partition import path_str
    from ..utils import make_scaled_loss_fn
    loss_fn = make_scaled_loss_fn(apply_fn, gas)

    manual_axes = frozenset(
        a for a in (dp_axes if isinstance(dp_axes, tuple) else (dp_axes, )))

    def _manual_spec(spec):
        """Project a spec onto the manual axes (partial-manual shard_map
        in/out specs may reference ONLY the manual axis names; auto-axis
        sharding rides on the operands themselves)."""
        return P(*[_collapse(tuple(a for a in _entry_names(e)
                                   if a in manual_axes)) for e in spec])

    def micro(params, scale, inputs):
        param_specs = jax.tree_util.tree_map(_translate,
                                             plan.param_specs(params),
                                             is_leaf=lambda x: isinstance(
                                                 x, P))
        master_specs = jax.tree_util.tree_map(_translate,
                                              plan.master_specs(params),
                                              is_leaf=lambda x: isinstance(
                                                  x, P))
        if manual_only:
            param_specs = jax.tree_util.tree_map(
                _manual_spec, param_specs,
                is_leaf=lambda x: isinstance(x, P))
            master_specs = jax.tree_util.tree_map(
                _manual_spec, master_specs,
                is_leaf=lambda x: isinstance(x, P))
        from ..utils import batch_input_specs
        batch_specs = batch_input_specs(inputs, dp_axes,
                                        engine._n_replicated_batch_tail)

        def body(params, inputs):
            # stage-3: reassemble full params from local shards (int8 when qwZ)
            def gather_leaf(kp, x):
                spec = plan.param_spec(x.shape, path_str(kp))
                dim, axes = _zero_dim(spec, plan.param_axes)
                if dim is None:
                    return x
                if qw:
                    return quantized_all_gather(x, axes, dim)
                return jax.lax.all_gather(x, axes, axis=dim, tiled=True)

            full = jax.tree_util.tree_map_with_path(gather_leaf, params)
            (_, loss), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(full, scale, inputs)
            loss = jax.lax.pmean(loss, dp_axes)

            def reduce_leaf(kp, g):
                # translated spec lives in manual-mode axis space (dp_axes ∪
                # zp), so searching dp_axes covers plain/hpZ/MiCS alike
                spec = _translate(plan.master_spec(g.shape, path_str(kp)))
                dim, axes = _zero_dim(spec, dp_axes)
                if dim is None:
                    return jax.lax.pmean(g, dp_axes).astype(grad_dtype)
                n = 1
                for a in axes:
                    n *= mesh.shape[a]
                out = all_to_all_quant_reduce(g, axes, dim, n)
                # average over any remaining dp axes not in this dim
                rest = tuple(a for a in dp_axes if a not in axes)
                if rest:
                    out = jax.lax.pmean(out, rest)
                return out.astype(grad_dtype)

            grads = jax.tree_util.tree_map_with_path(reduce_leaf, grads)
            return loss, grads

        kw = dict(mesh=mesh, in_specs=(param_specs, batch_specs),
                  out_specs=(P(), master_specs), check_vma=False)
        if manual_only:
            kw["axis_names"] = manual_axes  # tp stays auto (GSPMD)
        fn = shard_map(body, **kw)
        return fn(params, inputs)

    return micro
