"""Bucketed comm/compute-overlap schedulers for both halves of ZeRO.

Without this module every ZeRO-2/3 gradient reduce runs *after* the
backward compute that produces it: the engine's micro-step takes
``jax.value_and_grad`` over the whole model and only then constrains /
reduces the full gradient tree, so at multi-host scale the DCN hop is pure
exposed time — exactly the ``exposed_comm_fraction`` the telemetry
subsystem measures.  T3 (arXiv 2401.16677) and DeAR (arXiv 2302.12445)
show that fine-grained, bucket-level pipelining of gradient reduction
against the remaining backward compute hides most of that cost.  This
module is the TPU-native translation:

* :func:`partition_buckets` — walk the parameter tree in **reverse-layer
  order** (the order gradients materialize during backward) and group
  leaves into ``overlap_bucket_mb``-bounded buckets, DDP/DeAR bucket
  semantics without the flatten/copy (leaves keep their logical shapes).

* :func:`mark_tree` — the GSPMD hook: each bucket's leaves pass through a
  ``custom_vjp`` identity whose backward applies that bucket's gradient
  sharding constraints.  The constraint (→ XLA reduce-scatter /
  all-reduce) is thereby emitted *inside the backward graph* at the point
  the bucket's cotangents finish, instead of on the final gradient
  outputs — giving XLA's latency-hiding scheduler a per-bucket reduce op
  it can slide under the remaining backward compute.  (TPU HLO expresses
  overlap in-op rather than as async start/done pairs — see
  docs/parallelism.md, ``tools/domino_overlap_tpu.py`` — which is why the
  scheduler targets bucket-level *graph structure*, not async-pair
  scheduling.)

* :func:`pipelined_bucket_reduce` — the qgZ hook: reduce bucket *k* as
  two stages (intra-node hop, inter-node quantized hop) and fence bucket
  *k*'s inter-node stage behind bucket *k−max_inflight*'s completion with
  ``lax.optimization_barrier`` — a software pipeline where the quantized
  DCN all-to-all of bucket *k−1* runs while bucket *k* is still in its
  intra-node psum_scatter.  Both qgZ micros ride it: the flat-manual
  micro calls it inside its ``shard_map`` body, and the GSPMD-first micro
  (``runtime/zero/gspmd.py``, ISSUE 15) passes its per-leaf reduce
  *islands* as stage2 — together with :func:`mark_tree` /
  :func:`mark_gather_tree` these barrier-fenced buckets are the ONLY
  overlap mechanism on the GSPMD path: no manual region is ever opened
  just to schedule communication.

ZeRO-3's *other* half — the parameter all-gather that precedes every
layer's forward (and its re-gather before backward) — gets the mirrored
forward-direction treatment, the TPU analog of the reference's prefetch
coordinator (``partitioned_param_coordinator.py``,
``stage3_prefetch_bucket_size``):

* :func:`partition_prefetch_buckets` — the same size-bounded greedy
  partition in **forward-layer order** (the order params are consumed),
  with persistent (replicated) leaves excluded: they were never sharded,
  so there is nothing to gather or to count against the live-parameter
  budget.

* :func:`mark_gather_tree` — the GSPMD hook: each bucket's leaves pass
  through a ``custom_vjp`` identity whose *forward* ties the bucket with
  one ``optimization_barrier`` and applies the bucket's **gathered**
  sharding constraints, emitting that bucket's all-gathers inside the
  forward graph where the latency-hiding scheduler can issue bucket
  *k+1*'s gather while bucket *k*'s layers compute.  Bucket *k* is fenced
  behind bucket *k−window*'s gathered output, so at most ``window``
  buckets prefetch ahead — :func:`live_window` derives that bound from
  ``stage3_max_live_parameters`` so live gathered params never
  materialize the whole model.  Backward is the identity: the gather's
  transpose (the gradient reduce) stays wherever the engine / the
  backward scheduler above put it.

* :func:`pipelined_gather` — the manual-SPMD hook: pipeline ``zeropp``'s
  (quantized) per-leaf all-gather bucket by bucket with the same bounded
  in-flight window, qwZ wire format and all.

Disabled (the default ``comm_optimizations.overlap.enabled: false``, and
``overlap.prefetch.enabled: false``) the engine never imports this module
on the hot path and the compiled HLO is bit-identical to the unbucketed
step.
"""

import numpy as np

import jax

from .partition import path_str

MB = 1 << 20

#: jaxpr/trace marker name prefix — one distinct ``bucket_reduce_<k>``
#: custom_vjp per bucket; the structural unit tests key off this.
BUCKET_MARKER = "bucket_reduce"

#: forward-direction analog: one ``param_gather_<k>`` marker per prefetch
#: bucket (named scope in the forward graph, ``param_gather/<k>`` spans in
#: telemetry)
GATHER_MARKER = "param_gather"


class GradBucket:
    """One size-bounded group of gradient leaves, dispatched as a unit.

    ``indices`` point into the *forward-order* flattened leaf list (what
    ``jax.tree_util.tree_flatten`` yields); buckets themselves are ordered
    by dispatch time: reverse-layer for the gradient reduce, forward-layer
    for the param-gather prefetch.  ``elems`` is the bucket's element
    count — the unit ``stage3_max_live_parameters`` budgets in.
    """

    __slots__ = ("index", "indices", "paths", "nbytes", "elems")

    def __init__(self, index, indices, paths, nbytes, elems=0):
        self.index = index
        self.indices = tuple(indices)
        self.paths = tuple(paths)
        self.nbytes = int(nbytes)
        self.elems = int(elems)

    def __repr__(self):
        return (f"GradBucket({self.index}, leaves={len(self.indices)}, "
                f"{self.nbytes / MB:.2f}MiB)")


def leaf_nbytes(x):
    shape = getattr(x, "shape", ())
    itemsize = getattr(getattr(x, "dtype", None), "itemsize", 4)
    return int(np.prod(shape, dtype=np.int64)) * int(itemsize)


def leaf_elems(x):
    return int(np.prod(getattr(x, "shape", ()), dtype=np.int64))


def _greedy_partition(indexed_items, bucket_bytes):
    """The one greedy close-on-overflow partitioner both directions share.

    ``indexed_items`` yields ``(index, path, leaf)`` triples in dispatch
    order (reverse-layer for the grad reduce, forward-layer for the
    prefetch).  Invariants (unit-tested from both wrappers):

    * every yielded leaf lands in exactly one bucket (exact cover);
    * a bucket closes before adding a leaf would exceed ``bucket_bytes``
      (so every bucket except possibly single-leaf ones respects the
      bound);
    * a single leaf larger than ``bucket_bytes`` gets its own bucket;
    * concatenating buckets preserves the yielded order.
    """
    bucket_bytes = max(1, int(bucket_bytes))
    buckets = []
    cur_idx, cur_paths, cur_bytes, cur_elems = [], [], 0, 0

    def close():
        nonlocal cur_idx, cur_paths, cur_bytes, cur_elems
        if cur_idx:
            buckets.append(GradBucket(len(buckets), cur_idx, cur_paths,
                                      cur_bytes, cur_elems))
            cur_idx, cur_paths, cur_bytes, cur_elems = [], [], 0, 0

    for i, path, leaf in indexed_items:
        nb = leaf_nbytes(leaf)
        if cur_idx and cur_bytes + nb > bucket_bytes:
            close()
        cur_idx.append(i)
        cur_paths.append(path)
        cur_bytes += nb
        cur_elems += leaf_elems(leaf)
        if cur_bytes >= bucket_bytes:
            close()
    close()
    return buckets


def partition_buckets(items, bucket_bytes):
    """Group ``items`` (forward-order ``(path, leaf)`` pairs) into
    size-bounded buckets in **reverse-layer order** — the order cotangents
    materialize during backward (see :func:`_greedy_partition` for the
    shared invariants)."""
    n = len(items)
    return _greedy_partition(
        ((n - 1 - rev, path, leaf)
         for rev, (path, leaf) in enumerate(reversed(items))),
        bucket_bytes)


def tree_buckets(tree, bucket_bytes):
    """Partition a pytree's leaves into buckets.  Returns
    ``(buckets, paths, treedef)`` with ``paths`` in forward leaf order."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    items = [(path_str(kp), x) for kp, x in flat]
    return partition_buckets(items, bucket_bytes), \
        [p for p, _ in items], treedef


def describe_buckets(buckets):
    """JSON-safe partition summary — trace metadata so a captured trace
    records which bucketing produced it (autotuner provenance)."""
    return [{"index": b.index, "leaves": len(b.indices),
             "mb": round(b.nbytes / MB, 4), "elems": b.elems,
             "paths": list(b.paths)}
            for b in buckets]


def _make_bucket_marker(index, shardings):
    """custom_vjp identity over one bucket's leaves; backward applies the
    bucket's gradient sharding constraints, emitting the reduce ops inside
    the backward graph where this bucket's cotangents finish."""

    def bucket_reduce(xs):
        return xs

    # distinct name per bucket → the jaxpr carries one identifiable
    # custom_vjp call per bucket (structural test surface)
    bucket_reduce.__name__ = f"{BUCKET_MARKER}_{index}"
    mark = jax.custom_vjp(bucket_reduce)

    def _fwd(xs):
        return xs, None

    def _bwd(_, gs):
        with jax.named_scope(f"{BUCKET_MARKER}_{index}"):
            out = [g if s is None else jax.lax.with_sharding_constraint(g, s)
                   for g, s in zip(gs, shardings)]
            # one barrier per bucket: keeps the bucket's reduces grouped as
            # a single schedulable unit (XLA may not CSE/split them across
            # bucket boundaries) and gives the jaxpr one countable
            # optimization_barrier eqn per bucket — the structural surface
            # the unit tests (and a skeptical reader of an HLO dump) check
            out = list(jax.lax.optimization_barrier(tuple(out)))
        return (out, )

    mark.defvjp(_fwd, _bwd)
    return mark


def mark_tree(params, grad_shardings, buckets):
    """Apply per-bucket grad-reduce markers to ``params``.

    ``grad_shardings`` is the matching pytree of ``NamedSharding``s (or
    ``PartitionSpec``-shaped Nones) the cotangents must be constrained to.
    Call *inside* the differentiated function so the markers sit between
    the raw params and the model — their backward then fires per bucket as
    the bucket's gradients materialize.
    """
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shard_leaves = jax.tree_util.tree_leaves(grad_shardings)
    if len(shard_leaves) != len(leaves):
        raise ValueError(
            f"grad_shardings tree ({len(shard_leaves)} leaves) does not "
            f"match params ({len(leaves)} leaves)")
    out = list(leaves)
    for b in buckets:
        mark = _make_bucket_marker(b.index,
                                   [shard_leaves[i] for i in b.indices])
        marked = mark([out[i] for i in b.indices])
        for j, i in enumerate(b.indices):
            out[i] = marked[j]
    return jax.tree_util.tree_unflatten(treedef, out)


def pipelined_bucket_reduce(grads, buckets, stage1, stage2, max_inflight=2):
    """Manual-SPMD bucket pipeline: reduce each bucket in two stages with a
    bounded in-flight window.

    ``stage1(path, g)`` is the intra-node hop (full-precision
    ``psum_scatter`` on ICI, or identity for flat leaves); ``stage2(path,
    h)`` is the inter-node hop (quantized all-to-all across DCN) plus any
    finishing math.  Bucket *k*'s stage2 inputs are fenced behind bucket
    *k−max_inflight*'s outputs via ``lax.optimization_barrier``: at most
    ``max_inflight`` buckets have their inter-node hop outstanding, and
    stage1 compute of bucket *k* is free to overlap stage2 communication
    of buckets *k−1 … k−max_inflight* — DeAR's decoupled pipeline as graph
    structure.  Buckets iterate in reverse-layer (dispatch) order.
    """
    max_inflight = max(1, int(max_inflight))
    flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
    paths = [path_str(kp) for kp, _ in flat]
    leaves = [x for _, x in flat]
    outs = [None] * len(leaves)
    done = []  # per bucket: list of stage2 outputs (the fence operands)
    for k, b in enumerate(buckets):
        h1 = [stage1(paths[i], leaves[i]) for i in b.indices]
        fence_at = k - max_inflight
        if fence_at >= 0 and done[fence_at]:
            # one barrier ties this bucket's stage1 results to the old
            # bucket's finished outputs: stage2(k) cannot be hoisted ahead
            # of bucket fence_at's completion
            tied = jax.lax.optimization_barrier(
                tuple(h1) + tuple(done[fence_at]))
            h1 = list(tied[:len(h1)])
            old = list(tied[len(h1):])
            prev = buckets[fence_at]
            done[fence_at] = old
            for j, i in enumerate(prev.indices):
                outs[i] = old[j]
        o = [stage2(paths[i], h) for i, h in zip(b.indices, h1)]
        done.append(o)
        for j, i in enumerate(b.indices):
            outs[i] = o[j]
    return jax.tree_util.tree_unflatten(treedef, outs)


# --------------------------------------------------------------------------
# forward-direction param-gather prefetch (ZeRO-3)
# --------------------------------------------------------------------------

@jax.custom_vjp
def fence(xs):
    """``lax.optimization_barrier`` with a straight-through gradient.

    The pinned jax has no AD rule for the raw primitive, and the GSPMD
    qwZ gather pipeline runs *inside* the differentiated loss — the fence
    shapes the forward schedule only, so cotangents pass through
    unchanged."""
    return jax.lax.optimization_barrier(tuple(xs))


def _fence_fwd(xs):
    return fence(xs), None


def _fence_bwd(_, gs):
    return (tuple(gs), )


fence.defvjp(_fence_fwd, _fence_bwd)


def partition_prefetch_buckets(items, bucket_bytes, skip=()):
    """Group ``items`` (forward-order ``(path, leaf)`` pairs) into
    size-bounded buckets in **forward-layer order** — the order the
    forward pass consumes params, i.e. the order their all-gathers should
    be issued (see :func:`_greedy_partition` for the shared invariants).

    ``skip`` is the persistent-leaf path set: replicated leaves take part
    in no gather, so they land in no bucket and count against no live
    budget (the regression the per-leaf persistence tests pin down).
    """
    skip = frozenset(skip)
    return _greedy_partition(
        ((i, path, leaf) for i, (path, leaf) in enumerate(items)
         if path not in skip),
        bucket_bytes)


def gather_items(params, plan):
    """Forward-order ``(path, leaf)`` items plus the persistent path set.

    A leaf is *persistent* when its param spec carries no ZeRO axis —
    either it sits under the persistence threshold
    (``stage3_param_persistence_threshold`` → ``min_partition_size``), its
    dims are fully claimed by tensor parallelism, or the stage is < 3.
    Persistent leaves are already replicated: no gather ever touches them,
    and they must not occupy prefetch buckets or live-parameter budget.
    """
    from .partition import zero_dim
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    items, persistent = [], set()
    for kp, x in flat:
        p = path_str(kp)
        items.append((p, x))
        spec = plan.param_spec(getattr(x, "shape", ()), p)
        # per-leaf axes: a rule-claimed axis (the expert "ep" dim, tp) is
        # model parallelism, not a gatherable ZeRO shard
        dim, _axes = zero_dim(spec, plan.leaf_zero_axes(p))
        if dim is None:
            persistent.add(p)
    return items, persistent


def live_window(buckets, max_live_params, max_inflight=2):
    """Prefetch window: how many buckets may have their gather outstanding.

    The largest ``W ≤ max_inflight`` such that every ``W`` consecutive
    buckets hold at most ``max_live_params`` gathered **elements** — the
    reference's ``stage3_max_live_parameters`` contract, expressed as a
    pipeline depth instead of an eviction loop (XLA's liveness frees a
    gathered bucket after its last use; the window bounds how far ahead
    new gathers may be issued).  Always ≥ 1: the bucket being consumed
    must exist regardless of budget.  ``max_live_params`` ≤ 0 means no
    element bound (window = ``max_inflight``).
    """
    w = max(1, int(max_inflight))
    if not buckets or not max_live_params or max_live_params <= 0:
        return w
    elems = [b.elems for b in buckets]
    # a window wider than the bucket list means "everything outstanding at
    # once" — validate it as the full list, or the sliding check below
    # iterates an empty range and the budget is silently ignored
    w = min(w, len(elems))
    while w > 1 and any(sum(elems[k:k + w]) > max_live_params
                        for k in range(len(elems) - w + 1)):
        w -= 1
    return w


def _make_gather_marker(index, shardings, n_fence, fence_sds):
    """custom_vjp over one bucket's leaves (+ the fence operands from
    bucket ``index − window``): the forward ties the bucket's raw shards
    and the fence values with ONE ``optimization_barrier`` — this bucket's
    gather cannot be hoisted before the fenced bucket's gather has
    completed — then applies the bucket's *gathered* sharding constraints,
    emitting the all-gathers inside the forward graph.  The backward is
    the identity on the bucket's cotangents (and exact zeros on the
    fences, which only ordered the schedule): the gather's transpose stays
    wherever the engine / the grad-reduce scheduler put it instead of
    being forced replicated by ``with_sharding_constraint``'s own
    transpose."""

    def param_gather(args):
        n = len(args) - n_fence
        tied = jax.lax.optimization_barrier(tuple(args))
        with jax.named_scope(f"{GATHER_MARKER}_{index}"):
            return tuple(
                x if s is None else jax.lax.with_sharding_constraint(x, s)
                for x, s in zip(tied[:n], shardings))

    param_gather.__name__ = f"{GATHER_MARKER}_{index}"
    mark = jax.custom_vjp(param_gather)

    def _fwd(args):
        return param_gather(args), None

    def _bwd(_, gs):
        import jax.numpy as jnp
        return (tuple(gs) + tuple(jnp.zeros(s.shape, s.dtype)
                                  for s in fence_sds), )

    mark.defvjp(_fwd, _bwd)
    return mark


def mark_gather_tree(params, gather_shardings, buckets, max_inflight=2):
    """Apply per-bucket prefetch markers to ``params`` (GSPMD stage-3).

    ``gather_shardings`` is the matching pytree of post-gather
    ``NamedSharding``s (param sharding minus the ZeRO axes — tp survives).
    Call *inside* the differentiated function: each bucket's all-gather is
    then a separately schedulable unit in the forward graph, fenced behind
    bucket ``k − max_inflight``'s gathered output so at most
    ``max_inflight`` buckets prefetch ahead (pass the
    :func:`live_window`-clamped value to honor
    ``stage3_max_live_parameters``).  Leaves outside every bucket
    (persistent) pass through untouched.
    """
    max_inflight = max(1, int(max_inflight))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shard_leaves = jax.tree_util.tree_leaves(gather_shardings)
    if len(shard_leaves) != len(leaves):
        raise ValueError(
            f"gather_shardings tree ({len(shard_leaves)} leaves) does not "
            f"match params ({len(leaves)} leaves)")
    out = list(leaves)
    done = []  # per bucket: gathered leaves (the fence operands)
    for k, b in enumerate(buckets):
        xs = [out[i] for i in b.indices]
        fence_at = k - max_inflight
        fences = tuple(done[fence_at]) if fence_at >= 0 else ()
        mark = _make_gather_marker(
            b.index, [shard_leaves[i] for i in b.indices], len(fences),
            tuple(jax.ShapeDtypeStruct(f.shape, f.dtype) for f in fences))
        g = mark(tuple(xs) + fences)
        done.append(list(g))
        for j, i in enumerate(b.indices):
            out[i] = g[j]
    return jax.tree_util.tree_unflatten(treedef, out)


def pipelined_gather(params, buckets, gather, max_inflight=2):
    """Manual-SPMD prefetch pipeline: gather each bucket's leaves with a
    bounded in-flight window.

    ``gather(path, x)`` reassembles one leaf — ``zeropp``'s quantized qwZ
    all-gather, a plain ``lax.all_gather``, or the identity for persistent
    leaves.  Bucket *k*'s gather inputs are fenced behind bucket
    *k−max_inflight*'s gathered outputs via ``lax.optimization_barrier``:
    at most ``max_inflight`` buckets have their (DCN-crossing, when
    quantized) gather outstanding while earlier buckets' layers compute —
    the reference prefetch coordinator's in-flight window as graph
    structure.  Leaves outside every bucket pass through ``gather``
    unfenced (the identity for persistent leaves).  Buckets iterate in
    forward-layer (consumption) order.
    """
    max_inflight = max(1, int(max_inflight))
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    paths = [path_str(kp) for kp, _ in flat]
    leaves = [x for _, x in flat]
    bucketed = {i for b in buckets for i in b.indices}
    outs = [None if i in bucketed else gather(paths[i], leaves[i])
            for i in range(len(leaves))]
    done = []  # per bucket: gathered outputs (the fence operands)
    for k, b in enumerate(buckets):
        xs = [leaves[i] for i in b.indices]
        fence_at = k - max_inflight
        if fence_at >= 0 and done[fence_at]:
            tied = fence(tuple(xs) + tuple(done[fence_at]))
            xs = list(tied[:len(xs)])
            old = list(tied[len(xs):])
            prev = buckets[fence_at]
            done[fence_at] = old
            for j, i in enumerate(prev.indices):
                outs[i] = old[j]
        g = [gather(paths[i], x) for i, x in zip(b.indices, xs)]
        done.append(g)
        for j, i in enumerate(b.indices):
            outs[i] = g[j]
    return jax.tree_util.tree_unflatten(treedef, outs)


def prefetch_opts(comm_opts):
    """The ``comm_optimizations.overlap.prefetch`` block, or None when
    absent/disabled.  Its gate is independent of ``overlap.enabled`` —
    the two directions (backward grad reduce, forward param gather)
    compose but arm separately."""
    ov = getattr(comm_opts, "overlap", None) if comm_opts is not None \
        else None
    pf = getattr(ov, "prefetch", None) if ov is not None else None
    if pf is None or not getattr(pf, "enabled", False):
        return None
    return pf


def prefetch_bucket_bytes(pf):
    """prefetch.bucket_mb → bytes; 0 (the default) falls back to the
    grad-overlap default bound.  Configs armed via the reference knob
    ``stage3_prefetch_bucket_size`` arrive with ``bucket_mb`` already
    stamped from that element count (``runtime/config.py`` does it where
    knob explicitness is known — the field's 5e7 default must not
    silently size buckets)."""
    mb = float(getattr(pf, "bucket_mb", 0.0))
    if mb > 0:
        return max(1, int(mb * MB))
    return 32 * MB


def resolve_prefetch(pf, zero_config=None):
    """Normalize a prefetch block + the stage-3 live-parameter knob into
    the plain numbers the gather hooks consume (one dict,
    duck-type-free)."""
    if pf is None:
        return None
    return {
        "bucket_bytes": prefetch_bucket_bytes(pf),
        "max_inflight": max(1, int(getattr(pf, "max_inflight", 2))),
        "max_live_params": int(
            getattr(zero_config, "max_live_parameters", 0) or 0)
        if zero_config is not None else 0,
    }


def prefetch_buckets_for(params, plan, resolved):
    """``(buckets, window, persistent)`` for a resolved prefetch config:
    forward-order buckets over the gatherable leaves, the
    max_live-clamped in-flight window, and the persistent path set."""
    items, persistent = gather_items(params, plan)
    buckets = partition_prefetch_buckets(items, resolved["bucket_bytes"],
                                         skip=persistent)
    window = live_window(buckets, resolved["max_live_params"],
                         resolved["max_inflight"])
    return buckets, window, persistent


def overlap_opts(comm_opts):
    """The duck-typed ``comm_optimizations.overlap`` block, or None when
    absent/disabled — the single gate every integration point checks."""
    ov = getattr(comm_opts, "overlap", None) if comm_opts is not None \
        else None
    if ov is None or not getattr(ov, "enabled", False):
        return None
    return ov


def bucket_bytes_of(ov):
    """overlap.bucket_mb → bytes (fractional MB allowed: tiny test models
    need sub-MB bounds to produce more than one bucket)."""
    return max(1, int(float(getattr(ov, "bucket_mb", 32)) * MB))
