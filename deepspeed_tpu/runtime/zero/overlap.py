"""Bucketed backward-pass gradient-reduction scheduler.

Without this module every ZeRO-2/3 gradient reduce runs *after* the
backward compute that produces it: the engine's micro-step takes
``jax.value_and_grad`` over the whole model and only then constrains /
reduces the full gradient tree, so at multi-host scale the DCN hop is pure
exposed time — exactly the ``exposed_comm_fraction`` the telemetry
subsystem measures.  T3 (arXiv 2401.16677) and DeAR (arXiv 2302.12445)
show that fine-grained, bucket-level pipelining of gradient reduction
against the remaining backward compute hides most of that cost.  This
module is the TPU-native translation:

* :func:`partition_buckets` — walk the parameter tree in **reverse-layer
  order** (the order gradients materialize during backward) and group
  leaves into ``overlap_bucket_mb``-bounded buckets, DDP/DeAR bucket
  semantics without the flatten/copy (leaves keep their logical shapes).

* :func:`mark_tree` — the GSPMD hook: each bucket's leaves pass through a
  ``custom_vjp`` identity whose backward applies that bucket's gradient
  sharding constraints.  The constraint (→ XLA reduce-scatter /
  all-reduce) is thereby emitted *inside the backward graph* at the point
  the bucket's cotangents finish, instead of on the final gradient
  outputs — giving XLA's latency-hiding scheduler a per-bucket reduce op
  it can slide under the remaining backward compute.  (TPU HLO expresses
  overlap in-op rather than as async start/done pairs — see
  docs/parallelism.md, ``tools/domino_overlap_tpu.py`` — which is why the
  scheduler targets bucket-level *graph structure*, not async-pair
  scheduling.)

* :func:`pipelined_bucket_reduce` — the manual-SPMD (qgZ) hook: reduce
  bucket *k* as two stages (intra-node hop, inter-node quantized hop) and
  fence bucket *k*'s inter-node stage behind bucket *k−max_inflight*'s
  completion with ``lax.optimization_barrier`` — a software pipeline where
  the quantized DCN all-to-all of bucket *k−1* runs while bucket *k* is
  still in its intra-node psum_scatter.

Disabled (the default ``comm_optimizations.overlap.enabled: false``) the
engine never imports this module on the hot path and the compiled HLO is
bit-identical to the unbucketed step.
"""

import numpy as np

import jax

from .partition import path_str

MB = 1 << 20

#: jaxpr/trace marker name prefix — one distinct ``bucket_reduce_<k>``
#: custom_vjp per bucket; the structural unit tests key off this.
BUCKET_MARKER = "bucket_reduce"


class GradBucket:
    """One size-bounded group of gradient leaves, dispatched as a unit.

    ``indices`` point into the *forward-order* flattened leaf list (what
    ``jax.tree_util.tree_flatten`` yields); buckets themselves are ordered
    by dispatch time, i.e. reverse-layer.
    """

    __slots__ = ("index", "indices", "paths", "nbytes")

    def __init__(self, index, indices, paths, nbytes):
        self.index = index
        self.indices = tuple(indices)
        self.paths = tuple(paths)
        self.nbytes = int(nbytes)

    def __repr__(self):
        return (f"GradBucket({self.index}, leaves={len(self.indices)}, "
                f"{self.nbytes / MB:.2f}MiB)")


def leaf_nbytes(x):
    shape = getattr(x, "shape", ())
    itemsize = getattr(getattr(x, "dtype", None), "itemsize", 4)
    return int(np.prod(shape, dtype=np.int64)) * int(itemsize)


def partition_buckets(items, bucket_bytes):
    """Group ``items`` (forward-order ``(path, leaf)`` pairs) into
    size-bounded buckets in reverse-layer order.

    Invariants (unit-tested):

    * every leaf lands in exactly one bucket (exact cover);
    * a bucket closes before adding a leaf would exceed ``bucket_bytes``
      (so every bucket except possibly single-leaf ones respects the
      bound);
    * a single leaf larger than ``bucket_bytes`` gets its own bucket;
    * concatenating buckets yields the exact reverse of the forward leaf
      order — the order cotangents materialize during backward.
    """
    bucket_bytes = max(1, int(bucket_bytes))
    buckets = []
    cur_idx, cur_paths, cur_bytes = [], [], 0

    def close():
        nonlocal cur_idx, cur_paths, cur_bytes
        if cur_idx:
            buckets.append(GradBucket(len(buckets), cur_idx, cur_paths,
                                      cur_bytes))
            cur_idx, cur_paths, cur_bytes = [], [], 0

    n = len(items)
    for rev, (path, leaf) in enumerate(reversed(items)):
        nb = leaf_nbytes(leaf)
        if cur_idx and cur_bytes + nb > bucket_bytes:
            close()
        cur_idx.append(n - 1 - rev)
        cur_paths.append(path)
        cur_bytes += nb
        if cur_bytes >= bucket_bytes:
            close()
    close()
    return buckets


def tree_buckets(tree, bucket_bytes):
    """Partition a pytree's leaves into buckets.  Returns
    ``(buckets, paths, treedef)`` with ``paths`` in forward leaf order."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    items = [(path_str(kp), x) for kp, x in flat]
    return partition_buckets(items, bucket_bytes), \
        [p for p, _ in items], treedef


def describe_buckets(buckets):
    """JSON-safe partition summary — trace metadata so a captured trace
    records which bucketing produced it (autotuner provenance)."""
    return [{"index": b.index, "leaves": len(b.indices),
             "mb": round(b.nbytes / MB, 4), "paths": list(b.paths)}
            for b in buckets]


def _make_bucket_marker(index, shardings):
    """custom_vjp identity over one bucket's leaves; backward applies the
    bucket's gradient sharding constraints, emitting the reduce ops inside
    the backward graph where this bucket's cotangents finish."""

    def bucket_reduce(xs):
        return xs

    # distinct name per bucket → the jaxpr carries one identifiable
    # custom_vjp call per bucket (structural test surface)
    bucket_reduce.__name__ = f"{BUCKET_MARKER}_{index}"
    mark = jax.custom_vjp(bucket_reduce)

    def _fwd(xs):
        return xs, None

    def _bwd(_, gs):
        with jax.named_scope(f"{BUCKET_MARKER}_{index}"):
            out = [g if s is None else jax.lax.with_sharding_constraint(g, s)
                   for g, s in zip(gs, shardings)]
            # one barrier per bucket: keeps the bucket's reduces grouped as
            # a single schedulable unit (XLA may not CSE/split them across
            # bucket boundaries) and gives the jaxpr one countable
            # optimization_barrier eqn per bucket — the structural surface
            # the unit tests (and a skeptical reader of an HLO dump) check
            out = list(jax.lax.optimization_barrier(tuple(out)))
        return (out, )

    mark.defvjp(_fwd, _bwd)
    return mark


def mark_tree(params, grad_shardings, buckets):
    """Apply per-bucket grad-reduce markers to ``params``.

    ``grad_shardings`` is the matching pytree of ``NamedSharding``s (or
    ``PartitionSpec``-shaped Nones) the cotangents must be constrained to.
    Call *inside* the differentiated function so the markers sit between
    the raw params and the model — their backward then fires per bucket as
    the bucket's gradients materialize.
    """
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shard_leaves = jax.tree_util.tree_leaves(grad_shardings)
    if len(shard_leaves) != len(leaves):
        raise ValueError(
            f"grad_shardings tree ({len(shard_leaves)} leaves) does not "
            f"match params ({len(leaves)} leaves)")
    out = list(leaves)
    for b in buckets:
        mark = _make_bucket_marker(b.index,
                                   [shard_leaves[i] for i in b.indices])
        marked = mark([out[i] for i in b.indices])
        for j, i in enumerate(b.indices):
            out[i] = marked[j]
    return jax.tree_util.tree_unflatten(treedef, out)


def pipelined_bucket_reduce(grads, buckets, stage1, stage2, max_inflight=2):
    """Manual-SPMD bucket pipeline: reduce each bucket in two stages with a
    bounded in-flight window.

    ``stage1(path, g)`` is the intra-node hop (full-precision
    ``psum_scatter`` on ICI, or identity for flat leaves); ``stage2(path,
    h)`` is the inter-node hop (quantized all-to-all across DCN) plus any
    finishing math.  Bucket *k*'s stage2 inputs are fenced behind bucket
    *k−max_inflight*'s outputs via ``lax.optimization_barrier``: at most
    ``max_inflight`` buckets have their inter-node hop outstanding, and
    stage1 compute of bucket *k* is free to overlap stage2 communication
    of buckets *k−1 … k−max_inflight* — DeAR's decoupled pipeline as graph
    structure.  Buckets iterate in reverse-layer (dispatch) order.
    """
    max_inflight = max(1, int(max_inflight))
    flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
    paths = [path_str(kp) for kp, _ in flat]
    leaves = [x for _, x in flat]
    outs = [None] * len(leaves)
    done = []  # per bucket: list of stage2 outputs (the fence operands)
    for k, b in enumerate(buckets):
        h1 = [stage1(paths[i], leaves[i]) for i in b.indices]
        fence_at = k - max_inflight
        if fence_at >= 0 and done[fence_at]:
            # one barrier ties this bucket's stage1 results to the old
            # bucket's finished outputs: stage2(k) cannot be hoisted ahead
            # of bucket fence_at's completion
            tied = jax.lax.optimization_barrier(
                tuple(h1) + tuple(done[fence_at]))
            h1 = list(tied[:len(h1)])
            old = list(tied[len(h1):])
            prev = buckets[fence_at]
            done[fence_at] = old
            for j, i in enumerate(prev.indices):
                outs[i] = old[j]
        o = [stage2(paths[i], h) for i, h in zip(b.indices, h1)]
        done.append(o)
        for j, i in enumerate(b.indices):
            outs[i] = o[j]
    return jax.tree_util.tree_unflatten(treedef, outs)


def overlap_opts(comm_opts):
    """The duck-typed ``comm_optimizations.overlap`` block, or None when
    absent/disabled — the single gate every integration point checks."""
    ov = getattr(comm_opts, "overlap", None) if comm_opts is not None \
        else None
    if ov is None or not getattr(ov, "enabled", False):
        return None
    return ov


def bucket_bytes_of(ov):
    """overlap.bucket_mb → bytes (fractional MB allowed: tiny test models
    need sub-MB bounds to produce more than one bucket)."""
    return max(1, int(float(getattr(ov, "bucket_mb", 32)) * MB))
