"""ZeRO config — same JSON schema as reference ``runtime/zero/config.py:344``
(``DeepSpeedZeroConfig``) + ``runtime/zero/offload_config.py:109``.

On TPU many of the knobs steer the *sharding policy* handed to XLA GSPMD
rather than hand-rolled bucketing (SURVEY.md §7 design stance); knobs that have
no XLA analog (e.g. ``allgather_bucket_size``) are accepted for config
compatibility and recorded, but only a documented subset changes compiled code.
"""

from enum import Enum
from typing import Optional

from pydantic import Field

from ..config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """Reference ``offload_config.py`` param offload section."""
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(int(1e8), ge=0)
    max_in_cpu: int = Field(int(1e9), ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = Field(1.0, ge=0.0, le=1.0)


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(int(5e8), ge=0)
    use_multi_rank_bucket_allreduce: bool = True
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(int(5e8), ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    sub_group_size: int = Field(int(1e9), ge=0)
    cpu_offload_param: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_param"})
    cpu_offload_use_pin_memory: Optional[bool] = None
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer"})

    prefetch_bucket_size: int = Field(int(5e7), ge=0,
                                      alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(int(1e5), ge=0,
                                             alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(int(1e14), ge=0,
                                             alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(int(1e9), ge=0,
                                     alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(int(1e9), ge=0,
                                    alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(
        False, alias="stage3_gather_16bit_weights_on_model_save")

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False

    # ZeRO++ (reference stage3.py:123 kwargs + engine.py:906-913)
    zero_hpz_partition_size: int = Field(1, ge=0)
    zero_quantized_weights: bool = False
    # qwZ wire format: int8 (reference default) | int4 | fp8 | fp6 | fp12
    # (fp formats via ops/fp_quantizer — csrc/fp_quantizer analog)
    zero_quantized_weights_format: str = "int8"
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False

    # MiCS (reference runtime/zero/mics.py)
    mics_shard_size: int = Field(-1, alias="mics_shard_size")
    mics_hierarchical_params_gather: bool = False

    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True
    log_trace_cache_warnings: bool = False

    def __post_init__(self):
        pass
