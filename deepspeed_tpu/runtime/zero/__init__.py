from .config import DeepSpeedZeroConfig
from .partition import ZeroPartitionPlan, shard_spec, tree_shardings
