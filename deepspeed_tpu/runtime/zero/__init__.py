from .config import DeepSpeedZeroConfig
from .overlap import GradBucket, partition_buckets, tree_buckets
from .partition import ZeroPartitionPlan, shard_spec, tree_shardings
