"""ZeRO partitioning as sharding policy.

The TPU-native heart of ZeRO (SURVEY.md §7 design stance): the reference's
flatten/bucket/hook machinery (``runtime/zero/stage_1_and_2.py:97``,
``stage3.py:111``, ``partition_parameters.py``) collapses into *sharding
functions* — given the ZeRO stage, produce ``NamedSharding``s for params /
gradients / optimizer state over the ZeRO mesh axes, and let GSPMD emit the
reduce-scatter / all-gather pipeline those files hand-roll:

  stage 0: params, grads, optimizer state replicated; grads all-reduced.
  stage 1: optimizer state (incl. fp32 master) sharded over dp.
  stage 2: + gradient accumulator sharded over dp → XLA emits reduce-scatter
           for the grad psum (reference ``average_tensor`` stage_1_and_2.py:1045).
  stage 3: + parameters sharded over dp → XLA all-gathers on use, exactly the
           fetch/release coordinator's job (partitioned_param_coordinator.py:276),
           scheduled statically by the latency-hiding scheduler.

Each tensor is sharded along its **largest divisible axis** (no flattening —
keeping the logical shape lets XLA pick layouts, and sidesteps the reference's
alignment/padding bookkeeping).  Tensors too small to split stay replicated —
the analog of the reference's persistent-small-param threshold
(``parameter_offload.py:249 mark_persistent_parameters``).
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...utils.logging import logger

_PINNED_HOST_OK = {}


def _pinned_host_supported(mesh):
    """Functional probe: memory_kind='pinned_host' may *construct* on any
    backend but fail at SPMD compile (CPU does exactly this) — so compile a
    one-op program once per backend and cache the verdict."""
    import jax.numpy as jnp
    backend = jax.default_backend()
    if backend not in _PINNED_HOST_OK:
        try:
            s = NamedSharding(mesh, P(), memory_kind="pinned_host")
            jax.jit(lambda: jnp.zeros((8, ), jnp.float32),
                    out_shardings=s)()
            _PINNED_HOST_OK[backend] = True
        except Exception:
            _PINNED_HOST_OK[backend] = False
    return _PINNED_HOST_OK[backend]


def shard_spec(shape, mesh: Mesh, axes, min_size=1, base_spec=None):
    """PartitionSpec sharding ``shape``'s largest divisible dim over ``axes``.

    ``axes`` is a tuple of mesh axis names treated as one factored axis
    (e.g. ("dp", "sp") for seq-data-parallel ZeRO sharding, reference
    engine.py:1651).  ``base_spec`` (e.g. a tensor-parallel spec) is preserved:
    the ZeRO axes go to the largest *unclaimed* dim; a dim already sharded by
    base_spec divides its residual size.
    """
    if not shape:
        return base_spec if base_spec is not None else P()
    base = list(base_spec) if base_spec is not None else []
    base = base + [None] * (len(shape) - len(base))
    # Axes already claimed by the base spec are excluded: e.g. expert params
    # sharded over "ep" take ZeRO sharding over "dp" only — which is exactly
    # the reference's expert-DP reduction group (engine.py:2510
    # _reduce_expert_gradients).
    used = set()
    for ax in base:
        if ax is None:
            continue
        used.update(ax if isinstance(ax, tuple) else (ax, ))
    axes = tuple(a for a in axes if a not in used)
    if not axes:
        return P(*base)
    n = int(np.prod([mesh.shape[a] for a in axes], dtype=np.int64))
    if n <= 1 or int(np.prod(shape, dtype=np.int64)) < min_size:
        return P(*base)
    # largest unclaimed dim divisible by n; ties → first
    best = None
    for i, d in sorted(enumerate(shape), key=lambda t: -t[1]):
        if base[i] is not None:
            continue
        if d % n == 0:
            best = i
            break
    if best is not None:
        base[best] = axes if len(axes) > 1 else axes[0]
        return P(*base)
    # No unclaimed dim fits: compose onto a claimed dim whose residual size
    # (after its existing axes) still divides n — keeps ZeRO sharding alive
    # when TP claimed the only divisible dim.
    for i, d in sorted(enumerate(shape), key=lambda t: -t[1]):
        if base[i] is None:
            continue
        existing = base[i] if isinstance(base[i], tuple) else (base[i], )
        claimed = int(np.prod([mesh.shape[a] for a in existing], dtype=np.int64))
        if d % (claimed * n) == 0:
            base[i] = existing + tuple(axes)
            return P(*base)
    return P(*base)


def zero_dim(spec, zero_axes):
    """Locate the dim of a PartitionSpec carrying ZeRO axes.  Returns
    ``(dim, axes_present)`` or ``(None, ())`` — the shared primitive behind
    the qwZ/qgZ leaf walkers (``zeropp.py``) and the collectives engine's
    per-leaf variant selection."""
    for i, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry, )
        present = tuple(a for a in names if a in zero_axes)
        if present:
            return i, present
    return None, ()


def gathered_spec(spec, zero_axes):
    """``spec`` with its ZeRO axes stripped from the zero dim — the leaf's
    sharding AFTER the stage-3 all-gather (tp and other non-ZeRO axes
    survive).  Persistent / unsharded leaves come back unchanged.  Shared
    by the qwZ gather wrappers (``zeropp``) and the forward prefetch
    markers (``overlap.mark_gather_tree``)."""
    dim, axes = zero_dim(spec, zero_axes)
    if dim is None:
        return spec
    entry = spec[dim]
    names = entry if isinstance(entry, tuple) else (entry, )
    kept = tuple(a for a in names if a not in axes)
    new = list(spec)
    new[dim] = kept if len(kept) > 1 else (kept[0] if kept else None)
    return P(*new)


def path_str(kp):
    """jax key-path → 'a/b/c' string for rule matching."""
    parts = []
    for k in kp:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def match_tp_rule(rules, path):
    """Match ``path`` against rule keys.

    Two rule kinds, which COMPOSE rather than compete:

    * exact suffix keys (``'q_proj/kernel'``) — longest suffix wins; the
      suffix must start at a '/' component boundary (so ``'wo/kernel'`` does
      not match ``'moe_two/kernel'``);
    * scope wildcards (``'scope/*'`` or ``'a/b/*'``) — match any path that
      contains that component sequence before the leaf; their spec claims the
      *leading* dims (e.g. the stacked-layer dim of pipeline blocks or the
      expert dim), and a simultaneously-matching exact rule's spec is appended after
      it (so ``'blocks/*': P('pp')`` + ``'q_proj/kernel': P(None,'tp',None)``
      → ``P('pp', None, 'tp', None)`` on a stacked param).
    """
    if not rules:
        return None
    best, best_len = None, -1
    scope_spec, scope_len = None, -1
    bounded = "/" + path
    for key, spec in rules.items():
        if key.endswith("/*"):
            scope = key[:-2]
            # component-boundary containment (multi-component scopes allowed)
            if ("/" + scope + "/") in bounded and len(key) > scope_len:
                scope_spec, scope_len = spec, len(key)
            continue
        if (path == key or path.endswith("/" + key)) and len(key) > best_len:
            best, best_len = spec, len(key)
    if scope_spec is not None and best is not None:
        return P(*tuple(scope_spec) + tuple(best))
    if scope_spec is not None:
        return scope_spec
    return best


def tree_shard_specs(tree, mesh, axes, min_size=1):
    return jax.tree_util.tree_map(
        lambda x: shard_spec(getattr(x, "shape", ()), mesh, axes, min_size), tree)


def tree_shardings(tree, mesh, axes, min_size=1):
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, shard_spec(getattr(x, "shape", ()), mesh,
                                                 axes, min_size)), tree)


def replicated(mesh):
    return NamedSharding(mesh, P())


def tree_replicated(tree, mesh):
    return jax.tree_util.tree_map(lambda x: NamedSharding(mesh, P()), tree)


class ZeroPartitionPlan:
    """Sharding policy for one ZeRO stage over given mesh axes.

    ``tp_rules``: optional dict {path-suffix: PartitionSpec} adding
    tensor-parallel sharding (composed with ZeRO axes; the TP analog of
    module_inject).  ``min_partition_size``: params with fewer elements stay
    replicated (persistence threshold analog).
    """

    def __init__(self, stage, mesh, zero_axes=("dp", ), min_partition_size=1,
                 offload_optimizer=False, offload_param=False, tp_rules=None,
                 hpz_mesh=None, mics=False, comm_opts=None):
        self.stage = stage
        self.mesh = mesh
        self.zero_axes = tuple(a for a in zero_axes if mesh.shape.get(a, 1) >= 1)
        self.min_partition_size = min_partition_size
        self.offload_optimizer = offload_optimizer
        self.offload_param = offload_param
        # comm_optimizations config (duck-typed; see comm/collectives/) —
        # steers the wire format of the quantized ZeRO hot paths
        self.comm_opts = comm_opts
        # TP rules: path-suffix → PartitionSpec over the "tp" axis (AutoTP
        # analog, reference module_inject/auto_tp.py:273) — composed with the
        # ZeRO axes on every state tensor.
        self.tp_rules = tp_rules or {}
        # hpZ (ZeRO++ secondary partition, reference engine.py:906 + utils/
        # groups.py:531): *params* shard over only the intra-host "zp" factor
        # of dp — forward all-gathers ride short ICI hops — while master/grads
        # stay sharded over full dp.  MiCS (reference runtime/zero/mics.py):
        # ALL state shards over the "zp" shard group and replicates across
        # groups; gradients still average over full dp (GSPMD emits the
        # hierarchical allreduce automatically from the specs).
        self.param_mesh, self.param_axes = mesh, self.zero_axes
        self.state_mesh, self.state_axes = mesh, self.zero_axes
        if hpz_mesh is not None:
            from ...utils.groups import ZP_AXIS
            # zp replaces only the dp/ep factor; other ZeRO axes (e.g. "sp"
            # under Ulysses seq-dp sharding) survive — hpz_mesh carries them.
            extra = tuple(a for a in self.zero_axes if a not in ("dp", "ep"))
            zp_axes = (ZP_AXIS, ) + extra
            if mics:
                self.param_mesh = self.state_mesh = hpz_mesh
                self.param_axes = self.state_axes = zp_axes
            elif stage >= 3:
                self.param_mesh, self.param_axes = hpz_mesh, zp_axes
        from ... import telemetry as _telemetry
        if _telemetry.enabled:
            # re-plans (elastic rescale, hpZ factoring changes) land in the
            # trace as metadata; the engine also emits this at bring-up
            _telemetry.metadata("zero_partition_plan", self.describe())

    def describe(self):
        """JSON-safe summary of the sharding policy — trace metadata and
        the autotuner's record of what configuration produced a trace."""
        from .gspmd import resolve_zero_mode
        from .overlap import overlap_opts, prefetch_opts
        co = self.comm_opts
        ov = overlap_opts(co)
        pf = prefetch_opts(co)
        return {
            "stage": self.stage,
            "zero_mode": resolve_zero_mode(co),
            "zero_axes": list(self.zero_axes),
            "param_axes": list(self.param_axes),
            "state_axes": list(self.state_axes),
            "min_partition_size": int(self.min_partition_size),
            "offload_optimizer": bool(self.offload_optimizer),
            "offload_param": bool(self.offload_param),
            "tp_rules": len(self.tp_rules),
            "hierarchical_reduce": self.hierarchical_reduce(),
            "grad_wire": list(self.grad_wire()),
            "param_wire": list(self.param_wire()),
            "comm_optimizations_enabled": bool(
                co is not None and getattr(co, "enabled", False)),
            "overlap_enabled": bool(ov is not None),
            "overlap_bucket_mb": (float(getattr(ov, "bucket_mb", 0.0))
                                  if ov is not None else 0.0),
            "overlap_max_inflight": (int(getattr(ov, "max_inflight", 0))
                                     if ov is not None else 0),
            "prefetch_enabled": bool(pf is not None),
            "prefetch_bucket_mb": (float(getattr(pf, "bucket_mb", 0.0))
                                   if pf is not None else 0.0),
            "prefetch_max_inflight": (int(getattr(pf, "max_inflight", 0))
                                      if pf is not None else 0),
        }

    # wire formats ----------------------------------------------------------
    # The quantized ZeRO hot paths (zeropp.py qwZ/qgZ) ask the plan what to
    # put on the wire; ``comm_optimizations`` wins when it enabled the
    # corresponding traffic class, else the ZeRO++ legacy knobs/defaults.
    def _co_wire(self, flag):
        co = self.comm_opts
        if co is not None and getattr(co, "enabled", False) and \
                getattr(co, flag, False):
            return co.wire_dtype, co.quantization_group_size
        return None

    def grad_wire(self):
        """(wire_format, scale_group_size) for quantized gradient reduce."""
        from ...comm.collectives.quantized import DEFAULT_GROUP_SIZE
        return self._co_wire("quantized_gradients") or \
            ("int8", DEFAULT_GROUP_SIZE)

    def param_wire(self, fallback_format="int8"):
        """(wire_format, scale_group_size) for quantized param all-gather."""
        from ...comm.collectives.quantized import DEFAULT_GROUP_SIZE
        return self._co_wire("quantized_weights") or \
            (fallback_format, DEFAULT_GROUP_SIZE)

    def wire_for_size(self, default_fmt, nbytes):
        """Per-leaf wire format through the ``wire_dtype_by_size`` ladder
        (docs/autotuning.md): the first rung admitting ``nbytes`` logical
        bytes wins — ``"fp32"`` means this leaf rides the unquantized
        schedule — and ``default_fmt`` covers no-ladder configs and sizes
        above every rung.  This is the ZeRO-hot-path twin of
        ``CollectivesEngine.resolve_wire_dtype``: the same ladder the
        eager dispatch honors steers the qgZ/qwZ micro-step leaves, so an
        autotuned per-size choice is applied where the training traffic
        actually flows."""
        co = self.comm_opts
        if co is None or not getattr(co, "enabled", False):
            return default_fmt
        from ...comm.collectives.engine import (build_wire_ladder,
                                                resolve_in_ladder)
        if not hasattr(self, "_wire_ladder"):
            self._wire_ladder = build_wire_ladder(
                getattr(co, "wire_dtype_by_size", None))
        return resolve_in_ladder(self._wire_ladder, nbytes, default_fmt)

    def hierarchical_reduce(self):
        """True when comm_optimizations asks gradient reduction to run the
        2-hop (intra fp → inter quantized) scheme where the ZeRO group spans
        a multi-axis hierarchy (dp×ep, hpZ's zp_outer×zp)."""
        co = self.comm_opts
        return bool(co is not None and getattr(co, "enabled", False)
                    and getattr(co, "hierarchical_allreduce", False))

    # per-leaf axis bookkeeping ---------------------------------------------
    def rule_claimed_axes(self, path):
        """Mesh axes the matched tp rule pins for ``path`` — the expert
        stack's "ep" dim (``expert_sharding_rules``), tensor-parallel "tp"
        dims, ….  Those axes are MODEL parallelism for that leaf, not ZeRO
        data sharding: the stage-3 gather must not reassemble experts
        across ranks, and grad reduction must not average distinct experts
        (the reference's expert-DP split, ``moe/utils.py is_moe_param``)."""
        if not self.tp_rules or path is None:
            return ()
        rule = match_tp_rule(self.tp_rules, path)
        if rule is None:
            return ()
        names = []
        for entry in rule:
            if entry is None:
                continue
            for a in (entry if isinstance(entry, tuple) else (entry, )):
                if a is not None and a != "zero" and a not in names:
                    names.append(a)
        return tuple(names)

    def leaf_zero_axes(self, path, axes=None):
        """The ZeRO axes that actually apply to ``path``: the plan's axes
        minus the ones its rule claims (for non-rule leaves this is exactly
        ``param_axes`` — zero behavior change).  THE per-leaf notion every
        gather/reduce walker must key on (``zeropp``, the prefetch
        partitioner, ``gather_shardings``)."""
        axes = tuple(self.param_axes if axes is None else axes)
        claimed = self.rule_claimed_axes(path)
        if not claimed:
            return axes
        return tuple(a for a in axes if a not in claimed)

    # specs -----------------------------------------------------------------
    def _expand_rule(self, spec, shape, zero_axes, mesh):
        """Expand ``"zero"`` placeholders in a rule spec and sanitize.

        Rules may pin where the ZeRO shard lands with the pseudo-axis
        ``"zero"`` (e.g. ``P(None, 'tp', 'zero')`` puts it on the head dim).
        Placement matters beyond memory balance: ZeRO-sharding a matmul's
        *contracting* dim (or an embedding's hidden dim) makes GSPMD
        propagate hidden-dim sharding into the activations and then
        involuntarily full-rematerialize them back to batch/seq sharding at
        every norm boundary.  ``zero_axes`` is the stage-dependent expansion
        of the placeholder (empty → dropped): params expand it only at
        stage ≥3, master at ≥1, grads at ≥2.

        Sanitization is per-axis greedy (kv-head analog of reference
        ``module_inject/tp_shard.py``): an explicit axis the dim can't divide
        is dropped; zero axes are placed one by one while divisibility holds,
        drawing from a pool that excludes axes the rule claims elsewhere
        (e.g. 'ep' on expert params) and consuming placed axes so a
        placeholder appearing on two dims can't double-place.

        Returns ``(PartitionSpec, pinned)`` — ``pinned`` is True when the
        rule contains a placeholder and its placement is settled (zero axes
        landed, or there were none to place), i.e. the caller must not add
        heuristic ZeRO sharding on top.
        """
        used = set()
        for ax in spec:
            for a in (ax if isinstance(ax, tuple) else (ax, )):
                if a is not None and a != "zero":
                    used.add(a)
        pool = [a for a in zero_axes if a not in used]
        wanted = any("zero" in (ax if isinstance(ax, tuple) else (ax, ))
                     for ax in spec if ax is not None)
        placed = False
        out = []
        for i, ax in enumerate(spec):
            if ax is None or (shape is not None and i >= len(shape)):
                out.append(None)
                continue
            names = ax if isinstance(ax, tuple) else (ax, )
            dim = None if shape is None else shape[i]
            final, prod = [], 1
            for a in names:
                if a == "zero":
                    for z in list(pool):
                        n = mesh.shape.get(z, 1)
                        if n > 1 and (dim is None or dim % (prod * n) == 0):
                            final.append(z)
                            prod *= n
                            pool.remove(z)
                            placed = True
                    continue
                if a not in mesh.shape:
                    raise ValueError(
                        f"tp_rules references axis {a!r} not in mesh axes "
                        f"{tuple(mesh.shape)}")
                n = mesh.shape[a]
                if dim is None or dim % (prod * n) == 0:
                    final.append(a)
                    prod *= n
            out.append(tuple(final) if len(final) > 1
                       else (final[0] if final else None))
        return P(*out), (wanted and (placed or not zero_axes))

    def _spec_for(self, shape, path, mesh, axes, enabled):
        rule = (match_tp_rule(self.tp_rules, path)
                if path is not None else None)
        zero_axes = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
        if rule is None:
            base, pinned = None, False
        else:
            base, pinned = self._expand_rule(
                rule, shape, zero_axes if enabled else (), mesh)
        if not enabled:
            return base if base is not None else P()
        if pinned:
            return base
        # plain TP rule, no rule at all, or the pinned dim couldn't take any
        # zero axis → heuristic (shard_spec re-excludes base-claimed axes)
        return shard_spec(shape, mesh, axes, self.min_partition_size,
                          base_spec=base)

    def param_spec(self, shape, path=None):
        return self._spec_for(shape, path, self.param_mesh, self.param_axes,
                              self.stage >= 3)

    def master_spec(self, shape, path=None):
        """fp32 master weights + optimizer moments."""
        return self._spec_for(shape, path, self.state_mesh, self.state_axes,
                              self.stage >= 1)

    def grad_spec(self, shape, path=None):
        """Gradient accumulator sharding. Stage ≥2 shards grads (the engine's
        micro-step constrains grad outputs to this, making XLA lower the DP
        psum to reduce-scatter)."""
        return self._spec_for(shape, path, self.state_mesh, self.state_axes,
                              self.stage >= 2)

    # tree versions ---------------------------------------------------------
    def _memory_kind(self, offload):
        # Host offload: params/optimizer state resident in pinned host memory,
        # streamed to device per use (reference ZeRO-Offload; SURVEY.md §7
        # "pinned-host offload → memory kinds").
        if not offload:
            return None
        if not _pinned_host_supported(self.mesh):
            # LOUD fallback (round-1 review): an "offload enabled" config
            # silently running fully in HBM is an OOM trap at real scale
            if not getattr(self, "_offload_fallback_warned", False):
                self._offload_fallback_warned = True
                logger.warning(
                    "offload requested but memory_kind='pinned_host' does "
                    "not compile on this platform — STATE STAYS IN DEVICE "
                    "MEMORY; expect the HBM footprint of a non-offload run "
                    "(use offload device 'nvme' for managed disk residency)")
            return None
        return "pinned_host"

    def _sharding(self, spec, offload=False, mesh=None):
        mesh = mesh if mesh is not None else self.mesh
        kind = self._memory_kind(offload)
        if kind is not None:
            return NamedSharding(mesh, spec, memory_kind=kind)
        return NamedSharding(mesh, spec)

    def param_shardings(self, params):
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: self._sharding(
                self.param_spec(x.shape, path_str(kp)),
                offload=self.offload_param and self.stage >= 3,
                mesh=self.param_mesh), params)

    def master_shardings(self, params):
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: self._sharding(self.master_spec(x.shape, path_str(kp)),
                                         offload=self.offload_optimizer,
                                         mesh=self.state_mesh), params)

    def grad_shardings(self, params):
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: self._sharding(self.grad_spec(x.shape, path_str(kp)),
                                         mesh=self.state_mesh),
            params)

    def gather_shardings(self, params):
        """``NamedSharding``s of the POST-gather layout — each leaf's param
        sharding minus the ZeRO axes (tp survives; persistent leaves keep
        their spec).  The forward-prefetch markers constrain to these, so
        XLA emits the stage-3 all-gather at the marker instead of at first
        use."""
        def one(kp, x):
            p = path_str(kp)
            # per-leaf axes: rule-claimed axes (expert "ep", tp) survive the
            # gather — only the leaf's own ZeRO axes are stripped
            return NamedSharding(
                self.param_mesh,
                gathered_spec(self.param_spec(x.shape, p),
                              self.leaf_zero_axes(p)))

        return jax.tree_util.tree_map_with_path(one, params)

    def micro_shardings(self, params, inputs=(), n_replicated_tail=0,
                        grads="grad"):
        """The FULL in/out ``NamedSharding`` set of ONE jitted micro-step
        — the GSPMD-first contract (ISSUE 15, docs/zero.md "GSPMD-first
        ZeRO"): params in their stage layout, the loss scale and
        engine-appended input tails replicated, batch inputs sharded over
        the ZeRO axes on their leading dim; out, the loss replicated and
        the gradients in the accumulator layout (``grads="grad"``, the
        GSPMD micro's constraint target) or the master partition
        (``grads="master"``, what the qgZ reduce islands and the manual
        micro emit).  Returned as ``((params, scale, inputs), (loss,
        grads))`` — exactly the ``jit(in_shardings=…, out_shardings=…)``
        pytrees for ``micro(params, scale, inputs) -> (loss, grads)``.

        Only meaningful on the plan's own mesh (hpZ/MiCS micros translate
        their own specs); the engine cross-checks the emitted set against
        the live arrays before arming it."""
        if grads not in ("grad", "master"):
            raise ValueError(f"micro_shardings grads={grads!r} must be "
                             "'grad' or 'master'")
        from ..utils import batch_input_specs
        mesh = self.mesh
        axes = tuple(a for a in self.zero_axes
                     if mesh.shape.get(a, 1) > 1) or self.zero_axes
        rep = NamedSharding(mesh, P())
        batch = tuple(NamedSharding(mesh, s)
                      for s in batch_input_specs(inputs, axes,
                                                 n_replicated_tail))
        grad_sh = (self.grad_shardings(params) if grads == "grad"
                   else self.master_shardings(params))
        return ((self.param_shardings(params), rep, batch), (rep, grad_sh))

    def param_specs(self, params):
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: self.param_spec(x.shape, path_str(kp)), params)

    def master_specs(self, params):
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: self.master_spec(x.shape, path_str(kp)), params)

    def grad_specs(self, params):
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: self.grad_spec(x.shape, path_str(kp)), params)
