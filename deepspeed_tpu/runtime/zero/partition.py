"""ZeRO partitioning as sharding policy.

The TPU-native heart of ZeRO (SURVEY.md §7 design stance): the reference's
flatten/bucket/hook machinery (``runtime/zero/stage_1_and_2.py:97``,
``stage3.py:111``, ``partition_parameters.py``) collapses into *sharding
functions* — given the ZeRO stage, produce ``NamedSharding``s for params /
gradients / optimizer state over the ZeRO mesh axes, and let GSPMD emit the
reduce-scatter / all-gather pipeline those files hand-roll:

  stage 0: params, grads, optimizer state replicated; grads all-reduced.
  stage 1: optimizer state (incl. fp32 master) sharded over dp.
  stage 2: + gradient accumulator sharded over dp → XLA emits reduce-scatter
           for the grad psum (reference ``average_tensor`` stage_1_and_2.py:1045).
  stage 3: + parameters sharded over dp → XLA all-gathers on use, exactly the
           fetch/release coordinator's job (partitioned_param_coordinator.py:276),
           scheduled statically by the latency-hiding scheduler.

Each tensor is sharded along its **largest divisible axis** (no flattening —
keeping the logical shape lets XLA pick layouts, and sidesteps the reference's
alignment/padding bookkeeping).  Tensors too small to split stay replicated —
the analog of the reference's persistent-small-param threshold
(``parameter_offload.py:249 mark_persistent_parameters``).
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_spec(shape, mesh: Mesh, axes, min_size=1):
    """PartitionSpec sharding ``shape``'s largest divisible dim over ``axes``.

    ``axes`` is a tuple of mesh axis names treated as one factored axis
    (e.g. ("dp", "sp") for seq-data-parallel ZeRO sharding, reference
    engine.py:1651).
    """
    if not shape:
        return P()
    n = int(np.prod([mesh.shape[a] for a in axes], dtype=np.int64))
    if n <= 1 or int(np.prod(shape, dtype=np.int64)) < min_size:
        return P()
    # largest dim divisible by n; ties → first
    best = None
    for i, d in sorted(enumerate(shape), key=lambda t: -t[1]):
        if d % n == 0:
            best = i
            break
    if best is None:
        return P()
    spec = [None] * len(shape)
    spec[best] = axes if len(axes) > 1 else axes[0]
    return P(*spec)


def tree_shard_specs(tree, mesh, axes, min_size=1):
    return jax.tree_util.tree_map(
        lambda x: shard_spec(getattr(x, "shape", ()), mesh, axes, min_size), tree)


def tree_shardings(tree, mesh, axes, min_size=1):
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, shard_spec(getattr(x, "shape", ()), mesh,
                                                 axes, min_size)), tree)


def replicated(mesh):
    return NamedSharding(mesh, P())


def tree_replicated(tree, mesh):
    return jax.tree_util.tree_map(lambda x: NamedSharding(mesh, P()), tree)


class ZeroPartitionPlan:
    """Sharding policy for one ZeRO stage over given mesh axes.

    ``tp_rules``: optional callable path→PartitionSpec adding tensor-parallel
    sharding (composed with ZeRO axes; the TP analog of module_inject).
    ``min_partition_size``: params with fewer elements stay replicated
    (persistence threshold analog).
    """

    def __init__(self, stage, mesh, zero_axes=("dp", ), min_partition_size=1,
                 offload_optimizer=False, offload_param=False):
        self.stage = stage
        self.mesh = mesh
        self.zero_axes = tuple(a for a in zero_axes if mesh.shape.get(a, 1) >= 1)
        self.min_partition_size = min_partition_size
        self.offload_optimizer = offload_optimizer
        self.offload_param = offload_param

    # specs -----------------------------------------------------------------
    def param_spec(self, shape):
        if self.stage >= 3:
            return shard_spec(shape, self.mesh, self.zero_axes,
                              self.min_partition_size)
        return P()

    def master_spec(self, shape):
        """fp32 master weights + optimizer moments."""
        if self.stage >= 1:
            return shard_spec(shape, self.mesh, self.zero_axes,
                              self.min_partition_size)
        return P()

    def grad_spec(self, shape):
        """Gradient accumulator sharding. Stage ≥2 shards grads (the engine's
        micro-step constrains grad outputs to this, making XLA lower the DP
        psum to reduce-scatter)."""
        if self.stage >= 2:
            return shard_spec(shape, self.mesh, self.zero_axes,
                              self.min_partition_size)
        return P()

    # tree versions ---------------------------------------------------------
    def _memory_kind(self, offload):
        # Host offload: params/optimizer state resident in pinned host memory,
        # streamed to device per use (reference ZeRO-Offload; SURVEY.md §7
        # "pinned-host offload → memory kinds").
        return "pinned_host" if offload else None

    def _sharding(self, spec, offload=False):
        kind = self._memory_kind(offload)
        if kind is not None:
            try:
                return NamedSharding(self.mesh, spec, memory_kind=kind)
            except Exception:
                return NamedSharding(self.mesh, spec)
        return NamedSharding(self.mesh, spec)

    def param_shardings(self, params):
        return jax.tree_util.tree_map(
            lambda x: self._sharding(self.param_spec(x.shape),
                                     offload=self.offload_param and self.stage >= 3),
            params)

    def master_shardings(self, params):
        return jax.tree_util.tree_map(
            lambda x: self._sharding(self.master_spec(x.shape),
                                     offload=self.offload_optimizer), params)

    def grad_shardings(self, params):
        return jax.tree_util.tree_map(
            lambda x: self._sharding(self.grad_spec(x.shape)), params)

    def param_specs(self, params):
        return jax.tree_util.tree_map(lambda x: self.param_spec(x.shape), params)

    def master_specs(self, params):
        return jax.tree_util.tree_map(lambda x: self.master_spec(x.shape), params)

    def grad_specs(self, params):
        return jax.tree_util.tree_map(lambda x: self.grad_spec(x.shape), params)
