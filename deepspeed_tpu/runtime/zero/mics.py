"""MiCS — Minimal-Communication Sharding (reference ``runtime/zero/mics.py``).

In the reference, MiCS is a ZeRO-3 subclass (``MiCS_Optimizer``
``mics.py:472``) that partitions params/grads/optimizer state over a
*shard group* of ``mics_shard_size`` ranks (instead of all of DP) and
replicates across groups, trading memory for shorter all-gathers plus a
hierarchical cross-group gradient all-reduce (``MiCS_Init``).

In the TPU design this is entirely a **sharding policy** (SURVEY.md §7): the
``dp`` mesh axis is factored as ``dp = zp_outer × zp`` (``utils/groups.py``
hpz mesh) and ``ZeroPartitionPlan(mics=True)`` shards *all* ZeRO state over
the inner ``zp`` axis only:

  * param/state all-gathers ride the short intra-group ICI hops — the
    "minimal communication" part;
  * gradients are still averaged over full dp: with grads constrained to
    zp-sharded-but-zp_outer-replicated layouts, GSPMD emits exactly the
    hierarchical reduce (reduce-scatter within the group, all-reduce across
    groups) that ``MiCS_Optimizer`` hand-implements.

Config: ``{"zero_optimization": {"stage": 3, "mics_shard_size": N}}`` —
identical JSON schema to the reference.  ``mics_hierarchical_params_gather``
is implied (the mesh factoring IS the hierarchy).

``MiCS_Init``/``MiCS_Optimizer`` classes are not needed — params are born in
their shard-group layout via ``engine.initialize_parameters`` — but thin
aliases are provided for import parity.
"""

from .partition import ZeroPartitionPlan


def mics_plan(mesh, hpz_mesh, stage=3, **kw):
    """Build the MiCS sharding policy (engine does this automatically when
    ``mics_shard_size > 1``)."""
    return ZeroPartitionPlan(stage=stage, mesh=mesh, hpz_mesh=hpz_mesh,
                             mics=True, **kw)


class MiCS_Init:
    """Import-parity alias for ``deepspeed.zero.MiCS_Init`` (reference
    ``mics.py``): a no-op context — partitioned creation happens in
    ``engine.initialize_parameters`` under the MiCS plan."""

    def __init__(self, *a, **kw):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
