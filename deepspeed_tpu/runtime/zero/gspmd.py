"""GSPMD-first ZeRO micro-step with quantized manual islands (ISSUE 15).

The flat-manual qgZ micro (:func:`~deepspeed_tpu.runtime.zero.zeropp.
build_manual_dp_micro`) wraps the ENTIRE forward/backward in one
``shard_map``: correct, but opaque — XLA's latency-hiding scheduler cannot
move the quantized collectives against the surrounding compute, every
sharding decision inside the region is hand-rolled, and the region is what
forced the jax-0.4.37 compat shims and CHECK-fail guards of PR 5.  This
module is the replacement default (docs/zero.md "GSPMD-first ZeRO"):

* the forward/backward runs as ONE ``jit`` over ``NamedSharding``-annotated
  params/grads (``ZeroPartitionPlan.micro_shardings`` emits the full in/out
  set) — XLA inserts *and schedules* the tensor-parallel and stage-3 gather
  collectives exactly as in the unquantized micro;
* per-rank (unreduced) gradients are exposed to the program as a *leading
  dp axis*: the batch reshapes ``[B, …] → [n, B/n, …]`` sharded
  ``P(dp, …)`` and ``jax.vmap(value_and_grad, in_axes=(None, None, 0))``
  yields each rank's full gradient contribution stacked on that axis —
  the same local values the manual micro's in-body ``value_and_grad``
  produced, without the manual region (bitwise-equal on the test meshes);
* ``shard_map`` survives ONLY where a quantized wire format requires
  bespoke bytes on the wire: the per-leaf qgZ reduce island below (codec +
  ``all_to_all_quant_reduce``, entered/exited through
  :func:`~deepspeed_tpu.comm.collectives.engine.gspmd_region`) and the qwZ
  gather island ``zeropp.quantized_weight_gather`` already runs in GSPMD
  mode.  Everything around the islands is XLA's to schedule — the EQuARX
  observation (arXiv 2506.17615) applied from user space;
* overlap composes through the PR 8/9 machinery: the reduce islands ride
  ``overlap.pipelined_bucket_reduce`` (bucket *k* fenced behind bucket
  *k−max_inflight* with ``optimization_barrier``) and the stage-3 gather
  rides the qwZ pipeline / ``mark_gather_tree`` prefetch markers — the
  bucket markers are the only manual-free overlap mechanism on this path.

Compositions whose correctness depends on the full-manual region keep it:
:func:`manual_micro_reasons` names them (tp partial-manual, hpZ/MiCS
reshaped meshes, MoE's manual-context dispatch, sp/pp rejection, dp×ep
hierarchies) and the engine routes those to ``build_manual_dp_micro``
unchanged.  ``comm_optimizations.zero_mode: "flat_manual"`` forces the
legacy micro everywhere — the ``ds_bench --zero-mode`` lane measures the
two against each other (flat-manual / GSPMD / GSPMD+quantized-islands).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm.collectives.engine import gspmd_region

#: accepted ``comm_optimizations.zero_mode`` values — "gspmd" (default) is
#: the GSPMD-first micro with quantized islands where the composition
#: allows it; "flat_manual" forces the legacy full-manual micro.
ZERO_MODES = ("gspmd", "flat_manual")


def resolve_zero_mode(comm_opts):
    """The configured ``zero_mode``, validated.  Absent block/field (and
    the legacy ``zero_quantized_gradients`` knob alone) mean "gspmd"."""
    mode = getattr(comm_opts, "zero_mode", None) if comm_opts is not None \
        else None
    mode = mode or "gspmd"
    if mode not in ZERO_MODES:
        raise ValueError(
            f"comm_optimizations.zero_mode {mode!r} unknown "
            f"(have {', '.join(ZERO_MODES)})")
    return mode


def manual_micro_reasons(engine):
    """Why this config still needs the flat-manual micro (empty tuple =
    the GSPMD-first micro applies).  Each entry is a composition whose
    correctness lives inside the full-manual region today — documented in
    docs/zero.md so the list shrinks deliberately, not silently."""
    plan = engine.plan
    reasons = []
    if engine.seq_parallel_world_size > 1 or engine.pp_world_size > 1:
        # the manual builder owns the loud sp/pp rejection text
        reasons.append("sp/pp axes (rejected by the manual builder)")
    if engine.mp_world_size > 1:
        reasons.append("tp > 1 (partial-manual micro)")
    if plan.param_mesh is not plan.mesh or plan.state_mesh is not plan.mesh:
        reasons.append("hpZ/MiCS shard groups (reshaped zp mesh)")
    moe_cfg = getattr(engine._config, "moe_config", None)
    if moe_cfg is not None and getattr(moe_cfg, "enabled", False):
        reasons.append("MoE manual-context expert dispatch")
    mesh = plan.mesh
    eff = [a for a in plan.zero_axes if mesh.shape.get(a, 1) > 1]
    if len(eff) > 1:
        reasons.append("multi-axis ZeRO group (dp×ep / hierarchical "
                       "in-body reduce)")
    return tuple(reasons)


def _lead_spec(entry, ndim):
    """P(entry, None, …) for a leading-dp-axis value of rank ``ndim``."""
    return P(*((entry, ) + (None, ) * (ndim - 1)))


def build_gspmd_quantized_micro(engine):
    """The GSPMD-first qgZ micro: ``micro(params, scale, inputs) ->
    (loss, grads)`` with grads in the master (ZeRO) layout — drop-in for
    the engine's compiled micro fn, loss/grad-bitwise-equal to
    ``build_manual_dp_micro`` on pure-dp meshes (unit-gated)."""
    from ...utils.logging import logger  # noqa: F401  (parity with zeropp)
    from ..utils import make_scaled_loss_fn
    from . import zeropp
    from .overlap import overlap_opts, prefetch_opts, resolve_prefetch
    from .partition import path_str, zero_dim

    plan = engine.plan
    zc = engine._config.zero_config
    co = engine._config.comm_optimizations_config
    co_on = getattr(co, "enabled", False)
    gas = engine.gradient_accumulation_steps()
    apply_fn = engine._effective_apply_fn()
    grad_dtype = engine.grad_accum_dtype
    mesh = plan.mesh
    dp_axes = tuple(a for a in plan.zero_axes if mesh.shape.get(a, 1) > 1)
    n = int(np.prod([mesh.shape[a] for a in dp_axes], dtype=np.int64)) \
        if dp_axes else 1
    lead = dp_axes if len(dp_axes) > 1 else (dp_axes[0] if dp_axes else None)

    qw = (zc.zero_quantized_weights or
          (co_on and getattr(co, "quantized_weights", False))) \
        and engine.zero_stage >= 3
    qw_fmt, qw_gs = plan.param_wire(zc.zero_quantized_weights_format)
    qg_fmt, qg_gs = plan.grad_wire()

    ov = overlap_opts(co)
    pf = prefetch_opts(co)
    if pf is not None and engine.zero_stage < 3:
        pf = None  # the engine already warned once (same rule as GSPMD)
    pf_resolved = resolve_prefetch(pf, zc) if pf is not None else None

    loss_fn = make_scaled_loss_fn(apply_fn, gas)

    def reduce_island(path, g):
        """One leaf's quantized gradient reduce as a shrunken manual
        island: ``g`` is the leading-axis ``[n, *shape]`` per-rank grad;
        the body (this rank's full contribution) runs EXACTLY the manual
        micro's ``reduce_leaf`` collective — ``all_to_all_quant_reduce``
        at the ladder-resolved wire — and the region re-enters GSPMD in
        the master layout."""
        spec = plan.master_spec(g.shape[1:], path)
        leaf_axes = plan.leaf_zero_axes(path, dp_axes)
        dim, axes = zero_dim(spec, leaf_axes)
        if n <= 1:
            # single-rank group: the lone lane IS the reduced gradient
            return jnp.squeeze(g, axis=0).astype(grad_dtype)
        # ladder keys on the LOGICAL (full-leaf) message size, the same
        # quantity the manual micro's in-body g.size reports
        fmt = plan.wire_for_size(qg_fmt,
                                 (g.size // n) * g.dtype.itemsize)

        def body(gl):
            g0 = jnp.squeeze(gl, axis=0)
            if dim is None:
                return jax.lax.pmean(g0, dp_axes).astype(grad_dtype)
            # route via the zeropp module attribute so test spies (and
            # future codec swaps) see one canonical call site
            out = zeropp.all_to_all_quant_reduce(
                g0, axes, dim, n, wire_format=fmt, group_size=qg_gs)
            rest = tuple(a for a in leaf_axes if a not in axes)
            if rest:
                out = jax.lax.pmean(out, rest)
            return out.astype(grad_dtype)

        return gspmd_region(
            body, mesh=mesh, in_specs=_lead_spec(lead, g.ndim),
            out_specs=spec)(g)

    def micro(params, scale, inputs):
        n_tail = engine._n_replicated_batch_tail
        k = len(inputs) - n_tail
        batch, tail = inputs[:k], inputs[k:]
        resh = []
        for x in batch:
            xr = x.reshape((n, x.shape[0] // n) + x.shape[1:])
            if lead is not None:
                xr = jax.lax.with_sharding_constraint(
                    xr, NamedSharding(mesh, _lead_spec(lead, xr.ndim)))
            resh.append(xr)

        full = params
        if qw:
            # qwZ: the per-leaf quantized gather island (already GSPMD-
            # native); with prefetch armed it pipelines its own buckets
            full = zeropp.quantized_weight_gather(
                params, plan, wire_format=qw_fmt, group_size=qw_gs,
                prefetch=pf_resolved)
        elif pf_resolved is not None:
            # flat-wire stage-3 prefetch: the PR 9 gather markers emit
            # each bucket's all-gather inside the forward graph
            from .overlap import mark_gather_tree, prefetch_buckets_for
            buckets, window, _ = prefetch_buckets_for(params, plan,
                                                      pf_resolved)
            if buckets:
                full = mark_gather_tree(params,
                                        plan.gather_shardings(params),
                                        buckets, max_inflight=window)

        def slice_loss(p, s, sl, tl):
            return loss_fn(p, s, tuple(sl) + tuple(tl))

        vg = jax.vmap(jax.value_and_grad(slice_loss, has_aux=True),
                      in_axes=(None, None, 0, None))
        (_, losses), grads = vg(full, scale, tuple(resh), tail)

        if n <= 1:
            loss = losses[0]
        else:
            # pmean island: the exact loss-normalization primitive the
            # manual micro runs (bitwise parity over the scalar too)
            losses = jax.lax.with_sharding_constraint(
                losses, NamedSharding(mesh, P(lead)))
            loss = gspmd_region(
                lambda l: jax.lax.pmean(l[0], dp_axes), mesh=mesh,
                in_specs=P(lead), out_specs=P())(losses)

        if lead is not None:
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, _lead_spec(lead, g.ndim))),
                grads)
        if ov is not None and n > 1:
            # bucketed pipeline over the islands: bucket k's quantized
            # exchange fenced behind bucket k−max_inflight — the PR 8
            # scheduler, with islands as stage2 (buckets are sized on the
            # LOGICAL leaf shapes, i.e. the params tree)
            from .overlap import (bucket_bytes_of, pipelined_bucket_reduce,
                                  tree_buckets)
            buckets, _, _ = tree_buckets(params, bucket_bytes_of(ov))
            grads = pipelined_bucket_reduce(
                grads, buckets, lambda p, g: g, reduce_island,
                max_inflight=getattr(ov, "max_inflight", 2))
        else:
            grads = jax.tree_util.tree_map_with_path(
                lambda kp, g: reduce_island(path_str(kp), g), grads)
        return loss, grads

    return micro
