"""DeepSpeedEngine — the central training wrapper (L4).

TPU-native re-design of reference ``runtime/engine.py:183``.  The reference
wraps a torch module and intercepts autograd (``forward`` :1848, ``backward``
:2007, ``step`` :2204) with per-param hooks feeding bucketed collectives.  Here
the engine owns a **jitted SPMD train step** over the global mesh:

* ``forward(*inputs)``  — runs the compiled value_and_grad micro-step, stashes
  gradients on device, returns the loss;
* ``backward(loss)``    — folds the stashed grads into the (ZeRO-sharded)
  accumulator: stage ≥2 constrains the accumulator sharding so XLA lowers the
  DP gradient reduction to reduce-scatter (the ``average_tensor`` path,
  reference stage_1_and_2.py:1045);
* ``step()``            — at the grad-accum boundary (reference
  ``is_gradient_accumulation_boundary`` engine.py:2088) runs the compiled
  update: unscale → overflow check → clip → optimizer on the sharded fp32
  master partition → re-materialize compute params (all-gather for stage ≤2,
  still-sharded for stage 3) → dynamic loss-scale update.

ZeRO stages are *sharding policies* (``zero/partition.py``), not optimizer
subclasses; the optimizer is an optax-style transform from ``deepspeed_tpu.ops``.
"""

import os
import tempfile
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from .. import telemetry as _telemetry
from ..accelerator import get_accelerator
from ..utils import groups
from ..utils.logging import log_dist, logger
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER,
                           STEP_GLOBAL_TIMER, NoopTimer,
                           SynchronizedWallClockTimer, ThroughputTimer)
from .config import (ADAGRAD_OPTIMIZER, ADAM_OPTIMIZER, ADAMW_OPTIMIZER,
                     DeepSpeedConfig, FUSED_ADAM_OPTIMIZER,
                     FUSED_LAMB_OPTIMIZER, LAMB_OPTIMIZER, LION_OPTIMIZER,
                     SGD_OPTIMIZER)
from .dataloader import DeepSpeedDataLoader
from .loss_scaler import create_loss_scaler, has_overflow
from .lr_schedules import get_lr_scheduler
from .utils import clip_grads_by_global_norm, count_parameters, global_grad_norm
from .zero.partition import ZeroPartitionPlan

MEMORY_OPT_ALLREDUCE_SIZE = 500000000


def _owned_host_tree(tree):
    """``jax.device_get`` that GUARANTEES owning numpy arrays.

    On the CPU backend device_get returns zero-copy views (``owndata=False``,
    dlpack capsule base) aliasing the live XLA buffer; an offload path that
    drops the device reference and later reads the "host copy" is then
    reading freed/donation-reused memory — observed as NaN losses or a
    hard interpreter abort after ``offload_states``.  Copy only when the
    result actually aliases, so real-device transfers stay single-copy."""
    def own(a):
        a = np.asarray(a)
        return a if a.flags.owndata else np.array(a, copy=True)
    return jax.tree_util.tree_map(own, jax.device_get(tree))


class _ParamGroup(dict):
    """torch-style param group whose ``["lr"] = x`` writes reach the compiled
    step: the engine routes the value into the optimizer state's runtime
    ``lr_override`` leaf (no recompile).  Reference torch schedulers mutate
    ``param_groups[0]["lr"]`` directly and FusedAdam honors it."""

    def __init__(self, engine, **kw):
        super().__init__(**kw)
        self._engine = engine

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        if key == "lr" and value is not None:
            self._engine._set_client_lr(float(value))

    # dict.update/setdefault bypass __setitem__ on subclasses — route them
    # through it, or an update({"lr": x}) would be silently inert (the
    # round-2 bug class this facade exists to fix)
    def update(self, *args, **kw):
        for k, v in dict(*args, **kw).items():
            self[k] = v

    def setdefault(self, key, default=None):
        if key not in self:
            self[key] = default
        return self[key]


class _OptimizerFacade:
    """torch-optimizer-shaped view of the engine's optimizer state, for user
    code that expects ``initialize()``'s second return value (reference returns
    the wrapped torch optimizer).  ``param_groups`` exposes lr for schedulers
    written against the torch API; writes take effect (see ``_ParamGroup``)."""

    def __init__(self, engine):
        self._engine = engine
        self.param_groups = [_ParamGroup(engine, lr=None)]

    def state_dict(self):
        return {"opt_state": self._engine.opt_state}

    def load_state_dict(self, sd):
        self._engine.opt_state = sd["opt_state"]

    def zero_grad(self, set_to_none=True):
        pass  # accumulator zeroing happens inside the compiled step

    def step(self):
        self._engine.step()

    @property
    def loss_scale(self):
        return self._engine.cur_scale


def _is_flax_module(model):
    try:
        import flax.linen as nn
        return isinstance(model, nn.Module)
    except ImportError:
        return False


class DeepSpeedEngine:

    def __init__(self,
                 args=None,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 collate_fn=None,
                 config=None,
                 mpu=None,
                 dont_change_device=False,
                 tp_rules=None):
        if not isinstance(config, DeepSpeedConfig):
            config = DeepSpeedConfig(config)
        self._config = config
        self.client_model = model
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training = True
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self._stashed_grads = None
        self._flops_profiled = False
        self.flops_profiler = None
        self._compiled_micro = {}
        self._compiled_apply = None
        self._compiled_eval = {}
        self._micro_cost = {}     # shape key → cost-model entry (MFU feed)
        self._apply_cost = None
        # compression / user hooks
        self._param_transforms = []   # differentiable params→params, in fwd
        self._post_step_hooks = []    # called after each optimizer step

        # ---------------------------------------------------------- bring-up
        # (reference initialize() :143-146 → init_distributed; :153-162 mesh)
        mc = config.mesh_config
        zc = config.zero_config
        # hpZ secondary partition and MiCS shard groups both factor dp into
        # (outer, inner) — one reshaped mesh serves either.
        if zc.mics_shard_size and zc.mics_shard_size > 1 and \
                zc.zero_hpz_partition_size > 1 and \
                zc.zero_hpz_partition_size != zc.mics_shard_size:
            raise ValueError(
                f"mics_shard_size={zc.mics_shard_size} and "
                f"zero_hpz_partition_size={zc.zero_hpz_partition_size} are "
                "mutually exclusive shard-group factorings")
        zp_size = (zc.mics_shard_size if zc.mics_shard_size and
                   zc.mics_shard_size > 1 else zc.zero_hpz_partition_size)
        # multi-process rendezvous FIRST — the mesh below must see the
        # federated device view (reference order: init_distributed :143
        # before mesh :153)
        dist.ensure_runtime_initialized()
        rebuild = None
        if groups.mesh_is_initialized():
            # An earlier model.init / eager op may have auto-built the
            # default dp-only mesh.  If the config EXPLICITLY requests a
            # different factorization, silently keeping the stale mesh
            # would train with sp/tp/pp = 1 while the user asked otherwise
            # — rebuild instead (arrays re-placed by the engine's own
            # device_puts).  Config dims left at their defaults MERGE from
            # the current mesh (a deliberately pre-built tp=2 survives a
            # config that only names sp), and dims the config and mesh
            # agree on never force a rebuild.
            want = {"pp": mc.pp, "sp": mc.sp, "tp": mc.tp, "ep": mc.ep}
            if mc.dp not in (-1, None):
                want["dp"] = mc.dp
            # compare against MeshState TOTALS, not Mesh.shape — the grid's
            # dp axis is dp_total/ep, so shape-based comparison would flag
            # a spurious dp mismatch on every ep>1 mesh
            ms = groups.get_mesh_state()
            cur = {"pp": ms.pp, "dp": ms.dp, "sp": ms.sp, "tp": ms.tp,
                   "ep": ms.ep}
            mismatch = {k: v for k, v in want.items()
                        if v and v > 1 and cur.get(k, 1) != v}
            if mismatch:
                rebuild = {k: (want[k] if want.get(k, 1) and
                               want.get(k, 1) > 1 else cur.get(k, 1))
                           for k in ("pp", "sp", "tp", "ep")}
                rebuild["dp"] = want.get("dp")  # None → re-derive remaining
                logger.warning(
                    f"mesh already initialized as {cur} but the config "
                    f"explicitly requests {mismatch}; rebuilding as "
                    f"{ {k: v for k, v in rebuild.items() if v} } "
                    "(config dims merged over the existing mesh)")
                groups.reset_mesh()
                dist.destroy_process_group()
        if not groups.mesh_is_initialized():
            m = rebuild or {
                "pp": mc.pp, "sp": mc.sp, "tp": mc.tp, "ep": mc.ep,
                "dp": None if mc.dp in (-1, None) else mc.dp}
            groups.initialize_mesh(
                pp=m["pp"], dp=m["dp"], sp=m["sp"], tp=m["tp"], ep=m["ep"],
                zero_partition_size=zp_size)
        elif zp_size and zp_size > 1 and \
                groups.get_mesh_state().zero_partition_size != zp_size:
            # a pre-initialized mesh without the matching dp factoring would
            # silently drop hpZ/MiCS — fail loudly instead
            raise ValueError(
                f"config requests zero partition groups of {zp_size} but the "
                f"mesh was pre-initialized with zero_partition_size="
                f"{groups.get_mesh_state().zero_partition_size}; pass "
                "zero_partition_size to groups.initialize_mesh()")
        dist.init_distributed(config=config)
        self.mesh = groups.get_global_mesh()
        self.dp_world_size = groups._get_data_parallel_world_size()
        self.seq_parallel_world_size = groups._get_sequence_parallel_world_size()
        self.mp_world_size = groups._get_model_parallel_world_size()
        self.pp_world_size = groups._get_pipe_parallel_world_size()

        config.resolve_batch_sizes(self.dp_world_size)

        # ------------------------------------------------------- precision
        if config.bfloat16_enabled:
            self.compute_dtype = jnp.bfloat16
        elif config.fp16_enabled:
            self.compute_dtype = jnp.float16
        else:
            self.compute_dtype = jnp.float32
        self.loss_scaler = create_loss_scaler(
            config.fp16_enabled, config.loss_scale,
            config.dynamic_loss_scale_args)
        self.grad_accum_dtype = {
            None: jnp.float32, "fp32": jnp.float32,
            "fp16": jnp.float16, "bf16": jnp.bfloat16,
        }[config.gradient_accumulation_dtype]

        # ---------------------------------------------------------- model fn
        # (reference _configure_distributed_model engine.py:1145: dtype cast +
        # device move; here: build apply_fn + cast/shard params)
        self.module = model
        if _is_flax_module(model):
            def apply_fn(params, *inputs, rngs=None, **kw):
                variables = {"params": params}
                return model.apply(variables, *inputs, rngs=rngs, **kw)
            self._apply_fn = apply_fn
            self._flax = True
        elif callable(model):
            self._apply_fn = model
            self._flax = False
        else:
            raise TypeError(
                "model must be a flax Module or a callable f(params, *inputs)")

        # ZeRO partition plan (stage → sharding policy)
        zero_axes = groups.zero_sharding_axes(
            sequence_parallel=self.seq_parallel_world_size > 1)
        self.zero_stage = zc.stage
        if tp_rules is None:
            tp_rules = getattr(model, "tp_sharding_rules", None)
        # ------------------------------------------------------------- MoE
        # (docs/moe.md) — install the expert-parallel dispatch options and
        # make expert stacks shard over "ep" without hand-plumbed rules.
        # moe.enabled: false resets the dispatcher to the flat GSPMD path
        # (bit-identical program).
        from ..moe import engine as moe_engine
        moe_cfg = config.moe_config
        moe_engine.configure(moe_cfg if moe_cfg.enabled else None,
                             comm_opts=config.comm_optimizations_config)
        if moe_cfg.enabled:
            from ..moe.experts import expert_sharding_rules
            tp_rules = {**expert_sharding_rules(), **(tp_rules or {})}
        # per-step noisy-gate rng threaded through flax apply (the RSample/
        # Jitter policies were a silent no-op unless callers hand-plumbed an
        # rng); the key rides the input tail like PLD's, folded per layer by
        # flax's scope-path mixing in make_rng
        self._moe_gating_tail = bool(moe_cfg.enabled and _is_flax_module(
            model))
        self._moe_gating_key = jax.random.PRNGKey(
            moe_cfg.gating_seed if moe_cfg.gating_seed is not None
            else config._param_dict.get("seed", 1234)) \
            if self._moe_gating_tail else None
        self.plan = ZeroPartitionPlan(
            stage=zc.stage, mesh=self.mesh, zero_axes=zero_axes,
            tp_rules=tp_rules,
            min_partition_size=max(1, zc.param_persistence_threshold // 8),
            # NVMe residency is managed by the step-wired swapper, not by
            # memory-kind annotations (those are for host-RAM offload)
            offload_optimizer=(zc.offload_optimizer is not None
                               and str(zc.offload_optimizer.device) == "cpu"),
            offload_param=(zc.offload_param is not None
                           and zc.offload_param.device != "none"),
            # only when the config asked for it — a pre-initialized mesh may
            # carry an hpz factoring this engine did not request
            hpz_mesh=(groups.get_mesh_state().hpz_mesh
                      if zp_size and zp_size > 1 else None),
            mics=bool(zc.mics_shard_size and zc.mics_shard_size > 1),
            comm_opts=config.comm_optimizations_config)

        # legacy curriculum learning (reference engine exposes a
        # CurriculumScheduler when "curriculum_learning" is configured)
        self.curriculum_scheduler = None
        if self._config.curriculum_enabled_legacy:
            from .data_pipeline.curriculum_scheduler import CurriculumScheduler
            params = {k: v for k, v in
                      self._config.curriculum_params_legacy.items()
                      if k != "enabled"}
            self.curriculum_scheduler = CurriculumScheduler(params)

        ac = self._config.activation_checkpointing_config
        if ac.partition_activations or ac.cpu_checkpointing or \
                ac.contiguous_memory_optimization or ac.number_checkpoints:
            from .activation_checkpointing import configure as ac_configure
            ac_configure(deepspeed_config=self._config)

        # ------------------------------------------------------- parameters
        self.params = None
        self.master = None
        self.opt_state = None
        self.grad_acc = None
        self.scale_state = None
        self._pending_client_lr = None  # torch-API param_groups lr write
        self._last_loss = None          # reported loss for monitor events
        self._micro_losses = []         # gas-window losses (device scalars)
        self._configure_nvme_swapper(zc)
        if model_parameters is not None:
            self._install_parameters(model_parameters)

        # -------------------------------------------------------- optimizer
        self.optimizer = None
        self._grad_transform = None
        self._configure_optimizer(optimizer)

        # ------------------------------------------------------- scheduler
        self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)

        # ------------------------------------------------------- dataloader
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(
                training_data, collate_fn=collate_fn)

        # ---------------------------------------------------------- timers
        self.wall_clock_breakdown_enabled = config.wall_clock_breakdown
        self.timers = (SynchronizedWallClockTimer()
                       if config.wall_clock_breakdown else NoopTimer())
        self.tput_timer = ThroughputTimer(
            config=type("C", (), {"enabled": True})(),
            batch_size=config.train_batch_size,
            steps_per_output=config.steps_per_print)

        # ---------------------------------------------------------- monitor
        from ..monitor.monitor import MonitorMaster
        self.monitor = MonitorMaster(config.monitor_config)

        # --------------------------------------------------------- telemetry
        # (docs/observability.md) — enabling it wires the structured-event
        # spine: step spans + JSONL records, comm attribution, metrics
        # registry with the monitor as a sink.  Reading loss/grad-norm for
        # the step record costs one device sync per boundary, same as the
        # finite-grad guard; disabled (default) every emit site below is a
        # single module-attribute check.
        self._tel_step_tokens = 0
        self._tel_step_flops = 0.0       # Σ compiled flops this boundary
        self._tel_flops_incomplete = False
        self._mem_planner_emitted = False
        # "sequence_length" (top-level config key, docs/observability.md):
        # tokens per sample for the step records' token accounting.  Unset,
        # the engine ASSUMES axis 1 of inputs[0] is the sequence — loudly,
        # once (see _count_batch_tokens); token-rate metrics are omitted
        # (None, not garbage) when no defensible count exists.
        self.sequence_length = config.sequence_length
        self._seq_len_warned = False
        tc = config.telemetry_config
        if tc.enabled:
            _telemetry.configure(tc, monitor=self.monitor,
                                 rank=jax.process_index())
            _telemetry.metadata("mesh", {k: int(v) for k, v in
                                         dict(self.mesh.shape).items()})
            _telemetry.metadata("zero_partition_plan", self.plan.describe())
            _telemetry.metadata("config_hash", config.config_hash())
            _telemetry.gauge(
                "train/zero_stage",
                help="configured ZeRO stage").set(self.zero_stage)

        # -------------------------------------------------------- resilience
        rs = config.resilience_config
        self._finite_guard = rs.check_finite_grads
        self._consecutive_skips = 0
        self._gnorm_ema = None   # host-side running mean for spike detection
        if self._finite_guard.enabled and self._onebit_opt is not None:
            raise ValueError(
                "resilience.check_finite_grads is not supported with 1-bit "
                "optimizers (their apply path manages its own skip logic); "
                "disable one of them")
        self._heartbeat = None
        from ..elasticity.watchdog import HEARTBEAT_DIR_ENV
        hb_dir = rs.watchdog.heartbeat_dir or os.environ.get(
            HEARTBEAT_DIR_ENV, "")
        if (rs.watchdog.enabled or HEARTBEAT_DIR_ENV in os.environ) \
                and hb_dir:
            from ..elasticity.watchdog import HeartbeatWriter
            self._heartbeat = HeartbeatWriter(hb_dir,
                                              rank=jax.process_index())
        elif rs.watchdog.enabled:
            logger.warning(
                "resilience.watchdog enabled but no heartbeat_dir "
                "configured and DS_TPU_HEARTBEAT_DIR is unset — no "
                "heartbeats will be written (run under the elastic agent "
                "or set resilience.watchdog.heartbeat_dir)")

        # ------------------------------------------- progressive layer drop
        pld_cfg = getattr(config, "pld_config", None)
        if pld_cfg is not None and pld_cfg.enabled:
            import inspect
            target = model.__call__ if self._flax else model
            # non-flax models additionally receive the rng key explicitly
            # (flax models get it via the "pld" rng collection)
            needed = (("pld_theta", ) if self._flax
                      else ("pld_theta", "pld_rng"))
            try:
                sig_params = inspect.signature(target).parameters
                has_var_kw = any(p.kind == inspect.Parameter.VAR_KEYWORD
                                 for p in sig_params.values())
                accepts = has_var_kw or all(n in sig_params for n in needed)
            except (TypeError, ValueError):
                accepts = True  # unintrospectable callables get benefit of doubt
            if not accepts:
                raise ValueError(
                    "progressive_layer_drop is enabled but the model does "
                    f"not accept {' and '.join(needed)} keyword(s) — use "
                    "PLD-aware layers (e.g. DeepSpeedTransformerLayer) or "
                    "disable it")
            from .progressive_layer_drop import ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=pld_cfg.theta, gamma=pld_cfg.gamma)
        else:
            self.progressive_layer_drop = None
        # the PLD theta scalar + rng key (and the MoE gating key before
        # them) ride the END of the micro's input tuple and are replicated
        # (not dp-sharded) by the manual-SPMD micros (qgZ / 1-bit) —
        # reference composes PLD with comm compression the same way
        # (engine-level curriculum, orthogonal)
        self._n_replicated_batch_tail = (
            2 if self.progressive_layer_drop is not None else 0)
        if self._moe_gating_tail:
            self._n_replicated_batch_tail += 1

        # ----------------------------------------------- eigenvalue (compression)
        eig_cfg = getattr(config, "eigenvalue_config", None)
        if eig_cfg is not None and eig_cfg.enabled:
            from .eigenvalue import Eigenvalue
            self.eigenvalue = Eigenvalue(
                verbose=eig_cfg.verbose, max_iter=eig_cfg.max_iter,
                tol=eig_cfg.tol, stability=eig_cfg.stability,
                gas_boundary_resolution=eig_cfg.gas_boundary_resolution,
                layer_name=eig_cfg.layer_name, layer_num=eig_cfg.layer_num)
        else:
            self.eigenvalue = None
        self.block_eigenvalue = None

        if model_parameters is not None:
            log_dist(
                f"DeepSpeedEngine ready: zero_stage={self.zero_stage} "
                f"dtype={self.compute_dtype.__name__} mesh={dict(self.mesh.shape)} "
                f"params={count_parameters(self.params):,}", ranks=[0])

    # ------------------------------------------------------------------ setup
    def _install_parameters(self, model_parameters):
        """Cast + shard the parameter pytree per the ZeRO plan (the analog of
        zero.Init partitioning, reference partition_parameters.py:816 — params
        are 'born partitioned' via device_put with sharded layouts)."""
        mixed = self.compute_dtype != jnp.float32
        param_shardings = self.plan.param_shardings(model_parameters)

        def owned_copy(tree, dtype, shardings):
            # a compiled copy, NOT device_put: device_put may alias the
            # caller's buffers, which the donated apply-step later deletes —
            # the engine must own its state outright
            cast = jax.tree_util.tree_map(
                lambda p: jnp.asarray(p, dtype=dtype), tree)
            return jax.jit(
                lambda t: jax.tree_util.tree_map(jnp.copy, t),
                out_shardings=shardings)(cast)

        self.params = owned_copy(model_parameters, self.compute_dtype,
                                 param_shardings)
        if mixed or self.zero_stage >= 1:
            master_shardings = self.plan.master_shardings(model_parameters)
            self.master = owned_copy(model_parameters, jnp.float32,
                                     master_shardings)
        else:
            self.master = None  # pure fp32 stage-0: params are the master
        # Gradient accumulator is allocated lazily: the first backward()'s
        # stashed grads (already cast + sharded by the micro-step) become the
        # accumulator, so gas=1 never materializes a second grad buffer.
        self.grad_acc = None
        # Replicated commit avoids the 2nd-call full micro-step recompile
        # (observed as two 33MB jit_micro executables / 2× tunnel compile
        # time, r4) — see commit_scale_state.
        from .loss_scaler import commit_scale_state
        self.scale_state = commit_scale_state(self.mesh,
                                              self.loss_scaler.init())

    def initialize_parameters(self, rng_or_seed, *sample_inputs, **kw):
        """Flax path: init params on the engine's mesh (zero.Init analog —
        with stage 3 the fp32 master is created directly into its shards)."""
        if not self._flax:
            raise RuntimeError("initialize_parameters requires a flax Module")
        rng = (jax.random.PRNGKey(rng_or_seed)
               if isinstance(rng_or_seed, int) else rng_or_seed)
        variables = jax.eval_shape(self.module.init, rng, *sample_inputs, **kw)
        params_shape = variables["params"]
        if self.mp_world_size > 1 and not self.plan.tp_rules:
            # tp>1 with no hand-written rules: derive them from the model's
            # dataflow (reference auto_tp.py:273 tp_parser analog)
            from ..module_inject.tp_parser import derive_tp_rules_from_dataflow
            self.plan.tp_rules = derive_tp_rules_from_dataflow(
                lambda p, *i: self.module.apply({"params": p}, *i, **kw),
                params_shape, *sample_inputs)
            log_dist(f"AutoTP derived {len(self.plan.tp_rules)} sharding "
                     f"rules from dataflow", ranks=[0])
        shardings = self.plan.master_shardings(params_shape)

        def init_fn(rng):
            return self.module.init(rng, *sample_inputs, **kw)["params"]

        params = jax.jit(init_fn, out_shardings=shardings)(rng)
        self._install_parameters(params)
        if self.optimizer is None or self.opt_state is None:
            self._configure_optimizer(self.client_optimizer)
        return self.params

    def _configure_optimizer(self, client_optimizer):
        """Reference ``_configure_optimizer`` engine.py:1280 +
        ``_configure_basic_optimizer`` :1330 (config name → optimizer)."""
        from ..ops.adam import fused_adam
        from ..ops.lamb import fused_lamb
        from ..ops.lion import fused_lion, sgd
        from ..ops.muon import muon
        self._host_opt_desc = None   # set for host-steppable optimizers
        from .config import (MUON_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
                             ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER)

        cfg = self._config
        lr_fn = None
        if cfg.scheduler_name is not None:
            sched = get_lr_scheduler(cfg.scheduler_name, cfg.scheduler_params)
            lr_fn = sched.get_lr
            self._sched_for_lr = sched

        self._onebit_opt = None
        onebit_map = {}
        try:
            from .fp16.onebit import OnebitAdam, OnebitLamb, ZeroOneAdam
            onebit_map = {ONEBIT_ADAM_OPTIMIZER: OnebitAdam,
                          ONEBIT_LAMB_OPTIMIZER: OnebitLamb,
                          ZERO_ONE_ADAM_OPTIMIZER: ZeroOneAdam}
        except ImportError:
            pass
        if cfg.optimizer_name in onebit_map and client_optimizer is None:
            p = dict(cfg.optimizer_params or {})
            self._onebit_opt = onebit_map[cfg.optimizer_name](lr_fn=lr_fn, **p)
            self._grad_transform = None
            self.optimizer = _OptimizerFacade(self)
            if self.params is not None:
                self._init_onebit_state()
            return

        if client_optimizer is not None:
            self._grad_transform = client_optimizer
        elif cfg.optimizer_name is not None:
            p = dict(cfg.optimizer_params or {})
            name = cfg.optimizer_name
            lr = p.pop("lr", 1e-3)
            if name in (ADAM_OPTIMIZER, FUSED_ADAM_OPTIMIZER, ADAMW_OPTIMIZER):
                adam_w = p.pop("adam_w_mode", name == ADAMW_OPTIMIZER or
                               name == FUSED_ADAM_OPTIMIZER)
                betas = tuple(p.pop("betas", (0.9, 0.999)))
                eps = p.pop("eps", 1e-8)
                wd = p.pop("weight_decay", 0.0)
                bc = p.pop("bias_correction", True)
                self._grad_transform = fused_adam(
                    lr=lr, betas=betas, eps=eps, weight_decay=wd,
                    adam_w_mode=adam_w, bias_correction=bc, lr_fn=lr_fn)
                if bc:
                    # host-steppable: the native CPU kernel implements
                    # exactly this bias-corrected update
                    self._host_opt_desc = ("adam", dict(
                        lr=lr, betas=betas, eps=eps, weight_decay=wd,
                        adamw_mode=adam_w))
            elif name in (LAMB_OPTIMIZER, FUSED_LAMB_OPTIMIZER):
                self._grad_transform = fused_lamb(
                    lr=lr, betas=tuple(p.pop("betas", (0.9, 0.999))),
                    eps=p.pop("eps", 1e-8),
                    weight_decay=p.pop("weight_decay", 0.0),
                    max_coeff=p.pop("max_coeff", 10.0),
                    min_coeff=p.pop("min_coeff", 0.01), lr_fn=lr_fn)
            elif name == LION_OPTIMIZER:
                betas = tuple(p.pop("betas", (0.9, 0.99)))
                wd = p.pop("weight_decay", 0.0)
                self._grad_transform = fused_lion(
                    lr=lr, betas=betas, weight_decay=wd, lr_fn=lr_fn)
                self._host_opt_desc = ("lion", dict(
                    lr=lr, betas=betas, weight_decay=wd))
            elif name == SGD_OPTIMIZER:
                self._grad_transform = sgd(
                    lr=lr, momentum=p.pop("momentum", 0.0),
                    weight_decay=p.pop("weight_decay", 0.0), lr_fn=lr_fn)
            elif name == ADAGRAD_OPTIMIZER:
                from ..ops.adagrad import fused_adagrad
                eps = p.pop("eps", 1e-10)
                wd = p.pop("weight_decay", 0.0)
                self._grad_transform = fused_adagrad(
                    lr=lr, eps=eps, weight_decay=wd, lr_fn=lr_fn)
                self._host_opt_desc = ("adagrad", dict(
                    lr=lr, eps=eps, weight_decay=wd))
            elif name == MUON_OPTIMIZER:
                self._grad_transform = muon(
                    lr=lr, momentum=p.pop("momentum", 0.95),
                    nesterov=p.pop("nesterov", True),
                    ns_steps=p.pop("ns_steps", 5),
                    weight_decay=p.pop("weight_decay", 0.0), lr_fn=lr_fn)
            else:
                raise ValueError(f"unsupported optimizer {name!r} (have: adam, "
                                 "adamw, fusedadam, lamb, fusedlamb, lion, "
                                 "sgd, muon, adagrad)")
        else:
            self._grad_transform = fused_adam(lr=1e-3, lr_fn=lr_fn)

        self.optimizer = _OptimizerFacade(self)
        if self.params is not None:
            target = self.master if self.master is not None else self.params
            opt_shardings = jax.tree_util.tree_map(
                lambda _: None, target)  # let jit place it like its param
            self.opt_state = jax.jit(
                self._grad_transform.init,
                out_shardings=self._opt_state_shardings(target))(target)
            if self._pending_client_lr is not None:
                self._set_client_lr(self._pending_client_lr)
            if self._nvme_swapper is not None:
                # NVMe offload: state leaves HBM right away (reference
                # stage3.py swaps states out at init, not lazily)
                self._nvme_swap_out()

    # ----------------------------------------------------- NVMe state offload
    def _configure_nvme_swapper(self, zc):
        """Optimizer-state NVMe offload (reference ``stage3.py:1926``
        ``_optimizer_states_and_gradient_swap_in`` + ``swap_tensor/
        partitioned_optimizer_swapper.py``): fp32 master + moments live on
        disk between steps; ``step()`` swaps them in (async reads launched at
        the last ``backward()`` so disk latency overlaps the bwd compute
        tail) and swaps them back out after the update (async writes overlap
        the next forward)."""
        self._nvme_swapper = None
        self._nvme_prefetch = None
        self._state_on_nvme = False
        oo = zc.offload_optimizer
        if oo is not None and str(oo.device) == "nvme":
            from .swap_tensor import PartitionedOptimizerSwapper
            base = oo.nvme_path or os.path.join(
                tempfile.gettempdir(), "ds_tpu_nvme")
            swap_dir = os.path.join(
                str(base), f"zero_stage_{zc.stage}",
                f"rank{jax.process_index()}")
            self._nvme_swapper = PartitionedOptimizerSwapper(swap_dir)
            log_dist(f"NVMe optimizer-state offload → {swap_dir}", ranks=[0])

    def _nvme_swap_out(self):
        """Move (master, opt_state) HBM → disk; async writes, device buffers
        released immediately (this is what shrinks the HBM footprint)."""
        tree = {"master": self.master, "opt_state": self.opt_state}
        host = _owned_host_tree(tree)
        self.master = None
        self.opt_state = None
        self._state_on_nvme = True
        self._nvme_swapper.swap_out_tree(host)

    def _nvme_start_swap_in(self):
        if self._nvme_prefetch is None:
            self._nvme_prefetch = self._nvme_swapper.swap_in_tree_async()

    def _ensure_state_resident(self):
        """Bring offloaded state (host via offload_states, or NVMe) back to
        device refs.  Used by step(), checkpointing, and fragment APIs."""
        if getattr(self, "_host_offloaded", None):
            self.reload_states()
        if self._nvme_swapper is None or not self._state_on_nvme:
            return
        self._nvme_start_swap_in()
        tree = self._nvme_swapper.finish_swap_in(self._nvme_prefetch)
        self._nvme_prefetch = None
        self.master = tree["master"]
        self.opt_state = tree["opt_state"]
        self._state_on_nvme = False

    def _try_host_offload_step(self):
        """Host-side optimizer step for the NVMe/host optimizer-state offload
        path (reference ``csrc/adam/cpu_adam_impl.cpp`` +
        ``stage_1_and_2.py:1186``): when master + moments are host-resident,
        run the native SIMD kernels against the host fp32 state and upload
        ONLY the re-cast compute params — the fp32 state never round-trips
        through HBM (VERDICT r3 missing #2).  Per-step device traffic drops
        from ~24 bytes/param (master+moments down *and* up) to
        grad-down + param-up (≈4-8 bytes/param).

        Returns the host grad-norm when it ran, else None (caller falls back
        to the compiled device apply)."""
        if self._nvme_swapper is None or not self._state_on_nvme or \
                self.grad_acc is None:
            return None
        if os.environ.get("DS_TPU_HOST_OFFLOAD_STEP", "1") == "0":
            return None   # A/B escape hatch: force the device apply path
        desc = getattr(self, "_host_opt_desc", None)
        if desc is None or self._config.fp16_enabled or \
                self._param_transforms or \
                getattr(self, "_host_offloaded", None) or \
                self._finite_guard.enabled or \
                jax.process_count() > 1:
            # dynamic loss scaling / QAT transforms / finite-grad guard /
            # multi-host keep the compiled device path (each would need its
            # own host pass — the guard's skip-select in particular)
            return None
        name, p = desc
        from ..ops import cpu_optimizers as K
        # grads → host (the ONLY device→host bytes on this path)
        grads = jax.device_get(self.grad_acc)
        param_shardings = self.plan.param_shardings(self.grad_acc)
        self.grad_acc = None
        self._nvme_start_swap_in()
        tree = self._nvme_swapper.finish_swap_in(self._nvme_prefetch)
        self._nvme_prefetch = None
        master, opt = tree["master"], tree["opt_state"]
        inv = 1.0 / float(np.asarray(self.scale_state.scale))

        def writable_f32(a):
            a = np.ascontiguousarray(a, dtype=np.float32)
            # device_get may hand back read-only views; the kernels (and the
            # clip/unscale passes) mutate in place
            return a if a.flags.writeable else a.copy()

        g_leaves = [writable_f32(g).ravel()
                    for g in jax.tree_util.tree_leaves(grads)]
        if inv != 1.0:
            for g in g_leaves:
                g *= np.float32(inv)
        gn = float(np.sqrt(sum(K.cpu_sq_norm(g) for g in g_leaves)))
        clip = self._config.gradient_clipping
        if clip and clip > 0 and gn > clip:
            coef = np.float32(clip / gn)
            for g in g_leaves:
                g *= coef

        m_leaves = [writable_f32(m)
                    for m in jax.tree_util.tree_leaves(master)]
        count_leaf = np.asarray(opt.count)
        count = int(count_leaf.ravel()[0]) + 1
        # mirror the device transform's lr exactly: lr_fn(count+1) with the
        # lr_override state leaf winning (resolve_lr semantics) — get_lr()
        # keys off global_steps, which lags count by one at the boundary
        ov_leaf = np.asarray(getattr(opt, "lr_override", np.nan))
        ov = float(ov_leaf.ravel()[0]) if ov_leaf.size else np.nan
        if not np.isnan(ov):
            lr = ov
        elif self._pending_client_lr is not None:
            lr = float(self._pending_client_lr)
        else:
            # ONLY the config-wired scheduler — the device transform's lr_fn
            # comes from cfg.scheduler_name, never from a client scheduler
            sched = getattr(self, "_sched_for_lr", None)
            lr = (float(np.asarray(sched.get_lr(np.int32(count))).ravel()[0])
                  if sched is not None else None)
        # first moment / accumulator tree: adam+lion call it mu, adagrad sum
        mu_attr = "mu" if hasattr(opt, "mu") else "sum"
        mu_tree = getattr(opt, mu_attr)
        mu_leaves = [writable_f32(x).ravel()
                     for x in jax.tree_util.tree_leaves(mu_tree)]
        bf16 = self.compute_dtype == jnp.bfloat16
        import ml_dtypes
        new_params = []
        if name == "adam":
            kern = K.DeepSpeedCPUAdam(lr=p["lr"], betas=p["betas"],
                                      eps=p["eps"],
                                      weight_decay=p["weight_decay"],
                                      adamw_mode=p["adamw_mode"])
            nu_leaves = [writable_f32(x).ravel()
                         for x in jax.tree_util.tree_leaves(opt.nu)]
            for m, g, mu, nu in zip(m_leaves, g_leaves, mu_leaves, nu_leaves):
                out = np.empty(m.size, np.uint16) if bf16 else None
                kern.step_count = count - 1
                kern.step(m.ravel(), g, mu, nu, bf16_out=out, lr=lr)
                new_params.append(
                    out.view(ml_dtypes.bfloat16).reshape(m.shape)
                    if bf16 else m)
        elif name == "adagrad":
            kern = K.DeepSpeedCPUAdagrad(lr=p["lr"], eps=p["eps"],
                                         weight_decay=p["weight_decay"])
            for m, g, s in zip(m_leaves, g_leaves, mu_leaves):
                out = np.empty(m.size, np.uint16) if bf16 else None
                kern.step(m.ravel(), g, s, bf16_out=out, lr=lr)
                new_params.append(
                    out.view(ml_dtypes.bfloat16).reshape(m.shape)
                    if bf16 else m)
        else:   # lion
            kern = K.DeepSpeedCPULion(lr=p["lr"], betas=p["betas"],
                                      weight_decay=p["weight_decay"])
            for m, g, mu in zip(m_leaves, g_leaves, mu_leaves):
                out = np.empty(m.size, np.uint16) if bf16 else None
                kern.step(m.ravel(), g, mu, bf16_out=out, lr=lr)
                new_params.append(
                    out.view(ml_dtypes.bfloat16).reshape(m.shape)
                    if bf16 else m)

        # upload ONLY the compute params, sharded per the plan
        treedef = jax.tree_util.tree_structure(master)
        params_tree = jax.tree_util.tree_unflatten(treedef, new_params)
        self.params = jax.tree_util.tree_map(
            lambda v, s: jax.device_put(v, s), params_tree, param_shardings)
        # moments/master were updated in place; persist + bump the count
        # (same leaf shape it arrived with — a later device-apply fallback
        # must see the tree layout it expects)
        new_opt = opt._replace(
            count=np.full_like(count_leaf, count),
            **{mu_attr: jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(mu_tree),
                [m.reshape(o.shape) for m, o in
                 zip(mu_leaves, jax.tree_util.tree_leaves(mu_tree))])})
        if name == "adam":
            new_opt = new_opt._replace(nu=jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(opt.nu),
                [n.reshape(o.shape) for n, o in
                 zip(nu_leaves, jax.tree_util.tree_leaves(opt.nu))]))
        master_tree = jax.tree_util.tree_unflatten(treedef, m_leaves)
        self.master = None
        self.opt_state = None
        self._state_on_nvme = True
        self._nvme_swapper.swap_out_tree({"master": master_tree,
                                          "opt_state": new_opt})
        self.host_offload_steps = getattr(self, "host_offload_steps", 0) + 1
        return gn

    def _init_onebit_state(self):
        """Place the 1-bit optimizer state: moments replicated, per-worker
        error buffers sharded over dp (fp16/onebit/common.py layout)."""
        from .fp16.onebit.common import _dp_axes
        axes, mesh = _dp_axes(self)
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        target = self.master if self.master is not None else self.params
        state = self._onebit_opt.init(target, max(1, n))
        rep = NamedSharding(mesh, P())
        err = NamedSharding(mesh, P(axes if axes else None, None))
        place = lambda t, s: jax.tree_util.tree_map(
            lambda x: jax.device_put(x, s), t)
        self.opt_state = state._replace(
            mu=place(state.mu, rep), nu=place(state.nu, rep),
            worker_error=place(state.worker_error, err),
            server_error=place(state.server_error, err),
            extra=place(state.extra, rep))

    def _opt_state_shardings(self, target):
        """Optimizer moments shard like the master weights; scalars replicated."""
        state_shape = jax.eval_shape(self._grad_transform.init, target)
        # Build by structure: state trees contain `mu`/`nu` shaped like the
        # target params; suffix path-matching applies the same TP rules.
        from .zero.partition import path_str

        def map_state(s):
            return jax.tree_util.tree_map_with_path(
                lambda kp, x: NamedSharding(
                    self.plan.state_mesh,
                    self.plan.master_spec(x.shape, path_str(kp))), s)
        return map_state(state_shape)

    def _configure_lr_scheduler(self, client_scheduler):
        cfg = self._config
        if client_scheduler is not None:
            return client_scheduler
        if cfg.scheduler_name is not None:
            return getattr(self, "_sched_for_lr", None) or get_lr_scheduler(
                cfg.scheduler_name, cfg.scheduler_params)
        return None

    # -------------------------------------------------------------- properties
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def zero_optimization_stage(self):
        return self.zero_stage

    def zero_optimization(self):
        return self.zero_stage > 0

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def bfloat16_enabled(self):
        return self._config.bfloat16_enabled

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def get_lr(self):
        if self._pending_client_lr is not None:
            return [self._pending_client_lr]
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "get_lr"):
            return [float(self.lr_scheduler.get_lr(
                jnp.asarray(max(1, self.global_steps))))]
        return [None]

    def _scheduler_reclaims_lr(self):
        """Reference semantics: an engine-managed lr scheduler rewrites
        ``param_groups`` every step, so a one-off client lr write lasts only
        until the scheduler's next step.  Mirror that by clearing the
        override whenever the managed scheduler steps."""
        if self._pending_client_lr is None:
            return
        self._pending_client_lr = None
        if self.opt_state is not None and hasattr(self.opt_state,
                                                  "lr_override"):
            from ..ops.adam import no_lr_override
            self.opt_state = self.opt_state._replace(
                lr_override=no_lr_override())

    def _set_client_lr(self, value):
        """Route a torch-API ``param_groups[0]["lr"]`` write into the
        optimizer state's runtime ``lr_override`` leaf so the already-compiled
        step picks it up without recompilation."""
        self._pending_client_lr = value
        if self.opt_state is None:
            return  # applied when the state is created
        if not hasattr(self.opt_state, "lr_override"):
            raise NotImplementedError(
                "this optimizer does not support torch-style lr writes via "
                "param_groups (client/1-bit optimizers manage their own lr); "
                "use an lr scheduler in the config instead")
        self.opt_state = self.opt_state._replace(
            lr_override=jnp.full((), value, jnp.float32))

    @property
    def cur_scale(self):
        return float(self.scale_state.scale) if self.scale_state is not None else 1.0

    @property
    def skipped_steps(self):
        """fp16 overflow-skipped step count.  The per-boundary overflow flag
        stays on device (no host sync in ``step()``); reading this property
        drains the device accumulator."""
        acc = getattr(self, "_overflow_acc", None)
        if acc is not None:
            self._overflow_acc = None
            self._skipped_base += int(jax.device_get(acc))
        return self._skipped_base

    @skipped_steps.setter
    def skipped_steps(self, value):
        self._skipped_base = int(value)
        self._overflow_acc = None

    def is_gradient_accumulation_boundary(self):
        """Reference engine.py:2088."""
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def train(self, mode=True):
        self.training = mode
        return self

    def eval(self):
        return self.train(False)

    # ------------------------------------------------------------- data path
    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None,
                     route=None, data_sampler=None, num_local_io_workers=None):
        """Reference ``deepspeed_io`` engine.py:1753: global-batch loader.
        ``num_local_io_workers`` > 0 overlaps batch IO/collation with the
        device step (threaded sliding window, see ``DeepSpeedDataLoader``)."""
        if batch_size is None:
            batch_size = (self.train_micro_batch_size_per_gpu() *
                          self.dp_world_size)
        if data_sampler is None:
            data_sampler = self._config_curriculum_sampler(dataset,
                                                           batch_size)
        return DeepSpeedDataLoader(dataset, batch_size=batch_size,
                                   collate_fn=collate_fn,
                                   num_local_io_workers=num_local_io_workers,
                                   data_sampler=data_sampler)

    def _config_curriculum_sampler(self, dataset, batch_size):
        """Config-driven curriculum sampler (reference ``deepspeed_io``
        builds a ``DeepSpeedDataSampler`` when
        ``data_efficiency.data_sampling.curriculum_learning`` is enabled,
        engine.py:1753): metric values come from a ``DataAnalyzer`` output
        directory (``{metric}_values.npy``) or inline ``metric_values``."""
        cl = (self._config.train_data_config.get("data_sampling", {})
              .get("curriculum_learning", {}))
        if not cl.get("enabled"):
            return None
        metrics = cl.get("curriculum_metrics", {})
        if not metrics:
            return None
        if len(metrics) > 1:
            logger.warning("multiple curriculum metrics configured; using "
                           "the first (difficulty composition not "
                           "implemented)")
        name, mcfg = next(iter(metrics.items()))
        if "metric_values" in mcfg:
            values = np.asarray(mcfg["metric_values"])
        else:
            from .data_pipeline.data_analyzer import DataAnalyzer
            values = DataAnalyzer.load_metric(mcfg["output_path"], name)
        sched_keys = ("min_difficulty", "max_difficulty", "schedule_type",
                      "schedule_config")
        from .data_pipeline.data_sampler import DeepSpeedDataSampler
        # global batch = micro × gas: the curriculum advances once per
        # OPTIMIZER step and the sampler yields gas micro index-lists
        gas = self.gradient_accumulation_steps()
        return DeepSpeedDataSampler(
            total_samples=len(dataset),
            global_batch_size=batch_size * gas,
            metric_values=values,
            curriculum_config={k: mcfg[k] for k in sched_keys
                               if k in mcfg},
            gradient_accumulation_steps=gas)

    def _batch_sharding(self, x):
        """Shard batch dim 0 over dp (and sequence dim 1 over sp if enabled)."""
        ndim = getattr(x, "ndim", 0)
        spec = [None] * ndim
        if ndim >= 1:
            spec[0] = groups.dp_axes()
        if ndim >= 2 and self.seq_parallel_world_size > 1:
            spec[1] = groups.SP_AXIS
        return NamedSharding(self.mesh, P(*spec))

    def shard_batch(self, *inputs):
        """Place host batch arrays onto the mesh.

        Single-process: ``device_put`` of the full global batch.
        Multi-process (pods): each process passes its LOCAL shard of the
        global batch — per-process data feeding, the reference's per-rank
        ``DistributedSampler`` contract (rank = ``groups.
        _get_data_parallel_rank()``) — and the global array is assembled
        without any cross-host data movement via
        ``jax.make_array_from_process_local_data``.
        """
        if jax.process_count() > 1:
            arrays = [np.asarray(x) for x in inputs]
            return tuple(
                jax.make_array_from_process_local_data(
                    self._batch_sharding(x), x)
                for x in arrays)
        for x in inputs:
            shape = np.shape(x)  # no copy/D2H — device arrays stay put
            if len(shape) >= 1 and shape[0] % max(1, self.dp_world_size):
                # fail HERE with config vocabulary, not deep inside
                # device_put with a raw sharding-divisibility error
                raise ValueError(
                    f"batch dim {shape[0]} is not divisible by the "
                    f"data-parallel degree {self.dp_world_size} — feed "
                    f"train_micro_batch_size_per_gpu × dp = "
                    f"{self.train_micro_batch_size_per_gpu()} × "
                    f"{self.dp_world_size} rows per micro-step (shape "
                    f"{shape})")
            if len(shape) >= 2 and self.seq_parallel_world_size > 1 and \
                    shape[1] % self.seq_parallel_world_size:
                raise ValueError(
                    f"sequence dim {shape[1]} is not divisible by the "
                    f"sequence-parallel degree "
                    f"{self.seq_parallel_world_size} (mesh sp) — pad the "
                    f"sequence (shape {shape})")
        return tuple(
            jax.device_put(jnp.asarray(x), self._batch_sharding(jnp.asarray(x)))
            for x in inputs)

    # -------------------------------------------------------------- hooks
    def register_param_transform(self, fn):
        """Register a differentiable params→params transform composed into
        the forward (QAT fake-quant, LoRA merge, …); invalidates compiles."""
        self._param_transforms.append(fn)
        self.invalidate_compiled()

    def register_post_step_hook(self, fn):
        self._post_step_hooks.append(fn)

    def invalidate_compiled(self):
        self._compiled_micro = {}
        self._compiled_apply = None
        self._compiled_eval = {}
        self._micro_cost = {}
        self._apply_cost = None

    def _effective_apply_fn(self, with_pld=True):
        """apply_fn with registered param transforms composed in — the single
        model-fn entry for every micro-step variant (GSPMD / qgZ / 1-bit)
        and the flops profiler.  In training mode with PLD enabled, the two
        trailing inputs forward() appends (theta, rng key) are stripped and
        delivered as kwargs here — so every consumer stays consistent with
        the augmented input convention."""
        fn = self._apply_fn
        for t in self._param_transforms:
            fn = (lambda inner, t: lambda params, *i, **k: inner(
                t(params), *i, **k))(fn, t)
        if self._moe_gating_tail and self.training and with_pld:
            # the per-step MoE gating key rides the input tail (before the
            # PLD pair); deliver it as the flax "gating" rng collection so
            # make_rng folds in each layer's scope path — per-step,
            # per-layer seeding without hand-plumbing.  Gated on the same
            # flag as the PLD strip: with_pld=False callers (eigenvalue
            # probe) pass RAW inputs with no appended tails, and popping
            # i[-1] there would eat a real model input
            inner_g = fn

            def fn(params, *i, rngs=None, **k):
                r = dict(rngs or {})
                r["gating"] = i[-1]
                return inner_g(params, *i[:-1], rngs=r, **k)
        if with_pld and self.progressive_layer_drop is not None \
                and self.training:
            inner = fn
            if self._flax:
                fn = lambda params, *i, **k: inner(
                    params, *i[:-2], pld_theta=i[-2],
                    rngs={"pld": i[-1]}, **k)
            else:
                # non-flax models receive the key explicitly — they have no
                # rng collection to draw the drop decision from
                fn = lambda params, *i, **k: inner(
                    params, *i[:-2], pld_theta=i[-2], pld_rng=i[-1], **k)
        return fn

    # ---------------------------------------------------------- compiled fns
    def _micro_step_fn(self):
        """Build (loss, grads) = value_and_grad over compute params."""
        if self._onebit_opt is not None:
            from .zero.overlap import overlap_opts, prefetch_opts
            if overlap_opts(self._config.comm_optimizations_config) \
                    is not None or \
                    prefetch_opts(self._config.comm_optimizations_config) \
                    is not None:
                # LOUD: the 1-bit micro manages its own gradient exchange
                # (error-compensated compressed all-reduce) — a user who
                # armed overlap (or overlap_comm / prefetch) must not
                # believe the bucket schedulers are hiding anything here
                logger.warning(
                    "comm_optimizations.overlap (and overlap.prefetch) is "
                    "ignored with 1-bit optimizers: their micro-step "
                    "consumes unreduced per-worker grads and runs its own "
                    "compressed exchange (docs/overlap.md limits)")
            # 1-bit optimizers consume *unreduced* per-worker grads
            return self._onebit_opt.build_micro(self)
        apply_fn = self._effective_apply_fn()
        gas = self.gradient_accumulation_steps()
        zc = self._config.zero_config
        co = self._config.comm_optimizations_config
        co_on = getattr(co, "enabled", False)
        if zc.zero_quantized_gradients or (co_on and co.quantized_gradients):
            # qgZ — the path selection collapses to gspmd / gspmd+islands
            # (ISSUE 15): the default is the GSPMD-first micro whose only
            # manual regions are the shrunken codec+collective islands
            # (runtime/zero/gspmd.py), so XLA schedules everything around
            # them; compositions whose correctness still lives inside the
            # full-manual region — and zero_mode: "flat_manual" — keep the
            # legacy micro (docs/zero.md "GSPMD-first ZeRO").
            from .zero.gspmd import build_gspmd_quantized_micro
            if self._qgz_uses_manual_micro():
                from .zero.zeropp import build_manual_dp_micro
                return build_manual_dp_micro(self)
            return build_gspmd_quantized_micro(self)
        from .zero.overlap import prefetch_opts, resolve_prefetch
        pf = prefetch_opts(co)
        if pf is not None and self.zero_stage < 3:
            if not getattr(self, "_prefetch_stage_warned", False):
                self._prefetch_stage_warned = True
                # LOUD: below stage 3 params are not sharded — there is no
                # forward all-gather for the prefetch pipeline to hide
                logger.warning(
                    "comm_optimizations.overlap.prefetch is ignored at "
                    "ZeRO stage %d: the stage-3 param all-gather it "
                    "pipelines does not exist (params replicated)",
                    self.zero_stage)
            pf = None
        pf_resolved = resolve_prefetch(pf, zc) if pf is not None else None
        qw = (zc.zero_quantized_weights or
              (co_on and co.quantized_weights)) and self.zero_stage >= 3
        if qw:
            # qwZ: int8 param all-gather (straight-through bwd); with
            # prefetch armed the gather itself runs the bucket pipeline,
            # so the GSPMD marker path below is skipped
            from .zero.zeropp import quantized_weight_gather
            inner = apply_fn
            qw_fmt, qw_gs = self.plan.param_wire(
                zc.zero_quantized_weights_format)
            apply_fn = lambda params, *inputs: inner(
                quantized_weight_gather(params, self.plan,
                                        wire_format=qw_fmt,
                                        group_size=qw_gs,
                                        prefetch=pf_resolved), *inputs)
        dc = self._config.domino_config
        if dc.enabled:
            if self.progressive_layer_drop is not None:
                raise ValueError(
                    "domino µ-streams cannot compose with "
                    "progressive_layer_drop (the PLD rng/theta tail would be "
                    "batch-split); disable one of them")
            # Domino µ-streams: independent half-batch subgraphs give the
            # latency-hiding scheduler filler compute for TP collectives
            from .domino.transformer import split_microstreams
            apply_fn = split_microstreams(apply_fn, dc.n_streams)
        from .utils import make_scaled_loss_fn
        loss_fn = make_scaled_loss_fn(apply_fn, gas)

        from .zero.overlap import overlap_opts
        ov = overlap_opts(co)
        if ov is not None:
            # bucketed overlap scheduler (GSPMD flavor): per-bucket
            # custom_vjp markers emit the gradient sharding constraints —
            # and thus XLA's reduce-scatters — inside the backward graph,
            # where the latency-hiding scheduler can slide them under the
            # remaining backward compute (docs/overlap.md)
            from .zero.overlap import (bucket_bytes_of, describe_buckets,
                                       mark_tree, tree_buckets)
            bucket_bytes = bucket_bytes_of(ov)
            inner_loss_fn = loss_fn

            def loss_fn(params, scale, inputs):
                buckets, _, _ = tree_buckets(params, bucket_bytes)
                if _telemetry.enabled and \
                        not getattr(self, "_overlap_meta_emitted", False):
                    self._overlap_meta_emitted = True
                    _telemetry.metadata("overlap_buckets",
                                        describe_buckets(buckets))
                marked = mark_tree(params, self.plan.grad_shardings(params),
                                   buckets)
                return inner_loss_fn(marked, scale, inputs)

        if pf_resolved is not None and not qw:
            # forward-direction prefetch (GSPMD flavor): per-bucket
            # custom_vjp markers apply the *gathered* sharding constraints
            # — and thus XLA's all-gathers — inside the forward graph, in
            # forward-layer order with a max_live-bounded in-flight window,
            # so bucket k+1's gather is issued while bucket k's layers
            # compute (docs/overlap.md forward-prefetch section).  The qwZ
            # path pipelines its own quantized gather above instead.
            from .zero.overlap import (describe_buckets, mark_gather_tree,
                                       prefetch_buckets_for)
            inner_pf_fn = loss_fn

            def loss_fn(params, scale, inputs):
                buckets, window, _ = prefetch_buckets_for(
                    params, self.plan, pf_resolved)
                if not buckets:
                    # every leaf persistent (or tp-claimed): nothing to
                    # gather, keep the program untouched
                    return inner_pf_fn(params, scale, inputs)
                if _telemetry.enabled and \
                        not getattr(self, "_prefetch_meta_emitted", False):
                    self._prefetch_meta_emitted = True
                    _telemetry.metadata(
                        "prefetch_buckets",
                        {"window": window,
                         "buckets": describe_buckets(buckets)})
                marked = mark_gather_tree(
                    params, self.plan.gather_shardings(params), buckets,
                    max_inflight=window)
                return inner_pf_fn(marked, scale, inputs)

        def micro(params, scale, inputs):
            (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, scale, inputs)
            grads = jax.tree_util.tree_map(
                lambda g, s: jax.lax.with_sharding_constraint(
                    g.astype(self.grad_accum_dtype), s),
                grads, self.plan.grad_shardings(params))
            return loss, grads

        return micro

    def _qgz_uses_manual_micro(self):
        """THE routing gate between the two qgZ micros — one predicate
        shared by ``_micro_step_fn`` (which micro is built) and
        ``_micro_variant`` (what the compiled program is named), so the
        tag can never drift from the program it labels.  True = the
        legacy full-manual micro: forced by ``zero_mode: "flat_manual"``
        or required by a composition ``manual_micro_reasons`` names
        (logged once when it's the reasons, not the knob)."""
        from .zero.gspmd import manual_micro_reasons, resolve_zero_mode
        co = self._config.comm_optimizations_config
        mode = resolve_zero_mode(co)
        reasons = manual_micro_reasons(self)
        if reasons and mode != "flat_manual" and \
                not getattr(self, "_manual_micro_logged", False):
            self._manual_micro_logged = True
            logger.info(
                "ZeRO quantized gradients: GSPMD-first micro not "
                "available for this config (%s) — running the "
                "flat-manual micro (docs/zero.md \"GSPMD-first "
                "ZeRO\")", "; ".join(reasons))
        return mode == "flat_manual" or bool(reasons)

    def _micro_variant(self):
        """Short tag of which micro-step flavor is compiled — the cost
        model's program names distinguish the overlap/prefetch/qgZ
        variants the ISSUE-14 observability tracks."""
        if self._onebit_opt is not None:
            return "1bit"
        zc = self._config.zero_config
        co = self._config.comm_optimizations_config
        co_on = getattr(co, "enabled", False)
        if zc.zero_quantized_gradients or (co_on and co.quantized_gradients):
            if self._qgz_uses_manual_micro():
                return "qgZ_manual"
            qv = "qgZ_islands"
            if (zc.zero_quantized_weights or
                    (co_on and co.quantized_weights)) and \
                    self.zero_stage >= 3:
                qv += "+qwZ"
            return qv
        from .zero.overlap import overlap_opts, prefetch_opts
        parts = []
        if overlap_opts(co) is not None:
            parts.append("overlap")
        if prefetch_opts(co) is not None and self.zero_stage >= 3:
            parts.append("prefetch")
        if (zc.zero_quantized_weights or (co_on and co.quantized_weights)) \
                and self.zero_stage >= 3:
            parts.append("qwZ")
        return "+".join(parts) if parts else "flat"

    def _micro_jit_shardings(self, inputs):
        """The explicit ``jit`` in/out ``NamedSharding`` set for the GSPMD
        micro variants (``plan.micro_shardings`` — ISSUE 15's "one jit over
        NamedSharding-annotated params/grads").  None when a variant owns
        its own layout (1-bit, the flat-manual micro, hpZ/MiCS reshaped
        meshes, offloaded state) or when the live arrays disagree with the
        plan's emitted set (e.g. sp batch sharding) — the compile must
        describe what actually runs, so disagreement falls back to
        inference rather than forcing a reshard."""
        if self._onebit_opt is not None:
            return None
        plan = self.plan
        if plan.param_mesh is not plan.mesh or \
                plan.state_mesh is not plan.mesh or \
                plan.offload_param or plan.offload_optimizer:
            return None
        variant = self._micro_variant()
        if variant in ("1bit", "qgZ_manual"):
            return None
        try:
            in_sh, out_sh = plan.micro_shardings(
                self.params, inputs, self._n_replicated_batch_tail,
                grads=("master" if variant.startswith("qgZ_islands")
                       else "grad"))
        except Exception as e:
            # degradation, not failure: the compile falls back to
            # sharding inference — but say so once, or a plan bug would
            # silently disable the explicit-sharding path everywhere
            if not getattr(self, "_micro_shardings_warned", False):
                self._micro_shardings_warned = True
                logger.warning(
                    "plan.micro_shardings unavailable for variant %s "
                    "(%s: %s) — compiling the micro-step with inferred "
                    "shardings", variant, type(e).__name__, e)
            return None

        def agree(x, s):
            sh = getattr(x, "sharding", None)
            if sh is None:
                return False
            try:
                return sh.is_equivalent_to(s, getattr(x, "ndim", 0))
            except (AttributeError, TypeError):
                return sh == s
        live = list(jax.tree_util.tree_leaves(self.params)) + list(inputs)
        want = list(jax.tree_util.tree_leaves(in_sh[0])) + list(in_sh[2])
        if len(live) != len(want) or \
                not all(agree(x, s) for x, s in zip(live, want)):
            return None
        return in_sh, out_sh

    def _get_compiled_micro(self, inputs):
        key = tuple((tuple(x.shape), str(x.dtype)) for x in inputs)
        if key not in self._compiled_micro:
            micro = self._micro_step_fn()
            # compile ahead-of-time (the same single compile jit would do
            # lazily) so XLA's cost/memory analysis of the EXACT training
            # executable lands in the cost-model registry — MFU/HBM
            # observability and the once-per-compile OOM-margin warning
            # (docs/observability.md "MFU & HBM"); falls back to plain jit
            # if the AOT path is unavailable on this backend
            from ..profiling import cost_model
            args = (self.params, self.scale_state.scale, inputs)
            sh = self._micro_jit_shardings(inputs)
            jitted = (jax.jit(micro, in_shardings=sh[0],
                              out_shardings=sh[1])
                      if sh is not None else jax.jit(micro))
            fn, entry = cost_model.capture_jit(
                f"train/micro_step[{self._micro_variant()}]"
                + (f"#{len(self._compiled_micro)}"
                   if self._compiled_micro else ""),
                jitted, args,
                # the analytic walk counts the GLOBAL logical program; the
                # registry convention is per-device flops (what each chip
                # executes under SPMD), so scale by the device count
                fallback_flops=lambda: cost_model.jaxpr_flops(
                    micro, *args)[0] / max(1, jax.device_count()),
                meta={"zero_stage": self.zero_stage,
                      "gas": self.gradient_accumulation_steps()})
            self._compiled_micro[key] = fn
            self._micro_cost[key] = entry
        return self._compiled_micro[key]

    def _accumulate_fn(self):
        def acc(grad_acc, grads):
            return jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), grad_acc, grads)
        return jax.jit(acc, donate_argnums=(0, ))

    def _apply_update_fn(self):
        """The boundary step: unscale, overflow, clip, optimizer, recast."""
        if self._onebit_opt is not None:
            inner = self._onebit_opt.build_apply(self)
            # 1-bit applies manage their own skip logic; accept (and drop)
            # the guard's spike-limit operand so step() calls uniformly
            return (lambda params, master, opt_state, grad_acc, scale_state,
                    spike_limit: inner(params, master, opt_state, grad_acc,
                                       scale_state))
        plan = self.plan
        cfg = self._config
        grad_clip = cfg.gradient_clipping
        transform = self._grad_transform
        scaler = self.loss_scaler
        fp16 = cfg.fp16_enabled
        guard = self._finite_guard.enabled
        compute_dtype = self.compute_dtype
        has_master = self.master is not None

        def apply(params, master, opt_state, grad_acc, scale_state,
                  spike_limit):
            inv = 1.0 / scale_state.scale
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * inv, grad_acc)
            del grad_acc
            # reshard grads to master layout (stage 1: scatter; free slice)
            grads = jax.tree_util.tree_map(
                lambda g, s: jax.lax.with_sharding_constraint(g, s),
                grads, plan.master_shardings(grads))
            overflow = (has_overflow(grads) if fp16 or guard
                        else jnp.zeros((), jnp.bool_))
            gnorm = global_grad_norm(grads)
            # the poisoned/spiking step rides the fp16 skip path for every
            # precision: the update is computed but never committed
            skip = overflow
            if guard:
                skip = jnp.logical_or(skip, gnorm > spike_limit)
            if grad_clip and grad_clip > 0:
                grads, _ = clip_grads_by_global_norm(grads, grad_clip, norm=gnorm)

            target = master if has_master else params
            updates, new_opt = transform.update(grads, opt_state, target)
            new_target = jax.tree_util.tree_map(
                lambda p, u: (p.astype(jnp.float32) + u.astype(jnp.float32)
                              ).astype(p.dtype), target, updates)

            # skip on overflow (reference fp16 optimizer step semantics)
            def sel(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(skip, o, n), new, old)
            new_target = sel(new_target, target)
            new_opt = sel(new_opt, opt_state)

            # Pin the OUTPUT layouts to the plan: without these constraints
            # XLA picks the master/optimizer output shardings freely and
            # (observed on the pinned jaxlib) returns them REPLICATED — the
            # ZeRO-1/2 state partition silently evaporated after the first
            # boundary, inflating steady-state HBM by ~Nx and forcing a
            # second apply-step compile on the de-sharded inputs.  Found by
            # the PR-14 compiled-cost capture (the AOT executable rejected
            # its own second call).
            from .zero.partition import path_str as _path_str
            new_target = jax.tree_util.tree_map(
                lambda t, s: jax.lax.with_sharding_constraint(t, s),
                new_target, plan.master_shardings(new_target))
            new_opt = jax.tree_util.tree_map_with_path(
                lambda kp, x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(
                        plan.state_mesh,
                        plan.master_spec(x.shape, _path_str(kp)))),
                new_opt)

            if has_master:
                new_master = new_target
                new_params = jax.tree_util.tree_map(
                    lambda m, s: jax.lax.with_sharding_constraint(
                        m.astype(compute_dtype), s),
                    new_master, plan.param_shardings(new_master))
            else:
                new_master = None
                new_params = new_target

            # loss-scale dynamics key off true fp16 overflow only — a
            # grad-norm spike must not shrink the scale
            new_scale = scaler.update(scale_state, overflow)
            return new_params, new_master, new_opt, new_scale, skip, gnorm

        return apply

    def _get_compiled_apply(self, args=None):
        if self._compiled_apply is None:
            jitted = jax.jit(
                self._apply_update_fn(), donate_argnums=(0, 1, 2, 3, 4))
            if args is not None:
                # AOT capture like the micro-step: the boundary update's
                # executable is where ALL model states are live at once —
                # its memory_analysis is the static figure the mem-
                # estimator planner is checked against (donation aliasing
                # is subtracted by the analysis)
                from ..profiling import cost_model
                apply_fn = self._apply_update_fn()
                fn, entry = cost_model.capture_jit(
                    "train/apply_update", jitted, args,
                    # per-device convention, like the micro fallback —
                    # keeps MFU available (not refused) on backends
                    # without cost_analysis()
                    fallback_flops=lambda: cost_model.jaxpr_flops(
                        apply_fn, *args)[0] / max(1, jax.device_count()),
                    meta={"zero_stage": self.zero_stage})
                self._compiled_apply = fn
                self._apply_cost = entry
            else:
                self._compiled_apply = jitted
        return self._compiled_apply

    def _spike_limit(self):
        """Grad-norm ceiling for the current step (replicated f32 scalar):
        ``spike_factor ×`` the running mean of recent healthy grad norms,
        +inf while disabled / warming up."""
        g = self._finite_guard
        if (not g.enabled or g.grad_norm_spike_factor <= 0
                or self._gnorm_ema is None
                or self.global_steps < g.spike_warmup_steps):
            return jnp.asarray(jnp.inf, jnp.float32)
        return jnp.asarray(g.grad_norm_spike_factor * self._gnorm_ema,
                           jnp.float32)

    def _account_guarded_step(self, skip, gnorm):
        """Host-side consecutive-skip bookkeeping for the finite-grad guard
        (one device sync per boundary — the documented cost of enabling
        it).  Aborts loudly when skips persist: silently skipping forever
        turns a poisoned data pipeline into a training run that 'finishes'
        without having trained."""
        g = self._finite_guard
        tripped = bool(jax.device_get(skip))
        gn = float(jax.device_get(gnorm))
        if not tripped:
            self._consecutive_skips = 0
            if np.isfinite(gn):
                self._gnorm_ema = (gn if self._gnorm_ema is None
                                   else 0.9 * self._gnorm_ema + 0.1 * gn)
            return
        self._consecutive_skips += 1
        logger.warning(
            "finite-grad guard: skipped poisoned step %d (grad norm %s, "
            "%d consecutive skip(s), abort at %d)", self.global_steps + 1,
            gn, self._consecutive_skips, g.max_consecutive_skips)
        if self.monitor.enabled:
            self.monitor.write_resilience_events(
                [("consecutive_skips", float(self._consecutive_skips))],
                step=self.global_samples)
        if self._consecutive_skips >= g.max_consecutive_skips:
            raise RuntimeError(
                f"finite-grad guard: {self._consecutive_skips} consecutive "
                f"steps produced non-finite or spiking gradients (last "
                f"grad norm {gn}, step {self.global_steps + 1}) — the "
                "input pipeline or numerics are poisoned, not transient; "
                "aborting so the supervisor can restart from the last "
                "valid checkpoint. Raise resilience.check_finite_grads."
                "max_consecutive_skips if this is expected.")

    # ------------------------------------------------------------- public API
    def forward(self, *inputs, **kwargs):
        """Reference engine.py:1848.  In training mode, runs the fused
        loss+grad micro-step and stashes grads for ``backward``."""
        self._check_params()
        inputs = self.shard_batch(*inputs)
        if not self.training:
            return self._eval_forward(inputs, kwargs)
        self.timers(FORWARD_GLOBAL_TIMER).start()
        if _telemetry.enabled:
            _telemetry.begin_step(self.global_steps)
            _telemetry.begin_span(_telemetry.SPAN_FORWARD)
            self._tel_step_tokens += self._count_batch_tokens(inputs)
        if self._moe_gating_tail:
            # per-step fold-in: same compiled program, fresh key each
            # micro-step; flax make_rng folds in the layer path per layer
            inputs = (*inputs, jax.random.fold_in(self._moe_gating_key,
                                                  self.micro_steps))
        if self.progressive_layer_drop is not None:
            inputs = (*inputs,
                      np.float32(self.progressive_layer_drop.get_theta()),
                      jax.random.PRNGKey(self.micro_steps))
        micro = self._get_compiled_micro(inputs)
        if _telemetry.enabled:
            key = tuple((tuple(x.shape), str(x.dtype)) for x in inputs)
            entry = self._micro_cost.get(key)
            if entry is not None:
                # the call COUNT is execution truth — it ticks even when
                # the backend gave this program no flop figure
                entry.calls += 1
            if entry is not None and entry.flops is not None:
                self._tel_step_flops += entry.flops
            else:
                # no flop count for this program: MFU must refuse (None),
                # not report garbage from a partial sum
                self._tel_flops_incomplete = True
        loss, grads = micro(self.params, self.scale_state.scale, inputs)
        from ..utils.fault_injection import fault_point
        if fault_point("engine.poison", step=self.micro_steps):
            # injected data poisoning: NaN loss + grads, exactly what a bad
            # batch / numerics blow-up produces — drives the finite-grad
            # guard tests
            loss = jnp.full_like(loss, jnp.nan)
            grads = jax.tree_util.tree_map(
                lambda g: jnp.full_like(g, jnp.nan), grads)
        self._stashed_grads = grads
        self._micro_losses.append(loss)  # device scalar; synced only on report
        if _telemetry.enabled:
            _telemetry.end_span(_telemetry.SPAN_FORWARD)
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        self._maybe_profile_flops(inputs)
        return loss

    def _eval_forward(self, inputs, kwargs):
        """Compiled eval/validation forward, shape-keyed like the train
        micro-step (reference ``engine.py:3696`` compile wrapper role) —
        transforms (QAT fake-quant, …) apply in eval too, otherwise
        validation measures a different model than is being optimized.
        kwargs are baked into the compiled closure only when they are mode
        flags (bool/str/None — the flax ``train=False``/``deterministic=True``
        style); anything else (arrays, rngs dicts, per-call-varying scalars)
        falls back to op-by-op dispatch so the cache cannot grow one
        executable per distinct kwarg value."""
        if not all(isinstance(v, (bool, str, type(None)))
                   for v in kwargs.values()):
            return self._effective_apply_fn()(self.params, *inputs, **kwargs)
        kw_key = tuple(sorted(kwargs.items()))
        key = (tuple((tuple(x.shape), str(x.dtype)) for x in inputs), kw_key)
        fn = self._compiled_eval.get(key)
        if fn is None:
            apply_fn = self._effective_apply_fn()
            fn = jax.jit(lambda params, *i: apply_fn(params, *i, **kwargs))
            self._compiled_eval[key] = fn
        return fn(self.params, *inputs)

    def _maybe_profile_flops(self, inputs):
        """Flops profiler hook (reference engine wires FlopsProfiler at
        ``flops_profiler.profile_step``, profiler.py:30)."""
        fp = self._config.flops_profiler_config
        if not fp.enabled or self._flops_profiled or \
                self.micro_steps + 1 < fp.profile_step:
            return
        self._flops_profiled = True
        from ..profiling.flops_profiler import FlopsProfiler, jaxpr_flops
        prof = FlopsProfiler(self)
        apply_fn = self._effective_apply_fn()

        def fwd(params, inputs):
            out = apply_fn(params, *inputs)
            return out[0] if isinstance(out, (tuple, list)) else out

        # analytic only (trace, no compile — the train step is already
        # compiled in _compiled_micro; recompiling here would double the
        # XLA compile time/memory for large models)
        prof.profile(fwd, self.params, inputs, compile_xla=False)
        prof.step_flops = jaxpr_flops(self._micro_step_fn(), self.params,
                                      self.scale_state.scale, inputs)[0]
        if dist.get_rank() == 0:
            prof.print_model_profile(profile_step=self.micro_steps + 1,
                                     top_modules=fp.top_modules,
                                     detailed=fp.detailed,
                                     output_file=fp.output_file)
        self.flops_profiler = prof

    def start_device_trace(self, trace_dir):
        """Capture a jax.profiler (xplane) trace of subsequent steps — the
        per-module latency view (flax scope names survive into XLA metadata;
        round-1 review: profiler depth beyond the analytic flops walk)."""
        from ..profiling.flops_profiler import FlopsProfiler
        self._trace_profiler = FlopsProfiler(self)
        return self._trace_profiler.start_trace(trace_dir)

    def stop_device_trace(self):
        return self._trace_profiler.stop_trace()

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    def backward(self, loss=None, **kwargs):
        """Reference engine.py:2007: fold stashed grads into the accumulator."""
        if self._stashed_grads is None:
            raise RuntimeError("backward() called without a prior forward() "
                               "in training mode")
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        if _telemetry.enabled:
            _telemetry.begin_span(_telemetry.SPAN_BACKWARD)
        offloaded = getattr(self, "_host_offloaded", None)
        if offloaded and "grad_acc" in offloaded:
            # grads offloaded mid-accumulation: restore BEFORE the None
            # check or the prior micro-batches' gradients are silently lost
            host, shardings = offloaded["grad_acc"]
            self.grad_acc = jax.tree_util.tree_map(jax.device_put, host,
                                                   shardings)
            del offloaded["grad_acc"]
        if _telemetry.enabled:
            # the fold that triggers the (GSPMD-lowered) DP grad reduction —
            # device-side reduce time lands inside this span under fence mode
            _telemetry.begin_span(_telemetry.SPAN_GRAD_REDUCE)
        if self.grad_acc is None:
            self.grad_acc = self._stashed_grads
        else:
            if not hasattr(self, "_acc_fn"):
                self._acc_fn = self._accumulate_fn()
            self.grad_acc = self._acc_fn(self.grad_acc, self._stashed_grads)
        if _telemetry.enabled:
            _telemetry.end_span(_telemetry.SPAN_GRAD_REDUCE)
        self._stashed_grads = None
        if (self._nvme_swapper is not None and self._state_on_nvme
                and self.is_gradient_accumulation_boundary()):
            # last microbatch: start the async disk reads now so they overlap
            # the backward compute tail (reference swap-in overlap,
            # stage3.py:1926)
            self._nvme_start_swap_in()
        if _telemetry.enabled:
            _telemetry.end_span(_telemetry.SPAN_BACKWARD)
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss

    def step(self):
        """Reference engine.py:2204 — apply at the grad-accum boundary."""
        self._check_params()
        self.timers(STEP_GLOBAL_TIMER).start()
        if self.is_gradient_accumulation_boundary():
            if self.grad_acc is None and \
                    not getattr(self, "_host_offloaded", None):
                raise RuntimeError("step() at a grad-accum boundary without "
                                   "any backward() since the last boundary")
            if _telemetry.enabled:
                _telemetry.begin_span(_telemetry.SPAN_OPTIMIZER)
            host_gnorm = self._try_host_offload_step()
            if host_gnorm is not None:
                skipped = jnp.zeros((), jnp.bool_)
                gnorm = host_gnorm
            else:
                # restore offloaded state FIRST — grads may live on host via
                # offload_states(include=["lp_grads"])
                self._ensure_state_resident()
                if self.grad_acc is None:
                    raise RuntimeError(
                        "step() at a grad-accum boundary without any "
                        "backward() since the last boundary")
                apply_args = (self.params, self.master, self.opt_state,
                              self.grad_acc, self.scale_state,
                              self._spike_limit())
                apply = self._get_compiled_apply(apply_args)
                (self.params, self.master, self.opt_state,
                 self.scale_state, skipped, gnorm) = apply(*apply_args)
                if _telemetry.enabled and self._apply_cost is not None:
                    # counted HERE (where the program ran, flops known or
                    # not) — the host-offload branch above never executes
                    # this executable
                    self._apply_cost.calls += 1
                self.grad_acc = None
                if self._nvme_swapper is not None:
                    # updated state back to disk (async; overlaps next fwd)
                    self._nvme_swap_out()
            if _telemetry.enabled:
                _telemetry.end_span(_telemetry.SPAN_OPTIMIZER)
            if self._finite_guard.enabled:
                self._account_guarded_step(skipped, gnorm)
            self.global_steps += 1
            self.global_samples += self.train_batch_size()
            if self.progressive_layer_drop is not None:
                self.progressive_layer_drop.update_state(self.global_steps)
            if self._config.fp16_enabled:
                # NO host sync here: the overflow flag accumulates on device
                # and drains at steps_per_print (or on a skipped_steps read)
                ov = skipped.astype(jnp.int32)
                self._overflow_acc = (ov if self._overflow_acc is None
                                      else self._overflow_acc + ov)
            if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "step"):
                self.lr_scheduler.step()
                self._scheduler_reclaims_lr()
            if self.curriculum_scheduler is not None:
                self.curriculum_scheduler.update_difficulty(self.global_steps)
            for hook in self._post_step_hooks:
                hook(self)
            if self._micro_losses:
                # the step's loss = mean over the gas window (reference
                # engine.py:2029 logs the accumulated mean, not the last
                # microbatch)
                self._last_loss = self._micro_losses
                self._micro_losses = []
            self._report_step_metrics(gnorm)
            if _telemetry.enabled:
                self._telemetry_step_end(skipped, gnorm)
            if self._heartbeat is not None:
                # liveness signal for the elastic agent's watchdog: one
                # atomic file write per optimizer step
                self._heartbeat.beat(self.global_steps)
        self.micro_steps += 1
        self.timers(STEP_GLOBAL_TIMER).stop()

    def _report_step_metrics(self, gnorm):
        if self._config.fp16_enabled and self.global_steps % \
                self._config.steps_per_print == 0:
            before = self._skipped_base
            if self.skipped_steps != before:   # drains the device accumulator
                log_dist(f"{self._skipped_base - before} overflow-skipped "
                         f"step(s) since last report (step "
                         f"{self.global_steps}), scale → {self.cur_scale}",
                         ranks=[0])
        if self.monitor.enabled and self.global_steps % \
                self._config.steps_per_print == 0:
            events = [("Train/Samples/lr", self.get_lr()[0] or 0.0,
                       self.global_samples)]
            if self._last_loss is not None:
                # reference writes Train/Samples/train_loss every logged step
                # (engine.py:2029) — the loss curve is the monitor's main job
                ll = self._last_loss
                val = (float(np.mean([float(l) for l in ll]))
                       if isinstance(ll, list) else float(ll))
                events.append(("Train/Samples/train_loss", val,
                               self.global_samples))
            if self._config.fp16_enabled:
                events.append(("Train/Samples/loss_scale", self.cur_scale,
                               self.global_samples))
            self.monitor.write_events(events)
        if self.wall_clock_breakdown_enabled:
            self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                             STEP_GLOBAL_TIMER])

    def _count_batch_tokens(self, inputs):
        """Tokens in this micro-batch for the step record's token-rate
        metrics (``tokens``, ``tokens_per_sec_per_chip``).

        With the top-level config key ``"sequence_length"`` set, tokens =
        batch × sequence_length, cross-checked LOUDLY against axis 1 of
        ``inputs[0]`` when it has one.  Unset, a ≥2-D first input ASSUMES
        axis 1 is the sequence — a heuristic that silently counted feature
        dims as tokens for non-token models, so it now warns once and
        points at the config key; a 1-D input counts samples.  Returns 0
        (→ rate metrics omitted as None, never garbage) when there is
        nothing defensible to count."""
        if not inputs:
            return 0
        shape = np.shape(inputs[0])
        if not shape:
            return 0
        seq = self.sequence_length
        if seq:
            if len(shape) >= 2 and shape[1] != seq and \
                    not self._seq_len_warned:
                self._seq_len_warned = True
                logger.warning(
                    "token accounting: config sequence_length=%d but "
                    "inputs[0] has axis-1 size %d — counting batch × "
                    "sequence_length per the config; fix the config (or "
                    "the batch layout) if tokens/s looks wrong", seq,
                    shape[1])
            return int(shape[0]) * int(seq)
        if len(shape) >= 2:
            if not self._seq_len_warned:
                self._seq_len_warned = True
                logger.warning(
                    "token accounting: no \"sequence_length\" in the "
                    "config — ASSUMING inputs[0] axis 1 (=%d) is the "
                    "sequence for tokens/s; set the top-level "
                    "sequence_length key to validate this (a feature dim "
                    "here silently inflates token rates — "
                    "docs/observability.md)", shape[1])
            return int(np.prod(shape[:2]))
        return int(shape[0])

    def _telemetry_step_end(self, skipped, gnorm):
        """Close the telemetry step window with the boundary's numbers and
        refresh the live-metrics registry.  Reading loss/grad-norm/skip
        forces one device sync per boundary — the documented cost of
        telemetry ON (mirrors the finite-grad guard).  The same sync makes
        the ``memory_stats()`` snapshot (the record's ``hbm`` section) a
        true boundary figure, and the compiled-cost registry prices the
        step's executed flops for ``mfu`` (docs/observability.md
        "MFU & HBM")."""
        metrics = {}
        ll = self._last_loss
        try:
            if ll is not None:
                metrics["loss"] = (float(np.mean([float(l) for l in ll]))
                                   if isinstance(ll, list) else float(ll))
            metrics["grad_norm"] = float(jax.device_get(gnorm))
            metrics["skipped"] = float(jax.device_get(skipped))
        except Exception as e:   # telemetry must never kill a step
            logger.warning("telemetry: step metric read failed (%s)", e)
        if self._config.fp16_enabled:
            metrics["loss_scale"] = self.cur_scale
        metrics["samples"] = self.train_batch_size()
        tokens = self._tel_step_tokens
        self._tel_step_tokens = 0
        if tokens:
            metrics["tokens"] = tokens
        metrics["lr"] = self.get_lr()[0]
        # compiled-cost feed: Σ micro flops this window + the boundary
        # update; refused (absent → None) when any executed program had no
        # flop count — MFU is a measurement, not a guess
        from ..profiling import cost_model
        step_flops = None
        if not self._tel_flops_incomplete and self._tel_step_flops > 0:
            step_flops = self._tel_step_flops
            if self._apply_cost is not None:
                if self._apply_cost.flops is None:
                    # the boundary update ran but has no flop figure: a
                    # micro-only sum would be a silent partial — refuse
                    step_flops = None
                else:
                    step_flops += self._apply_cost.flops
        if step_flops is not None:
            metrics["step_flops_per_chip"] = step_flops
            # the recorder derives mfu = step_flops / wall / peak at
            # end_step (it owns the wall clock); peak rides along so the
            # spine stays generic
            metrics["peak_flops_per_chip"] = \
                cost_model.peak_flops_per_chip()
        self._tel_step_flops = 0.0
        self._tel_flops_incomplete = False
        # device-memory snapshot on the boundary sync telemetry already
        # pays for → the step record's "hbm" section + live gauges
        hbm = None
        try:
            from .utils import memory_usage_snapshot
            snap = memory_usage_snapshot()
            hbm = {k: snap[k] for k in ("live_bytes", "peak_bytes",
                                        "limit_bytes")}
            _telemetry.record_hbm(hbm)
        except Exception as e:   # telemetry must never kill a step
            logger.warning("telemetry: memory_stats read failed (%s)", e)
        # refresh the compiled-programs table in the trace metadata every
        # boundary: entries mutate between captures too (call counts), and
        # a version-gated snapshot shipped stale calls=1 tables.  A handful
        # of dict writes per boundary, dwarfed by the device sync above.
        _telemetry.metadata("compiled_programs",
                            cost_model.registry().describe())
        if not self._mem_planner_emitted and self.params is not None:
            # static HBM planner figure for the trace's planner-vs-measured
            # delta (trace_report) — once, from the live partition plan
            self._mem_planner_emitted = True
            try:
                from ..profiling import mem_estimator
                est = mem_estimator.estimate_from_plan(
                    self.params, self.plan,
                    compute_dtype_bytes=jnp.dtype(
                        self.compute_dtype).itemsize,
                    grad_bytes=jnp.dtype(self.grad_accum_dtype).itemsize,
                    include_master=self.master is not None)
                _telemetry.metadata("mem_planner", est)
            except Exception as e:
                logger.warning("telemetry: mem planner estimate failed "
                               "(%s)", e)
        # MoE routed-token stats arrive via jax.debug.callback whenever
        # telemetry is on and the model contains MoE layers (record_routing
        # gates on telemetry, not the moe block) — drain the effect queue
        # so this step's stats land in THIS step's record, not the next
        # one's.  No-op (and cheap) when nothing is pending.
        try:
            jax.effects_barrier()
        except Exception:
            pass
        record = _telemetry.end_step(metrics=metrics)
        reg = _telemetry.get_registry()
        if reg is not None:
            reg.counter("train/steps",
                        help="optimizer steps completed").inc()
            if metrics.get("skipped"):
                reg.counter("train/skipped_steps",
                            help="boundary updates skipped (overflow/"
                            "finite-grad guard)").inc()
            if "loss" in metrics:
                reg.gauge("train/loss").set(metrics["loss"])
            if "grad_norm" in metrics:
                reg.gauge("train/grad_norm").set(metrics["grad_norm"])
            if record is not None:
                wall_s = record["wall_ms"] / 1e3
                reg.histogram("train/step_seconds",
                              help="optimizer-step wall time").observe(
                                  wall_s)
                reg.gauge("train/exposed_comm_fraction",
                          help="host-exposed comm time / step wall time"
                          ).set(record["comm"]["exposed_comm_fraction"])
                if tokens and wall_s > 0:
                    reg.gauge(
                        "train/tokens_per_sec_per_chip",
                        help="tokens/s/chip over the last step").set(
                            tokens / wall_s / max(1, jax.device_count()))
                rmfu = record.get("metrics", {}).get("mfu")
                if rmfu is not None:
                    reg.gauge(
                        "train/mfu",
                        help="model-FLOPs utilization: compiled per-chip "
                        "flops/s ÷ per-chip peak").set(rmfu)
            if hbm is not None:
                reg.gauge("hbm/live_bytes",
                          help="device bytes_in_use at the boundary"
                          ).set(hbm["live_bytes"])
                reg.gauge("hbm/peak_bytes",
                          help="device peak_bytes_in_use").set(
                              hbm["peak_bytes"])
                if hbm["limit_bytes"]:
                    reg.gauge("hbm/limit_bytes",
                              help="device bytes_limit").set(
                                  hbm["limit_bytes"])
        if self.global_steps % self._config.steps_per_print == 0:
            _telemetry.export_metrics(step=self.global_samples)

    def train_batch(self, data_iter=None):
        """Convenience full-batch step (forward+backward+step × GAS)."""
        if data_iter is None:
            data_iter = iter(self.training_dataloader)
        losses = []
        self.tput_timer.start()
        for _ in range(self.gradient_accumulation_steps()):
            batch = next(data_iter)
            if not isinstance(batch, (tuple, list)):
                batch = (batch, )
            loss = self.forward(*batch)
            self.backward(loss)
            self.step()
            losses.append(loss)
        self.tput_timer.stop(global_step=True)
        # mean over the gas window as a DEVICE scalar (reference train_batch
        # returns the aggregated loss tensor, engine.py:2029) — converting to
        # float here would block async dispatch on every micro-batch window
        if len(losses) == 1:
            return losses[0].astype(jnp.float32)
        return jnp.mean(jnp.stack([l.astype(jnp.float32) for l in losses]))

    def _check_params(self):
        offloaded = getattr(self, "_host_offloaded", None)
        if offloaded and "params" in offloaded:
            # forward needs ONLY the params back; master/opt_state stay on
            # host until step()/checkpointing asks (the point of offloading
            # optimizer state is running generation forwards without it)
            host, shardings = offloaded["params"]
            self.params = jax.tree_util.tree_map(jax.device_put, host,
                                                 shardings)
            del offloaded["params"]  # only after the puts succeeded
        if self.params is None:
            raise RuntimeError(
                "engine has no parameters — pass model_parameters to "
                "initialize() or call engine.initialize_parameters(seed, "
                "*sample_inputs) first")

    def compute_block_eigenvalues(self, *sample_inputs):
        """Per-block Hessian max-eigenvalues of the loss (reference engine
        eigenvalue hook, consumed by compression's quantization-offset
        scheduling).  Caches the result on ``self.block_eigenvalue``."""
        if self.eigenvalue is None:
            raise RuntimeError("eigenvalue is not enabled in the config "
                               '("eigenvalue": {"enabled": true})')
        self._check_params()
        inputs = self.shard_batch(*sample_inputs)
        apply_fn = self._effective_apply_fn(with_pld=False)
        self.block_eigenvalue = self.eigenvalue.compute_eigenvalue(
            lambda p, *i: apply_fn(p, *i), self.params, *inputs)
        return self.block_eigenvalue

    def compile(self, backend=None, compile_kwargs=None) -> None:
        """Reference ``engine.py:3696`` (torch.compile wrapper).  Every
        train/eval step here is already traced+compiled by XLA under jit, so
        this only records the request for API parity."""
        self._is_compiled = True

    @property
    def is_compiled(self) -> bool:
        return getattr(self, "_is_compiled", False)

    # ------------------------------------------------- state offload on demand
    _OFFLOAD_STATE_ATTRS = {"optim_states": "opt_state",
                            "hp_params": "master",
                            "lp_params": "params",
                            "lp_grads": "grad_acc"}

    def offload_states(self, include=None, device="cpu", pin_memory=True,
                       non_blocking=False):
        """Move engine states to host memory on demand (reference
        ``engine.py:3720``; used by RLHF-style flows to free HBM between
        phases).  ``include``: subset of {"optim_states", "hp_params",
        "lp_params", "lp_grads"}; default all.  States return via
        :meth:`reload_states` (or automatically on the next
        forward/backward/step)."""
        if str(device) not in ("cpu", "OffloadDeviceEnum.cpu"):
            raise ValueError(f"only host offload is supported, got {device}")
        if getattr(self, "_state_on_nvme", False):
            raise RuntimeError("states already offloaded to NVMe")
        names = (set(include) if include is not None
                 else set(self._OFFLOAD_STATE_ATTRS))
        self._host_offloaded = getattr(self, "_host_offloaded", None) or {}
        for name in names:
            # accept both "optim_states" and OffloadStateTypeEnum.optim_states
            attr = self._OFFLOAD_STATE_ATTRS.get(str(name).split(".")[-1])
            if attr is None:
                raise ValueError(
                    f"unknown state {name!r} "
                    f"(have: {sorted(self._OFFLOAD_STATE_ATTRS)})")
            tree = getattr(self, attr)
            if tree is None or attr in self._host_offloaded:
                continue
            shardings = jax.tree_util.tree_map(lambda x: x.sharding, tree)
            host = _owned_host_tree(tree)  # OWNING host copy — a device_get
            # view would alias the buffer released on the next line
            setattr(self, attr, None)     # release the HBM buffers
            self._host_offloaded[attr] = (host, shardings)

    def reload_states(self, non_blocking=False):
        """Reload offloaded states to their original device shardings
        (reference ``engine.py:3747``)."""
        for attr, (host, shardings) in (getattr(self, "_host_offloaded",
                                                None) or {}).items():
            setattr(self, attr, jax.tree_util.tree_map(
                jax.device_put, host, shardings))
        self._host_offloaded = {}

    # ----------------------------------------------------------- checkpointing
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, exclude_frozen_parameters=False,
                        async_save=False):
        """``async_save=True`` stages the write and returns immediately
        (the reference's Nebula async engine role); the `latest` tag
        commits at :meth:`wait_for_checkpoint` (also called automatically
        before the next save)."""
        from .checkpoint_engine import save_engine_checkpoint
        self._ensure_state_resident()
        self.wait_for_checkpoint()   # one pending async save at a time
        out = save_engine_checkpoint(self, save_dir, tag=tag,
                                     client_state=client_state,
                                     save_latest=save_latest,
                                     async_save=async_save)
        if async_save:
            self._pending_ckpt = out
            if not getattr(self, "_ckpt_atexit", False):
                # a script whose LAST act is an async save would otherwise
                # exit without ever committing the `latest` tag
                import atexit
                import weakref
                ref = weakref.ref(self)
                atexit.register(
                    lambda: ref() is not None and ref().wait_for_checkpoint())
                self._ckpt_atexit = True
        return out

    def wait_for_checkpoint(self):
        """Block until a pending ``async_save`` checkpoint is durable."""
        pending = getattr(self, "_pending_ckpt", None)
        if pending is not None:
            self._pending_ckpt = None  # even a failed commit must not wedge
            pending.wait()

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False, custom_load_fn=None):
        # a pending async save must commit first: `latest` isn't written
        # until then, and the target dir may still be mid-write
        self.wait_for_checkpoint()
        try:
            return self._load_checkpoint_impl(
                load_dir, tag, load_optimizer_states,
                load_lr_scheduler_states, load_module_only)
        finally:
            if self.progressive_layer_drop is not None:
                # resume at the annealed theta, not a fresh 1.0
                self.progressive_layer_drop.update_state(self.global_steps)

    def _load_checkpoint_impl(self, load_dir, tag, load_optimizer_states,
                              load_lr_scheduler_states, load_module_only):
        if self._config.checkpoint_config.load_universal:
            from ..checkpoint.universal_checkpoint import load_universal_checkpoint
            return load_universal_checkpoint(
                self, load_dir, tag=tag,
                load_optimizer_states=load_optimizer_states,
                load_lr_scheduler_states=load_lr_scheduler_states,
                load_module_only=load_module_only)
        from .checkpoint_engine import load_engine_checkpoint
        return load_engine_checkpoint(
            self, load_dir, tag=tag,
            load_optimizer_states=load_optimizer_states,
            load_lr_scheduler_states=load_lr_scheduler_states,
            load_module_only=load_module_only)

    def _export_16bit_tree(self):
        """Source tree for :meth:`save_16bit_model` — overridden by engines
        whose parameters do not live on device (InfinityEngine)."""
        return self.params

    def save_16bit_model(self, save_dir, save_filename="pytorch_model.bin",
                         exclude_frozen_parameters=False):
        """Consolidated compute-dtype export (reference engine.py:3638 +
        _zero3_consolidated_16bit_state_dict :3569 — here a device_get of the
        global arrays *is* the consolidation).

        Written as ``.npz``; bf16 leaves are stored as uint16 raw views with
        their names recorded under ``__bf16__`` (numpy cannot serialize the
        ml_dtypes dtype) — reload with
        :func:`deepspeed_tpu.runtime.utils.load_16bit_npz`."""
        import ml_dtypes
        import numpy as onp
        from .utils import ensure_directory_exists
        name = save_filename
        if name.endswith(".bin"):
            name = name[:-4] + ".npz"
        elif not name.endswith(".npz"):
            name += ".npz"   # np.savez appends it anyway; keep path honest
        path = os.path.join(save_dir, name)
        ensure_directory_exists(path)
        from .zero.partition import path_str
        flat, bf16_names = {}, []
        for kp, leaf in jax.tree_util.tree_leaves_with_path(
                self._export_16bit_tree()):
            arr = onp.asarray(leaf)
            if self.compute_dtype == jnp.bfloat16 and \
                    arr.dtype != ml_dtypes.bfloat16:
                arr = arr.astype(ml_dtypes.bfloat16)
            key = path_str(kp)
            if arr.dtype == ml_dtypes.bfloat16:
                bf16_names.append(key)
                arr = arr.view(onp.uint16)
            flat[key] = arr
        flat["__bf16__"] = onp.asarray(bf16_names)
        onp.savez(path, **flat)
        return path

    # -------------------------------------------------------------- zero APIs
    def get_fp32_param(self, path=None):
        """Tensor-fragment API analog (reference utils/tensor_fragment.py):
        full fp32 weights as a host pytree."""
        self._ensure_state_resident()
        src = self.master if self.master is not None else self.params
        return jax.tree_util.tree_map(lambda x: np.asarray(x, dtype=np.float32), src)

    def empty_partition_cache(self):
        pass  # XLA owns buffers; parity no-op (reference engine.py:3747 area)

    def parameter_names(self):
        """path_str names of every parameter, for the tensor-fragment API."""
        from ..utils.tensor_fragment import parameter_names
        return parameter_names(self)
