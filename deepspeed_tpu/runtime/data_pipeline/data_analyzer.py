"""Offline data analysis — reference
``runtime/data_pipeline/data_sampling/data_analyzer.py:22`` (DataAnalyzer).

Map-reduce over a dataset into curriculum index files:

* map: worker ``i`` walks its contiguous shard, evaluating each metric fn —
  ``single_value_per_sample`` metrics record one value per sample;
  ``accumulate_value_over_samples`` metrics fold into one running value
  (e.g. total token count).
* reduce: shards merge into the reference's artifact set per metric —
  ``{m}_sample_to_metric``   (MMap indexed: sample id → value)
  ``{m}_metric_to_sample``   (inverted: one document per distinct value,
                              listing its sample ids)
  ``{m}_index_to_sample``    (easy→hard consumption order)
  ``{m}_index_to_metric``    (the sorted values themselves)
  ``{m}_index_to_sample_percentile_merged`` (one document per percentile,
                              the curriculum scheduler's lookup granularity)
  plus ``{m}_values.npy`` for direct numpy consumption by
  ``DeepSpeedDataSampler(metric_values=...)``.

``custom_map_init/update/finalize`` and ``custom_reduce`` hooks mirror the
reference's extension points.  ``run_map_reduce(num_workers=N)`` spawns the
workers as processes (the reference uses multiprocessing the same way).
"""

import json
import os
from multiprocessing import get_context

import numpy as np

from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder)

SINGLE = "single_value_per_sample"
ACCUM = "accumulate_value_over_samples"


class DataAnalyzer:
    def __init__(self, dataset, output_path, metric_names=None,
                 metric_functions=None, metric_types=None, num_workers=1,
                 worker_id=0, batch_size=64, metric_dtypes=None,
                 custom_map_init=None, custom_map_update=None,
                 custom_map_finalize=None, custom_reduce=None,
                 sample_indices=None):
        """``metric_functions``: list of callables sample → scalar (SINGLE)
        or (running, sample) → running (ACCUM)."""
        self.dataset = dataset
        self.output_path = os.path.abspath(output_path)
        self.metric_names = metric_names or ["metric"]
        self.metric_functions = metric_functions or []
        self.metric_types = metric_types or [SINGLE] * len(self.metric_names)
        self.metric_dtypes = metric_dtypes or \
            [np.float64] * len(self.metric_names)
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size
        self.custom_map_init = custom_map_init
        self.custom_map_update = custom_map_update
        self.custom_map_finalize = custom_map_finalize
        self.custom_reduce = custom_reduce
        self.sample_indices = sample_indices
        os.makedirs(self.output_path, exist_ok=True)

    # ------------------------------------------------------------------ map
    def _shard_range(self):
        n = (len(self.sample_indices) if self.sample_indices is not None
             else len(self.dataset))
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return lo, min(n, lo + per)

    def _shard_file(self, name, worker_id=None):
        wid = self.worker_id if worker_id is None else worker_id
        return os.path.join(self.output_path, f"{name}_worker{wid}.npy")

    def run_map(self):
        """Analyze this worker's shard; write {metric}_worker{i}.npy."""
        lo, hi = self._shard_range()
        state = (self.custom_map_init() if self.custom_map_init else None)
        results = {}
        for name, mtype in zip(self.metric_names, self.metric_types):
            results[name] = [] if mtype == SINGLE else None
        for j in range(lo, hi):
            idx = (self.sample_indices[j] if self.sample_indices is not None
                   else j)
            sample = self.dataset[idx]
            for name, fn, mtype in zip(self.metric_names,
                                       self.metric_functions,
                                       self.metric_types):
                if mtype == SINGLE:
                    results[name].append(float(fn(sample)))
                elif mtype == ACCUM:
                    results[name] = fn(results[name], sample)
                else:
                    raise ValueError(f"unknown metric_type {mtype!r} "
                                     f"(have: {SINGLE!r}, {ACCUM!r})")
            if self.custom_map_update:
                state = self.custom_map_update(state, sample)
        if self.custom_map_finalize:
            state = self.custom_map_finalize(state)
            with open(os.path.join(
                    self.output_path,
                    f"custom_worker{self.worker_id}.json"), "w") as f:
                json.dump(state, f)
        for name, mtype in zip(self.metric_names, self.metric_types):
            if mtype == SINGLE:
                val = results[name]
            else:
                # an empty shard never ran the fold — contribute the sum
                # identity instead of crashing np.asarray on None
                val = [0.0 if results[name] is None else results[name]]
            np.save(self._shard_file(name),
                    np.asarray(val, dtype=np.float64))
        with open(os.path.join(self.output_path,
                               f"shard_worker{self.worker_id}.json"),
                  "w") as f:
            json.dump({"lo": lo, "hi": hi}, f)
        return {k: (np.asarray(v) if isinstance(v, list) else v)
                for k, v in results.items()}

    # --------------------------------------------------------------- reduce
    def _write_index_files(self, name, values, dtype):
        """The reference's per-metric artifact set as MMap indexed files."""
        pre = os.path.join(self.output_path, name)
        s2m = MMapIndexedDatasetBuilder(f"{pre}_sample_to_metric",
                                        dtype=dtype)
        for v in values:
            s2m.add_item(np.asarray([v], dtype=dtype))
        s2m.finalize()

        order = np.argsort(values, kind="stable")
        i2s = MMapIndexedDatasetBuilder(f"{pre}_index_to_sample",
                                        dtype=np.int64)
        i2s.add_item(order.astype(np.int64))
        i2s.finalize()
        i2m = MMapIndexedDatasetBuilder(f"{pre}_index_to_metric", dtype=dtype)
        i2m.add_item(values[order].astype(dtype))
        i2m.finalize()

        # inverted index: one document per distinct metric value (ascending)
        m2s = MMapIndexedDatasetBuilder(f"{pre}_metric_to_sample",
                                        dtype=np.int64)
        distinct = []
        sorted_vals = values[order]
        start = 0
        for k in range(1, len(order) + 1):
            if k == len(order) or sorted_vals[k] != sorted_vals[start]:
                m2s.add_item(order[start:k].astype(np.int64))
                distinct.append(float(sorted_vals[start]))
                start = k
        m2s.finalize()
        with open(f"{pre}_metric_to_sample_keys.json", "w") as f:
            json.dump(distinct, f)

        # percentile merge: 100 documents, percentile p → its sample ids
        # (reference index_to_sample_percentile_merged — the curriculum
        # difficulty lookup granularity)
        pm = MMapIndexedDatasetBuilder(
            f"{pre}_index_to_sample_percentile_merged", dtype=np.int64)
        bounds = (np.arange(1, 101) * len(order) / 100).astype(np.int64)
        start = 0
        for b in bounds:
            pm.add_item(order[start:b].astype(np.int64))
            start = b
        pm.finalize()

    def run_reduce(self):
        """Merge all worker shards → index files + {metric}_values.npy."""
        merged = {}
        for name, mtype, dtype in zip(self.metric_names, self.metric_types,
                                      self.metric_dtypes):
            parts = []
            for w in range(self.num_workers):
                path = self._shard_file(name, w)
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"worker {w} shard missing for metric {name}: {path}")
                parts.append(np.load(path))
            if mtype == ACCUM:
                # fold shard accumulators (sum — the reference's semantics
                # for token-count style metrics)
                total = float(np.sum([p[0] for p in parts]))
                with open(os.path.join(self.output_path,
                                       f"{name}_total.json"), "w") as f:
                    json.dump(total, f)
                merged[name] = total
                continue
            values = np.concatenate(parts)
            np.save(os.path.join(self.output_path, f"{name}_values.npy"),
                    values)
            order = np.argsort(values, kind="stable")
            np.save(os.path.join(self.output_path,
                                 f"{name}_index_to_sample.npy"), order)
            self._write_index_files(name, values, dtype)
            merged[name] = values
        if self.custom_reduce:
            states = []
            for w in range(self.num_workers):
                p = os.path.join(self.output_path, f"custom_worker{w}.json")
                if os.path.exists(p):
                    with open(p) as f:
                        states.append(json.load(f))
            merged["custom"] = self.custom_reduce(states)
        return merged

    def run(self):
        self.run_map()
        if self.worker_id == 0 and self.num_workers == 1:
            return self.run_reduce()
        return None

    def run_map_reduce(self, num_workers=None):
        """Spawn ``num_workers`` map processes, then reduce (the reference's
        multiprocessing flow, ``data_analyzer.py`` run_map_reduce)."""
        n = num_workers or self.num_workers
        self.num_workers = n
        if n == 1:
            self.run_map()
            return self.run_reduce()
        ctx = get_context("fork")
        procs = []
        for w in range(n):
            procs.append(ctx.Process(target=_map_worker, args=(self, w)))
            procs[-1].start()
        for p in procs:
            p.join()
            if p.exitcode != 0:
                raise RuntimeError(f"map worker failed (exit {p.exitcode})")
        return self.run_reduce()

    # ------------------------------------------------------------- consumers
    @staticmethod
    def load_metric(output_path, metric_name="metric"):
        return np.load(os.path.join(output_path, f"{metric_name}_values.npy"))

    @staticmethod
    def load_index_to_sample(output_path, metric_name="metric"):
        ds = MMapIndexedDataset(
            os.path.join(output_path, f"{metric_name}_index_to_sample"))
        return np.asarray(ds[0])

    @staticmethod
    def load_percentile_samples(output_path, metric_name="metric",
                                percentile=100):
        """Sample ids at difficulty ≤ the given percentile (1-100)."""
        ds = MMapIndexedDataset(os.path.join(
            output_path, f"{metric_name}_index_to_sample_percentile_merged"))
        parts = [np.asarray(ds[p]) for p in range(min(percentile, len(ds)))]
        return np.concatenate(parts) if parts else np.array([], np.int64)


def _map_worker(analyzer, worker_id):
    analyzer.worker_id = worker_id
    analyzer.run_map()


class DistributedDataAnalyzer:
    """Map-reduce analysis across *distributed* processes (reference
    ``data_analyzer.py:455 DistributedDataAnalyzer``): each rank maps its
    contiguous shard of the dataset, then the shards reduce into the same
    artifact set ``DataAnalyzer`` writes single-process.

    Two reduce transports:

    * ``shared_fs=True`` (default) — every rank writes its shard file to
      the common ``output_path``; after a barrier, rank 0 merges them (the
      reference DataAnalyzer's file-based merge, which assumes a shared
      filesystem — true for the NFS/GCS mounts TPU pods train from).
    * ``shared_fs=False`` — ranks send their shard arrays to rank 0 over the
      comm facade's host object channel (``send_obj``/``recv_obj``), the
      analog of the reference's torch.distributed gather; no common mount
      required.

    The reference's distributed sample-sort (``Dist.sample_sort``) exists
    to bound rank-0 memory on billion-sample corpora; here reduce is
    rank-0-resident, which holds to ~1e9 float64 values — beyond that,
    shard the metric space with multiple analyzers.  Output files are
    byte-identical to a single-process ``DataAnalyzer`` run."""

    def __init__(self, dataset, output_path, metric_names=None,
                 metric_functions=None, metric_types=None,
                 metric_dtypes=None, batch_size=64, sample_indices=None,
                 shared_fs=True, comm=None, custom_map_init=None,
                 custom_map_update=None, custom_map_finalize=None,
                 custom_reduce=None):
        from ... import comm as dist
        self._dist = comm or dist
        if not self._dist.is_initialized():
            self._dist.init_distributed()
        self.rank = self._dist.get_rank()
        # one analysis worker per PROCESS (jax: process == host), not per
        # mesh device — the dataset walk is host work
        import jax
        self.num_workers = jax.process_count()
        self.worker_rank = jax.process_index()
        self.shared_fs = shared_fs
        self._an = DataAnalyzer(
            dataset, output_path, metric_names=metric_names,
            metric_functions=metric_functions, metric_types=metric_types,
            metric_dtypes=metric_dtypes, batch_size=batch_size,
            num_workers=self.num_workers, worker_id=self.worker_rank,
            sample_indices=sample_indices,
            custom_map_init=custom_map_init,
            custom_map_update=custom_map_update,
            custom_map_finalize=custom_map_finalize,
            custom_reduce=custom_reduce)

    def run_map_reduce(self):
        """Returns the merged dict on rank 0, None elsewhere."""
        local = self._an.run_map()
        if self.num_workers == 1:
            return self._an.run_reduce()
        if self.shared_fs:
            self._dist.barrier()          # all shard files visible
            out = (self._an.run_reduce() if self.worker_rank == 0 else None)
            self._dist.barrier()          # artifacts complete before use
            return out
        # object-gather transport: no common mount
        def wire(v):
            if v is None:          # empty ACCUM shard → sum identity
                return 0.0
            return np.asarray(v).tolist() if not np.isscalar(v) else v

        def local_custom_state():
            """This rank's custom_map_finalize output (written by run_map
            to a LOCAL json) — it must ride the send payload: without a
            shared mount, rank 0's reduce cannot see the file, and the
            reference's custom_reduce would silently fold rank-0 state
            only."""
            if self._an.custom_map_finalize is None:
                return None
            path = os.path.join(self._an.output_path,
                                f"custom_worker{self.worker_rank}.json")
            with open(path) as f:
                return json.load(f)

        if self.worker_rank != 0:
            payload = {k: wire(v) for k, v in local.items()}
            payload["__custom_state__"] = local_custom_state()
            self._dist.send_obj(payload, dst=0, tag=701)
            self._dist.barrier()
            return None
        shards = [local]
        for w in range(1, self.num_workers):
            shards.append(self._dist.recv_obj(src=w, tag=701))
        # materialize every worker's shard file locally, then reuse the
        # single-process reduce verbatim (identical artifacts)
        for w, shard in enumerate(shards):
            for name, mtype in zip(self._an.metric_names,
                                   self._an.metric_types):
                val = wire(shard[name])
                if mtype == ACCUM and np.isscalar(val):
                    val = [val]
                np.save(self._an._shard_file(name, w),
                        np.asarray(val, dtype=np.float64))
            if w > 0 and shard.get("__custom_state__") is not None:
                with open(os.path.join(self._an.output_path,
                                       f"custom_worker{w}.json"), "w") as f:
                    json.dump(shard["__custom_state__"], f)
        out = self._an.run_reduce()
        self._dist.barrier()
        return out
