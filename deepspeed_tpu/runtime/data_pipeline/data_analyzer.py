"""Offline data analysis — reference
``runtime/data_pipeline/data_sampling/data_analyzer.py:22`` (DataAnalyzer).

Map-reduce over a dataset: worker i analyzes its contiguous shard with
user-supplied metric functions, writes per-shard results, and ``merge``
produces the final per-sample metric array + sample buckets that
``DeepSpeedDataSampler`` consumes for curriculum learning.
"""

import json
import os

import numpy as np


class DataAnalyzer:
    def __init__(self, dataset, output_path, metric_names=None,
                 metric_functions=None, num_workers=1, worker_id=0,
                 batch_size=64):
        """``metric_functions``: list of callables sample → scalar."""
        self.dataset = dataset
        self.output_path = os.path.abspath(output_path)
        self.metric_names = metric_names or ["metric"]
        self.metric_functions = metric_functions or []
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size
        os.makedirs(self.output_path, exist_ok=True)

    def _shard_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return lo, min(n, lo + per)

    def _shard_file(self, name, worker_id=None):
        wid = self.worker_id if worker_id is None else worker_id
        return os.path.join(self.output_path,
                            f"{name}_worker{wid}.npy")

    def run_map(self):
        """Analyze this worker's shard; write {metric}_worker{i}.npy."""
        lo, hi = self._shard_range()
        results = {name: [] for name in self.metric_names}
        for i in range(lo, hi):
            sample = self.dataset[i]
            for name, fn in zip(self.metric_names, self.metric_functions):
                results[name].append(float(fn(sample)))
        for name in self.metric_names:
            np.save(self._shard_file(name),
                    np.asarray(results[name], dtype=np.float64))
        with open(os.path.join(self.output_path,
                               f"shard_worker{self.worker_id}.json"), "w") as f:
            json.dump({"lo": lo, "hi": hi}, f)
        return {k: np.asarray(v) for k, v in results.items()}

    def run_reduce(self):
        """Merge all worker shards → {metric}_values.npy + index maps."""
        merged = {}
        for name in self.metric_names:
            parts = []
            for w in range(self.num_workers):
                path = self._shard_file(name, w)
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"worker {w} shard missing for metric {name}: {path}")
                parts.append(np.load(path))
            values = np.concatenate(parts)
            np.save(os.path.join(self.output_path, f"{name}_values.npy"),
                    values)
            # sample index sorted by metric (easy→hard), the curriculum
            # consumption order (reference index_to_sample files)
            order = np.argsort(values, kind="stable")
            np.save(os.path.join(self.output_path,
                                 f"{name}_index_to_sample.npy"), order)
            merged[name] = values
        return merged

    def run(self):
        self.run_map()
        if self.worker_id == 0 and self.num_workers == 1:
            return self.run_reduce()
        return None

    @staticmethod
    def load_metric(output_path, metric_name="metric"):
        return np.load(os.path.join(output_path, f"{metric_name}_values.npy"))
