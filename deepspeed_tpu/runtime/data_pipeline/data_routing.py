"""Random layerwise token dropping (random-LTD) — reference
``runtime/data_pipeline/data_routing/basic_layer.py:113`` + the
``csrc/random_ltd`` token_sort/gather_scatter CUDA kernels.

Each wrapped layer processes only a random subset of tokens; dropped tokens
bypass the layer (identity) and are scattered back in position.  On TPU the
sort/gather/scatter kernels are ``jax.random.permutation`` +
``jnp.take_along_axis``/``.at[].set`` — XLA lowers these to efficient
dynamic-gather ops, no custom kernel needed (SURVEY.md §2.2 random-LTD row).

The token budget follows a linear schedule from ``start`` to ``seq_len``
over ``total_steps`` (reference scheduler.py).
"""

import jax
import jax.numpy as jnp
import numpy as np


def random_ltd_select(key, seq_len, keep):
    """Sorted indices of ``keep`` kept tokens and the complementary dropped
    set (reference token_sort.cu)."""
    perm = jax.random.permutation(key, seq_len)
    kept = jnp.sort(perm[:keep])
    dropped = jnp.sort(perm[keep:])
    return kept, dropped


def random_ltd_gather(x, indices):
    """Gather tokens along the sequence axis (axis=1; [B, S, H])."""
    return jnp.take(x, indices, axis=1)


def random_ltd_scatter(full, part, indices):
    """Scatter layer outputs back into the full sequence (gather_scatter.cu)."""
    return full.at[:, indices, :].set(part)


def apply_random_ltd(layer_fn, x, key, keep, mask=None):
    """Run ``layer_fn`` on a random ``keep``-token subset of ``x`` [B,S,H];
    dropped tokens pass through unchanged (reference basic_layer forward)."""
    seq_len = x.shape[1]
    kept, _ = random_ltd_select(key, seq_len, keep)
    sub = random_ltd_gather(x, kept)
    sub_mask = None
    if mask is not None:
        # slice attention mask rows+cols to the kept tokens
        # (slice_attn_masks.cu)
        sub_mask = jnp.take(jnp.take(mask, kept, axis=-1), kept, axis=-2)
    out = layer_fn(sub, sub_mask) if mask is not None else layer_fn(sub)
    return random_ltd_scatter(x, out, kept)


class RandomLTDScheduler:
    """Token-budget schedule (reference data_routing/scheduler.py):
    linear increase from ``start_token`` to ``seq_len`` over
    ``token_lr_steps``."""

    def __init__(self, seq_len, start_token, token_lr_steps,
                 layer_ids=None):
        self.seq_len = int(seq_len)
        self.start_token = int(start_token)
        self.token_lr_steps = int(token_lr_steps)
        self.layer_ids = layer_ids
        self.current_step = 0

    def get_current_seq(self, step=None):
        step = self.current_step if step is None else step
        if step >= self.token_lr_steps:
            return self.seq_len
        frac = step / max(1, self.token_lr_steps)
        keep = self.start_token + frac * (self.seq_len - self.start_token)
        # keep a multiple of 128 when possible (TPU lane alignment — dynamic
        # gather shapes must still tile onto the MXU)
        keep = int(keep)
        if keep >= 256:
            keep = (keep // 128) * 128
        return min(self.seq_len, max(1, keep))

    def update_seq(self, step=None):
        if step is not None:
            self.current_step = step
        else:
            self.current_step += 1
        return self.get_current_seq()

    def state_dict(self):
        return {"current_step": self.current_step}

    def load_state_dict(self, sd):
        self.current_step = sd["current_step"]
