"""Memory-mapped indexed dataset — reference
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` (627 LoC,
Megatron-style .bin/.idx pair).

Format (little-endian):

    {path}.idx : magic b'DSTPUIDX' | version u64 | dtype_code u8 |
                 n_sequences u64 | sizes u32[n] | pointers u64[n]
    {path}.bin : raw sample data back-to-back

Reading is ``np.memmap`` — no deserialization, page-cache backed, safe to
share across dataloader workers; this is the property the reference's mmap
implementation exists for.
"""

import os
import struct

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
    5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix):
    return prefix + ".bin"


def index_file_path(prefix):
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    def __init__(self, out_prefix, dtype=np.int32):
        self.prefix = out_prefix
        self.dtype = np.dtype(dtype)
        self._bin = open(data_file_path(out_prefix), "wb")
        self._sizes = []

    def add_item(self, array):
        arr = np.asarray(array, dtype=self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def add_document(self, array):
        self.add_item(array)

    def finalize(self):
        self._bin.close()
        sizes = np.asarray(self._sizes, dtype=np.uint32)
        pointers = np.zeros(len(sizes), dtype=np.uint64)
        if len(sizes) > 1:
            np.cumsum(sizes[:-1].astype(np.uint64) * self.dtype.itemsize,
                      out=pointers[1:])
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(sizes.tobytes())
            f.write(pointers.tobytes())
        return self.prefix


class MMapIndexedDataset:
    """Map-style dataset over the .bin/.idx pair."""

    def __init__(self, prefix):
        idx_path = index_file_path(prefix)
        with open(idx_path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{idx_path}: bad magic {magic!r}")
            version, = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"{idx_path}: unsupported version {version}")
            code, = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(_DTYPES[code])
            self._len, = struct.unpack("<Q", f.read(8))
            header = f.tell()
        self._sizes = np.memmap(idx_path, dtype=np.uint32, mode="r",
                                offset=header, shape=(self._len, ))
        self._pointers = np.memmap(idx_path, dtype=np.uint64, mode="r",
                                   offset=header + 4 * self._len,
                                   shape=(self._len, ))
        self._data = np.memmap(data_file_path(prefix), dtype=self.dtype,
                               mode="r")

    def __len__(self):
        return self._len

    @property
    def sizes(self):
        return self._sizes

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._len))]
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError(i)
        start = int(self._pointers[i]) // self.dtype.itemsize
        size = int(self._sizes[i])
        return np.asarray(self._data[start:start + size])

    def get(self, idx, offset=0, length=None):
        """Partial read (reference ``MMapIndexedDataset.get``)."""
        start = int(self._pointers[idx]) // self.dtype.itemsize + offset
        size = int(self._sizes[idx]) - offset
        if length is not None:
            size = min(size, length)
        return np.asarray(self._data[start:start + size])

    @staticmethod
    def exists(prefix):
        return (os.path.exists(index_file_path(prefix))
                and os.path.exists(data_file_path(prefix)))


def make_indexed_dataset(prefix, impl="mmap", skip_warmup=True):
    """Reference factory name."""
    return MMapIndexedDataset(prefix)
