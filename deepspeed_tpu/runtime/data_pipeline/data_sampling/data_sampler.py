"""Back-compat import path (reference ``deepspeed/runtime/data_pipeline/
data_sampling/data_sampler.py:36``)."""

from ..data_sampler import (DeepSpeedDataSampler,  # noqa: F401
                            DistributedSampler)
