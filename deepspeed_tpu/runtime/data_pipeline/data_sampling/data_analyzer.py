"""Back-compat import path (reference ``deepspeed/runtime/data_pipeline/
data_sampling/data_analyzer.py:22``)."""

from ..data_analyzer import *  # noqa: F401,F403
from ..data_analyzer import DataAnalyzer, DistributedDataAnalyzer  # noqa: F401
