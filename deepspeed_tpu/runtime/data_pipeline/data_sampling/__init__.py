"""Back-compat package path (reference ``deepspeed/runtime/data_pipeline/
data_sampling/``) — implementations live one level up (flat layout)."""

from ..data_analyzer import DataAnalyzer  # noqa: F401
from ..data_sampler import (DeepSpeedDataSampler,  # noqa: F401
                            DistributedSampler)
