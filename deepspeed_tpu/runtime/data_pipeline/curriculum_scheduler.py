"""Curriculum learning scheduler — reference
``runtime/data_pipeline/curriculum_scheduler.py:158`` (CurriculumScheduler).

Maps global step → difficulty (e.g. sequence length).  Schedule types match
the reference config schema: ``fixed_linear``, ``fixed_root``,
``fixed_discrete``, ``custom``.
"""

import math

CURRICULUM_LEARNING_MIN_DIFFICULTY = "min_difficulty"
CURRICULUM_LEARNING_MAX_DIFFICULTY = "max_difficulty"
CURRICULUM_LEARNING_SCHEDULE_TYPE = "schedule_type"
CURRICULUM_LEARNING_SCHEDULE_CONFIG = "schedule_config"


class CurriculumScheduler:
    def __init__(self, config):
        self.state = {}
        assert CURRICULUM_LEARNING_MIN_DIFFICULTY in config, \
            f"curriculum config must define {CURRICULUM_LEARNING_MIN_DIFFICULTY}"
        assert CURRICULUM_LEARNING_MAX_DIFFICULTY in config, \
            f"curriculum config must define {CURRICULUM_LEARNING_MAX_DIFFICULTY}"
        assert CURRICULUM_LEARNING_SCHEDULE_TYPE in config, \
            f"curriculum config must define {CURRICULUM_LEARNING_SCHEDULE_TYPE}"
        self.min_difficulty = config[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        self.max_difficulty = config[CURRICULUM_LEARNING_MAX_DIFFICULTY]
        self.schedule_type = config[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        self.schedule_config = config.get(CURRICULUM_LEARNING_SCHEDULE_CONFIG, {})
        self.current_difficulty = self.min_difficulty
        self.custom_get_difficulty = None
        self.first_step = True

        if self.schedule_type == "fixed_linear":
            assert "total_curriculum_step" in self.schedule_config
            assert "difficulty_step" in self.schedule_config
        elif self.schedule_type == "fixed_root":
            assert "total_curriculum_step" in self.schedule_config
            assert "difficulty_step" in self.schedule_config
            assert "root_degree" in self.schedule_config
        elif self.schedule_type == "fixed_discrete":
            assert "difficulty" in self.schedule_config
            assert "max_step" in self.schedule_config
            assert len(self.schedule_config["difficulty"]) == \
                len(self.schedule_config["max_step"]) + 1
        elif self.schedule_type == "custom":
            pass
        else:
            raise ValueError(f"unknown curriculum schedule {self.schedule_type}")

    def get_current_difficulty(self):
        return self.current_difficulty

    def set_current_difficulty(self, difficulty):
        self.current_difficulty = difficulty

    def set_custom_get_difficulty(self, fn):
        self.custom_get_difficulty = fn

    def _fixed_root_difficulty(self, global_steps, root_degree):
        sc = self.schedule_config
        frac = min(1.0, global_steps / sc["total_curriculum_step"])
        diff = self.min_difficulty + (self.max_difficulty -
                                      self.min_difficulty) * \
            (frac ** (1.0 / root_degree))
        step = sc["difficulty_step"]
        diff = int(diff / step) * step
        return min(self.max_difficulty, max(self.min_difficulty, diff))

    def get_difficulty(self, global_steps):
        if self.schedule_type == "fixed_linear":
            return self._fixed_root_difficulty(global_steps, 1.0)
        if self.schedule_type == "fixed_root":
            return self._fixed_root_difficulty(
                global_steps, self.schedule_config["root_degree"])
        if self.schedule_type == "fixed_discrete":
            sc = self.schedule_config
            for diff, max_step in zip(sc["difficulty"], sc["max_step"]):
                if global_steps <= max_step:
                    return diff
            return sc["difficulty"][-1]
        if self.schedule_type == "custom":
            assert self.custom_get_difficulty is not None, \
                "custom schedule requires set_custom_get_difficulty()"
            return self.custom_get_difficulty(global_steps)
        raise ValueError(self.schedule_type)

    def update_difficulty(self, global_steps):
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty

    def state_dict(self):
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd):
        self.current_difficulty = sd["current_difficulty"]
