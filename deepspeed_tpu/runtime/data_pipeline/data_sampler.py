"""Samplers — reference
``runtime/data_pipeline/data_sampling/data_sampler.py:36``
(DeepSpeedDataSampler) + torch ``DistributedSampler`` semantics that the
plain dataloader path uses.

``DeepSpeedDataSampler`` implements curriculum-aware sampling: given a
per-sample difficulty metric (from ``DataAnalyzer``), each global batch draws
only samples whose difficulty ≤ the CurriculumScheduler's current value,
consuming easier buckets first — reference behavior, re-expressed without
torch generators (numpy PCG with a seed+epoch stream, identical across ranks
so every rank derives the same global batch; the engine shards it over dp).
"""

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DistributedSampler:
    """Rank-sharded epoch permutation (torch DistributedSampler parity —
    used when one process per chip feeds its own dataloader)."""

    def __init__(self, dataset_len, num_replicas=1, rank=0, shuffle=True,
                 seed=0, drop_last=False):
        if isinstance(dataset_len, (list, tuple)) or hasattr(dataset_len, "__len__"):
            dataset_len = len(dataset_len)
        self.n = int(dataset_len)
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = self.n // num_replicas
        else:
            self.num_samples = (self.n + num_replicas - 1) // num_replicas
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        return self.num_samples

    def __iter__(self):
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.n).tolist()
        else:
            indices = list(range(self.n))
        if not self.drop_last:
            pad = self.total_size - len(indices)
            if pad > 0 and indices:
                # pad may exceed n (e.g. n=3, replicas=8): cycle the index
                # list however many times it takes so every rank gets
                # num_samples entries
                reps = -(-pad // len(indices)) + 1
                indices = (indices * reps)[:self.total_size]
        else:
            indices = indices[:self.total_size]
        return iter(indices[self.rank:self.total_size:self.num_replicas])


class DeepSpeedDataSampler:
    """Curriculum-learning batch sampler.

    Args:
      total_samples: dataset length
      metric_values: per-sample difficulty (np array, e.g. seqlen) — the
        output of ``DataAnalyzer``; None disables filtering (plain shuffle)
      curriculum_config: dict for CurriculumScheduler (or a scheduler)
      global_batch_size: samples per global batch
    """

    def __init__(self, total_samples, global_batch_size, metric_values=None,
                 curriculum_config=None, shuffle=True, seed=1234,
                 drop_last=True, gradient_accumulation_steps=1,
                 data_parallel_rank=0, data_parallel_size=1):
        self.total_samples = int(total_samples)
        self.global_batch_size = int(global_batch_size)
        self.metric_values = (np.asarray(metric_values)
                              if metric_values is not None else None)
        if isinstance(curriculum_config, CurriculumScheduler):
            self.curriculum_scheduler = curriculum_config
        elif curriculum_config:
            self.curriculum_scheduler = CurriculumScheduler(curriculum_config)
        else:
            self.curriculum_scheduler = None
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        # gas: the curriculum advances once per GLOBAL batch (optimizer
        # step), which is then yielded as gas micro index-lists — the
        # reference paces difficulty by global step the same way
        self.gas = max(1, int(gradient_accumulation_steps))
        # the per-rank slice must divide evenly into gas micro index-lists:
        # a remainder would be silently DROPPED from every global batch in
        # __iter__ (yet still counted as consumed), starving each step
        if self.global_batch_size % self.dp_size != 0:
            raise ValueError(
                f"global_batch_size ({self.global_batch_size}) is not "
                f"divisible by data_parallel_size ({self.dp_size})")
        if (self.global_batch_size // self.dp_size) % self.gas != 0:
            raise ValueError(
                f"per-rank batch ({self.global_batch_size} // "
                f"{self.dp_size} = {self.global_batch_size // self.dp_size})"
                f" is not divisible by gradient_accumulation_steps "
                f"({self.gas}) — the trailing samples of every global "
                f"batch would be dropped; adjust the batch-size trinity")
        self.batch_step = 0         # lifetime GLOBAL batches drawn
        self.epoch_batch_step = 0   # global batches drawn in current epoch
        self.consumed_samples = 0

    def __len__(self):
        # micro batches per epoch (what the dataloader counts)
        return (self.total_samples // self.global_batch_size) * self.gas

    def state_dict(self):
        return {"batch_step": self.batch_step,
                "epoch_batch_step": self.epoch_batch_step,
                "consumed_samples": self.consumed_samples,
                "curriculum": (self.curriculum_scheduler.state_dict()
                               if self.curriculum_scheduler else None)}

    def load_state_dict(self, sd):
        self.batch_step = sd["batch_step"]
        self.epoch_batch_step = sd.get("epoch_batch_step",
                                       sd["batch_step"] % max(
                                           1, self.total_samples //
                                           self.global_batch_size))
        self.consumed_samples = sd["consumed_samples"]
        if self.curriculum_scheduler and sd.get("curriculum"):
            self.curriculum_scheduler.load_state_dict(sd["curriculum"])

    def _draw(self, remaining, step):
        """The global batch at lifetime ``step`` given the consumed mask —
        PURE in (remaining, step), so a resumed sampler can replay the
        current epoch's draws and rebuild consumption exactly."""
        difficulty = None
        if self.curriculum_scheduler is not None:
            difficulty = self.curriculum_scheduler.update_difficulty(step)
        if self.metric_values is not None and difficulty is not None:
            pool = np.nonzero(remaining &
                              (self.metric_values <= difficulty))[0]
        else:
            pool = np.nonzero(remaining)[0]
        if len(pool) < self.global_batch_size:
            # curriculum floor thinner than a batch: top up with the
            # easiest unconsumed samples
            rest = np.nonzero(remaining)[0]
            rest = rest[np.argsort(self.metric_values[rest],
                                   kind="stable")] \
                if self.metric_values is not None else rest
            extra = rest[~np.isin(rest, pool)]
            pool = np.concatenate(
                [pool, extra[:self.global_batch_size - len(pool)]])
        rng = np.random.default_rng(self.seed + step)
        if self.shuffle:
            return rng.choice(pool, size=self.global_batch_size,
                              replace=False)
        return pool[:self.global_batch_size]

    def __iter__(self):
        """One epoch: every sample drawn at most once (no replacement across
        batches — reference sampler consumption semantics), with the
        curriculum filter applied to the not-yet-consumed pool.  Every rank
        derives the same stream (seeded by batch_step), so the global batch
        is consistent without communication."""
        if self.total_samples < self.global_batch_size:
            return  # not even one full batch (drop_last semantics)
        # self.batch_step is the *lifetime* counter (curriculum difficulty
        # and seeds advance across epochs; checkpoint-resumable).  A fresh
        # iterator mid-epoch (resume, or re-iter) REPLAYS the epoch's prior
        # draws — _draw is deterministic in step — so already-consumed
        # samples are never re-drawn.
        remaining = np.ones(self.total_samples, dtype=bool)
        for k in range(self.epoch_batch_step):
            step = self.batch_step - self.epoch_batch_step + k
            remaining[self._draw(remaining, step)] = False
        epoch_len = self.total_samples // self.global_batch_size
        while remaining.sum() >= self.global_batch_size and \
                self.epoch_batch_step < epoch_len:
            batch = self._draw(remaining, self.batch_step)
            remaining[batch] = False
            self.batch_step += 1
            self.epoch_batch_step += 1
            self.consumed_samples += self.global_batch_size
            # per-dp-rank slice (engine path passes dp_size=1 and shards
            # the assembled batch itself), then gas micro slices
            per_rank = self.global_batch_size // self.dp_size
            lo = self.dp_rank * per_rank
            mine = batch[lo:lo + per_rank]
            micro = per_rank // self.gas
            for g in range(self.gas):
                yield mine[g * micro:(g + 1) * micro].tolist()
        if self.epoch_batch_step >= epoch_len or \
                remaining.sum() < self.global_batch_size:
            self.epoch_batch_step = 0  # epoch complete; next iter is fresh
