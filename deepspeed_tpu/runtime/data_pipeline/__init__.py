"""Data efficiency pipeline (reference ``runtime/data_pipeline/``):
curriculum learning, efficient sampling, offline data analysis, mmap indexed
datasets, and random-LTD token dropping."""

from .curriculum_scheduler import CurriculumScheduler
from .data_analyzer import DataAnalyzer, DistributedDataAnalyzer
from .data_sampler import DeepSpeedDataSampler, DistributedSampler
from .data_routing import (RandomLTDScheduler, random_ltd_gather,
                           random_ltd_scatter, random_ltd_select)
from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder,
                              make_indexed_dataset)
