from .module import LayerSpec, PipelineModule, TiedLayerSpec
from .topology import (PipeDataParallelTopology, PipelineParallelGrid,
                       PipeModelDataParallelTopology, ProcessTopology)
from . import schedule
