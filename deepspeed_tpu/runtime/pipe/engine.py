"""PipelineEngine — TPU-native pipeline parallelism (L5).

The reference's ``PipelineEngine`` (``runtime/pipe/engine.py:61``) is an
instruction interpreter: a Python loop dispatches ``ForwardPass``/
``BackwardPass``/``SendActivation``… commands (built by ``TrainSchedule``,
``schedule.py:189``) against torch autograd + p2p NCCL sends.

On TPU, per-instruction dispatch fights the XLA compilation model (SURVEY.md
§7 hard part 3).  Instead the ENTIRE pipelined train step is ONE jitted SPMD
program:

* the transformer's uniform blocks are **stacked**: every leaf [L, ...] with
  the leading layer dim sharded over the "pp" mesh axis — each pp rank owns
  its stage's slice (the ``PipelineModule._partition_layers`` analog);
* inside ``shard_map`` over "pp", each tick runs the stage's layers with
  ``lax.scan`` and hands activations to the next stage with ``ppermute``
  (the ``p2p.send/recv`` analog — a neighbor ICI hop);
* the microbatch loop is a ``lax.scan`` over the ``M + pp - 1`` GPipe
  fill/drain ticks — compiled size flat in M (one tick body compiled once);
  losses accumulate on the last stage and are ``psum``-averaged;
* ``jax.grad`` through the whole program gives the backward schedule — XLA's
  scheduler overlaps the reverse ppermutes exactly where 1F1B would, and
  per-block ``remat`` bounds the live activation set (validated by the
  compiled-memory test in ``tests/unit/runtime/pipe/test_pipe_memory.py``
  and the figures in ``docs/parallelism.md``);
* ZeRO/bf16/fp16 compose unchanged: stacked block params get base spec
  P("pp") on the layer dim and the ZeRO axes shard the rest (same plan
  machinery as TP).

The instruction schedule (``schedule.py``) is retained as a parity
artifact: its 1F1B instruction streams are asserted against the reference's
invariants in tests, documenting the schedule the fused program's AD
reproduces implicitly.
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...utils import groups
from ...utils.logging import log_dist, logger
from ..engine import DeepSpeedEngine
from .module import PipelineModule, TiedLayerSpec


class PipelineEngine(DeepSpeedEngine):
    """Engine for ``PipelineModule`` models.  Use ``train_batch(data_iter)``
    (reference ``pipe/engine.py:338``) — forward/backward/step of the base
    class are superseded by the fused pipelined step."""

    def __init__(self, args=None, model=None, optimizer=None,
                 model_parameters=None, training_data=None, lr_scheduler=None,
                 collate_fn=None, config=None, mpu=None, tp_rules=None,
                 **kw):
        assert isinstance(model, PipelineModule)
        self.pipe_module = model
        # Identify the uniform block region (longest run of identical specs).
        self._analyze_layers(model)

        rules = dict(tp_rules or {})
        # stacked blocks: leading layer dim sharded over pp
        rules.setdefault("blocks/*", P("pp"))

        # Parse the config ONCE so the pre-super guards below handle every
        # form the base engine accepts (dict, JSON path, None,
        # DeepSpeedConfig) identically.
        from ..config import DeepSpeedConfig
        if not isinstance(config, DeepSpeedConfig):
            config = DeepSpeedConfig(config)
        # PLD guard must fire BEFORE the base engine's pld signature check
        # sees our internal apply fn and gives misleading advice
        pld_cfg = getattr(config, "pld_config", None)
        if pld_cfg is not None and pld_cfg.enabled:
            raise NotImplementedError(
                "progressive_layer_drop is not supported by the pipeline "
                "engine (its fused program builds its own apply path); "
                "disable it or use the base engine")
        # ZeRO++ quantized comm would be SILENTLY ignored here: the fused
        # pipeline builds its own step (qgZ's manual-dp micro and qwZ's
        # apply-fn wrapper never run).  Reject loudly instead.
        if config.zero_config.zero_quantized_gradients or \
                config.zero_config.zero_quantized_weights:
            raise NotImplementedError(
                "ZeRO++ quantized communication (zero_quantized_gradients/"
                "zero_quantized_weights) is not wired into the fused "
                "pipeline step — disable it or use the base engine "
                "(dp/ep/tp meshes)")
        super().__init__(args=args, model=self._build_apply(), optimizer=optimizer,
                         model_parameters=model_parameters,
                         training_data=training_data, lr_scheduler=lr_scheduler,
                         collate_fn=collate_fn, config=config, mpu=mpu,
                         tp_rules=rules, **kw)
        # Stage geometry: contiguous uniform split of the block run, padded to
        # equal per-stage counts so the stacked leaves split evenly over "pp".
        # Pad blocks carry a False entry in the valid mask and are skipped
        # (y = x) inside the stage scan — uneven layer counts run fine, at the
        # cost of the pad slots' dead compute (reference analog:
        # ``module.py:391 _partition_layers`` method="uniform"; with identical
        # block signatures "parameters" balancing reduces to uniform).
        from ..utils import partition_uniform
        pp = self.pp_world_size
        parts = partition_uniform(self.n_blocks, pp)
        counts = [parts[i + 1] - parts[i] for i in range(pp)]
        self.block_parts = parts
        self.blocks_per_stage = max(counts)
        self.n_blocks_padded = pp * self.blocks_per_stage
        # global padded slot p ← global layer index, or -1 for a pad slot
        slot_to_layer = []
        for s in range(pp):
            for i in range(self.blocks_per_stage):
                slot_to_layer.append(parts[s] + i if i < counts[s] else -1)
        self._slot_to_layer = np.asarray(slot_to_layer)
        self._block_valid = jnp.asarray(self._slot_to_layer >= 0)
        self._compiled_pipe = {}
        self._compiled_eval = {}
        self.micro_batches = self.gradient_accumulation_steps()

    # ----------------------------------------------------------- layer split
    def _analyze_layers(self, model):
        specs = model.specs
        sig = [(s.typename, s.module_args, tuple(sorted(s.module_kwargs.items())))
               for s in specs]
        # longest run of equal signatures; tied specs are NEVER block
        # candidates (a tied pair around a single block would otherwise
        # outrank the block run and land in the stacked region)
        eligible = [not isinstance(s, TiedLayerSpec) for s in specs]
        best_start, best_len = 0, 0
        i = 0
        while i < len(sig):
            j = i
            while j < len(sig) and sig[j] == sig[i] and \
                    eligible[j] == eligible[i]:
                j += 1
            if eligible[i] and j - i > best_len:
                best_start, best_len = i, j - i
            i = j
        if best_len < 1:
            raise ValueError("PipelineModule needs at least one layer")
        self.pre_specs = specs[:best_start]
        self.block_specs = specs[best_start:best_start + best_len]
        self.post_specs = specs[best_start + best_len:]
        self.n_blocks = best_len
        self.pre_layers = [s.build() for s in self.pre_specs]
        self.block_proto = self.block_specs[0].build()
        self.post_layers = [s.build() for s in self.post_specs]
        self.loss_fn = model.loss_fn
        # Tied layers (reference TiedLayerSpec + tied-grad allreduce,
        # ``pipe/module.py:77`` / ``engine.py _exec_reduce_tied_grads``):
        # occurrences SHARE one param subtree under params["tied"][key].
        # pre/post params are replicated over pp in the fused program, so
        # the existing psum of their gradients across stages IS the
        # reference's tied-gradient allreduce — no extra machinery.
        # ``forward_fn`` (the reuse-site forward, e.g. lambda m, x:
        # m.attend(x)) runs via flax's ``method=``.
        self.pre_tied = [s.key if isinstance(s, TiedLayerSpec) else None
                         for s in self.pre_specs]
        self.post_tied = [s.key if isinstance(s, TiedLayerSpec) else None
                          for s in self.post_specs]

    # ------------------------------------------------------------- model fns
    def _dp_row_spec(self, ndim):
        """PartitionSpec sharding dim 1 (the batch rows of [M, rows, ...])
        over dp.  ONE definition — the jit-level device_put and the
        shard_map in_specs must agree or GSPMD silently reshards."""
        spec = [None] * ndim
        spec[1] = groups.dp_axes()
        return P(*spec)

    def _check_rows(self, rows, what):
        dp = self.dp_world_size
        if rows % max(1, dp):
            raise ValueError(
                f"{what} has {rows} rows — not divisible by the "
                f"data-parallel degree {dp}; the fused pipeline shards "
                f"batch rows over dp (pad or drop the ragged tail)")

    def _layer_params(self, params, region, i, tied_key):
        """Param subtree for pre/post layer i — tied layers read the shared
        ``params["tied"][key]`` copy."""
        if tied_key is not None:
            return params["tied"][tied_key]
        return params[region][f"layer_{i}"]

    def _apply_region(self, params, region, x):
        """Apply the pre or post layer list — THE single definition of the
        non-block forward (tied lookup + per-spec forward_fn), shared by
        the plain apply, the fused pipeline, and eval."""
        layers, tied, specs = (
            (self.pre_layers, self.pre_tied, self.pre_specs)
            if region == "pre" else
            (self.post_layers, self.post_tied, self.post_specs))
        for i, layer in enumerate(layers):
            p = self._layer_params(params, region, i, tied[i])
            fwd = getattr(specs[i], "forward_fn", None)
            if fwd is not None:
                x = layer.apply({"params": p}, x, method=fwd)
            else:
                x = layer.apply({"params": p}, x)
        return x

    def _forward_full(self, params, x):
        """pre → blocks → post over the stacked params (the single source
        of the non-pipelined forward composition)."""
        x = self._apply_region(params, "pre", x)
        x = self._stage_scan(params["blocks"], self._block_valid, x)
        return self._apply_region(params, "post", x)

    def _build_apply(self):
        """A plain (non-pipelined) apply over the same params — used for
        pp=1 and for numerical-parity tests."""
        engine_self = self

        def apply_fn(params, *batch):
            *inputs, labels = batch
            x = inputs[0] if len(inputs) == 1 else tuple(inputs)
            x = engine_self._forward_full(params, x)
            if engine_self.loss_fn is not None:
                return engine_self.loss_fn(x, labels)
            return x

        return apply_fn

    def _stage_scan(self, blocks, valid, x):
        """Apply a stack of blocks [L, ...] with a validity mask [L] (pad
        slots pass activations through unchanged)."""
        proto = self.block_proto

        def body(x, args):
            lp, ok = args
            y = proto.apply({"params": lp}, x)
            return jnp.where(ok, y, x), None

        x, _ = jax.lax.scan(body, x, (blocks, valid))
        return x

    def initialize_parameters(self, rng_or_seed, *sample_batch):
        """Init pre/blocks/post params; blocks vmapped → leaves [L, ...]."""
        rng = (jax.random.PRNGKey(rng_or_seed)
               if isinstance(rng_or_seed, int) else rng_or_seed)
        *inputs, labels = sample_batch
        x = jnp.asarray(inputs[0]) if len(inputs) == 1 else tuple(
            map(jnp.asarray, inputs))
        pre, tied = {}, {}
        for i, layer in enumerate(self.pre_layers):
            rng, sub = jax.random.split(rng)
            key = self.pre_tied[i]
            fwd = getattr(self.pre_specs[i], "forward_fn", None)
            mkw = {"method": fwd} if fwd is not None else {}
            if key is not None:
                if key not in tied:
                    tied[key] = layer.init(sub, x, **mkw)["params"]
                x = layer.apply({"params": tied[key]}, x, **mkw)
            else:
                pre[f"layer_{i}"] = layer.init(sub, x, **mkw)["params"]
                x = layer.apply({"params": pre[f"layer_{i}"]}, x, **mkw)

        rng, sub = jax.random.split(rng)
        layer_rngs = jax.random.split(sub, self.n_blocks)
        # padded stack: slot p takes layer slot_to_layer[p]'s rng; pad slots
        # reuse rng 0 (their params are inert — masked out in _stage_scan)
        slot_rngs = layer_rngs[np.maximum(self._slot_to_layer, 0)]
        blocks = jax.vmap(
            lambda r: self.block_proto.init(r, x)["params"])(slot_rngs)
        x = self.block_proto.apply(
            {"params": jax.tree_util.tree_map(lambda p: p[0], blocks)}, x)

        post = {}
        for i, layer in enumerate(self.post_layers):
            rng, sub = jax.random.split(rng)
            key = self.post_tied[i]
            fwd = getattr(self.post_specs[i], "forward_fn", None)
            mkw = {"method": fwd} if fwd is not None else {}
            if key is not None:
                if key not in tied:
                    tied[key] = layer.init(sub, x, **mkw)["params"]
                x = layer.apply({"params": tied[key]}, x, **mkw)
            else:
                post[f"layer_{i}"] = layer.init(sub, x, **mkw)["params"]
                x = layer.apply({"params": post[f"layer_{i}"]}, x, **mkw)

        params = {"pre": pre, "blocks": blocks, "post": post}
        if tied:
            params["tied"] = tied
        shardings = self.plan.master_shardings(params)
        params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, s), params, shardings)
        self._install_parameters(params)
        if self.optimizer is None or self.opt_state is None:
            self._configure_optimizer(self.client_optimizer)
        return self.params

    # ---------------------------------------------------------- fused pipeline
    def _pipe_loss_fn(self, M, with_logits=False):
        """Build loss(params, batch_mb, labels_mb) running the full pipeline
        schedule for M microbatches under shard_map over the pp axis.

        TPU-native 1F1B answer (round-2 redesign; reference ``TrainSchedule``
        semantics, ``schedule.py``):

        * the microbatch loop is a ``lax.scan`` over ``M + pp - 1`` ticks —
          compile time and program size are FLAT in M (round 1 unrolled it:
          compile O(M·pp));
        * each tick embeds only its own microbatch (dynamic slice), so no
          stage materializes all M embeddings;
        * the tick body is wrapped in ``jax.checkpoint``: the backward pass
          recomputes block internals per tick, so activation residency is the
          per-tick boundary state [mb, ...] × ticks plus ONE tick's remat
          working set — the same O(boundary·M) + O(stage) profile 1F1B
          targets (vs GPipe's O(M · full stage activations));
        * backward ticks are generated by AD through the scan; XLA schedules
          the reverse ppermutes back-to-back with the recompute, which is
          where 1F1B's overlap comes from in the instruction rendering.
        """
        pp = self.pp_world_size
        mesh = self.mesh
        engine_self = self
        loss_fn = self.loss_fn
        dp_axes = groups.dp_axes()

        def pre_apply(params, x):
            return engine_self._apply_region(params, "pre", x)

        def post_apply(params, x):
            return engine_self._apply_region(params, "post", x)

        def pipe(params, valid_local, batch_mb, labels_mb):
            """Runs inside shard_map over ("pp",).  blocks leaves are the
            LOCAL stage slice [blocks_per_stage, ...] with validity mask
            valid_local; pre/post replicated."""
            stage = jax.lax.axis_index("pp")
            perm = [(i, (i + 1) % pp) for i in range(pp)]

            # boundary-state geometry from one microbatch (trace-only)
            h_shape = jax.eval_shape(pre_apply, params, batch_mb[0])

            def tick_body(carry, t):
                state, total_loss, logit_acc = carry
                # stage 0 embeds microbatch t; every other stage — and stage
                # 0's drain ticks (t >= M) — takes the lax.cond false branch
                # and never executes the embedding.  shard_map is manual
                # SPMD, so the conditional is a genuine per-rank branch: the
                # embed/head FLOPs run only on their owning stage, matching
                # the reference's 1F1B ownership (first stage loads micros,
                # ``pipe/engine.py:882``; last stage computes loss, ``:583``).
                def feed_branch(state):
                    b = jax.lax.dynamic_index_in_dim(
                        batch_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                    return pre_apply(params, b)

                x = jax.lax.cond(
                    jnp.logical_and(stage == 0, t < M),
                    feed_branch, lambda state: state, state)
                y = engine_self._stage_scan(params["blocks"], valid_local, x)
                # last stage finishes microbatch t - (pp - 1)
                m_idx = t - (pp - 1)
                m_ok = jnp.logical_and(m_idx >= 0, m_idx < M)
                on_last = jnp.logical_and(stage == pp - 1, m_ok)

                def head_branch(y):
                    lbl = jax.lax.dynamic_index_in_dim(
                        labels_mb, jnp.clip(m_idx, 0, M - 1), 0,
                        keepdims=False)
                    out = post_apply(params, y)
                    l = (loss_fn(out, lbl).astype(jnp.float32)
                         if loss_fn is not None else jnp.zeros((), jnp.float32))
                    if logit_acc is not None:
                        return l, out.astype(logit_acc.dtype)
                    return l

                def skip_branch(y):
                    z = jnp.zeros((), jnp.float32)
                    if logit_acc is not None:
                        out_sd = jax.eval_shape(post_apply, params, y)
                        return z, jnp.zeros(out_sd.shape, logit_acc.dtype)
                    return z

                head_out = jax.lax.cond(on_last, head_branch, skip_branch, y)
                if logit_acc is not None:
                    l, out = head_out
                    logit_acc = jax.lax.dynamic_update_index_in_dim(
                        logit_acc, out, jnp.clip(m_idx, 0, M - 1), 0)
                else:
                    l = head_out
                total_loss = total_loss + l
                # neighbor hand-off (ring: last stage's output wraps to stage
                # 0 where the feed overwrites it)
                state = jax.lax.ppermute(y, "pp", perm)
                return (state, total_loss, logit_acc), None

            state0 = jnp.zeros(h_shape.shape, h_shape.dtype)
            if with_logits:
                out_shape = jax.eval_shape(
                    lambda p, h: post_apply(p, h), params, state0)
                logit_acc0 = jnp.zeros((M, ) + out_shape.shape,
                                       out_shape.dtype)
            else:
                logit_acc0 = None
            (state, total_loss, logit_acc), _ = jax.lax.scan(
                jax.checkpoint(tick_body), (state0, jnp.zeros((), jnp.float32),
                                            logit_acc0),
                jnp.arange(M + pp - 1))
            # loss/logits live on the last stage only → psum over pp
            # broadcasts them; each dp group saw only ITS batch-row shard,
            # so the scalar loss additionally pmeans over the dp axes.
            # CONTRACT (same as the reference pipeline's dp loss allreduce,
            # _aggregate_total_loss): loss_fn is a uniform per-row mean —
            # sum-reductions or weighted means are equal-weight averaged
            # per dp shard, not globally re-weighted.
            loss_out = jax.lax.psum(total_loss, "pp") / M
            loss_out = jax.lax.pmean(loss_out, dp_axes)
            if with_logits:
                return loss_out, jax.lax.psum(logit_acc, "pp")
            return loss_out

        def loss(params, batch_mb, labels_mb):
            # PARTIAL-manual region: manual over pp (ppermute, stage
            # branching) and the dp axes (batch-row sharding + loss pmean);
            # tp/sp stay AUTO so GSPMD keeps the ZeRO/TP sharding of the
            # non-layer param dims live INSIDE the region (a full-manual
            # region all-gathered tp-sharded weights at the boundary —
            # same dead-compute class as the batch replication fixed
            # alongside).  Batch rows (dim 1 of [M, rows, ...]) are
            # sharded over dp: every dp group pipelines only ITS shard.
            param_specs = {
                "pre": jax.tree_util.tree_map(lambda _: P(), params["pre"]),
                "blocks": jax.tree_util.tree_map(lambda _: P("pp"),
                                                 params["blocks"]),
                "post": jax.tree_util.tree_map(lambda _: P(), params["post"]),
            }
            if "tied" in params:  # shared copies: replicated like pre/post
                param_specs["tied"] = jax.tree_util.tree_map(
                    lambda _: P(), params["tied"])
            bspec = engine_self._dp_row_spec(batch_mb.ndim)
            lspec = engine_self._dp_row_spec(labels_mb.ndim)
            if with_logits:
                # logits [M, rows_local, ...]: rows sharded over dp,
                # trailing dims unsharded (unspecified)
                out_specs = (P(), P(None, dp_axes))
            else:
                out_specs = P()
            manual = frozenset({"pp", *dp_axes})
            return jax.shard_map(
                pipe, mesh=mesh,
                in_specs=(param_specs, P("pp"), bspec, lspec),
                out_specs=out_specs, check_vma=False,
                axis_names=manual)(
                    params, self._block_valid, batch_mb, labels_mb)

        return loss

    def _get_compiled_pipe(self, batch_mb, labels_mb):
        key = (tuple(batch_mb.shape), str(batch_mb.dtype),
               tuple(labels_mb.shape))
        if key not in self._compiled_pipe:
            M = int(batch_mb.shape[0])
            loss_fn = (self._pipe_loss_fn(M) if self.pp_world_size > 1 else
                       self._plain_gas_loss_fn())

            def step_fn(params, master, opt_state, scale_state, batch_mb,
                        labels_mb):
                target = master if master is not None else params

                def scaled(p):
                    cp = jax.tree_util.tree_map(
                        lambda t: t.astype(self.compute_dtype), p)
                    for transform in self._param_transforms:
                        cp = transform(cp)
                    return loss_fn(cp, batch_mb, labels_mb) * scale_state.scale

                loss_val, grads = jax.value_and_grad(
                    lambda p: scaled(p))(target)
                grads = jax.tree_util.tree_map(
                    lambda g, s: jax.lax.with_sharding_constraint(
                        g.astype(jnp.float32), s),
                    grads, self.plan.master_shardings(grads))
                inv = 1.0 / scale_state.scale
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
                from ..loss_scaler import has_overflow
                from ..utils import clip_grads_by_global_norm, global_grad_norm
                overflow = (has_overflow(grads) if self._config.fp16_enabled
                            else jnp.zeros((), jnp.bool_))
                gnorm = global_grad_norm(grads)
                gc = self._config.gradient_clipping
                if gc and gc > 0:
                    grads, _ = clip_grads_by_global_norm(grads, gc, norm=gnorm)
                updates, new_opt = self._grad_transform.update(
                    grads, opt_state, target)
                new_target = jax.tree_util.tree_map(
                    lambda p, u: (p.astype(jnp.float32) +
                                  u.astype(jnp.float32)).astype(p.dtype),
                    target, updates)
                sel = lambda new, old: jax.tree_util.tree_map(
                    lambda n, o: jnp.where(overflow, o, n), new, old)
                new_target = sel(new_target, target)
                new_opt = sel(new_opt, opt_state)
                if master is not None:
                    new_master = new_target
                    new_params = jax.tree_util.tree_map(
                        lambda m, s: jax.lax.with_sharding_constraint(
                            m.astype(self.compute_dtype), s),
                        new_master, self.plan.param_shardings(new_master))
                else:
                    new_master, new_params = None, new_target
                new_scale = self.loss_scaler.update(scale_state, overflow)
                return (new_params, new_master, new_opt, new_scale,
                        loss_val / scale_state.scale, overflow)

            self._compiled_pipe[key] = jax.jit(step_fn,
                                               donate_argnums=(0, 1, 2))
        return self._compiled_pipe[key]

    def invalidate_compiled(self):
        super().invalidate_compiled()
        self._compiled_pipe = {}
        self._compiled_eval = {}

    def _plain_gas_loss_fn(self):
        """pp=1 fallback: mean loss over the microbatch dim (vmap+mean).
        (param transforms are composed once in the step fn's ``scaled`` —
        not here — so the pp>1 path gets them identically)"""
        apply_fn = self._apply_fn

        def loss(params, batch_mb, labels_mb):
            def one(b, l):
                return apply_fn(params, b, l)

            losses = jax.vmap(one)(batch_mb, labels_mb)
            return jnp.mean(losses.astype(jnp.float32))

        return loss

    def _plain_logits_fn(self):
        """pp=1 eval with logits (reference ``eval_batch`` returns outputs
        regardless of pp degree — round-2 raised here)."""
        engine_self = self

        def one(params, b, l):
            x = engine_self._forward_full(params, b)
            loss = (engine_self.loss_fn(x, l).astype(jnp.float32)
                    if engine_self.loss_fn is not None
                    else jnp.zeros((), jnp.float32))
            return loss, x

        def fn(params, batch_mb, labels_mb):
            losses, logits = jax.vmap(partial(one, params))(batch_mb,
                                                            labels_mb)
            return jnp.mean(losses), logits

        return fn

    # -------------------------------------------------------------- public API
    def train_batch(self, data_iter=None):
        """One full training step over gas microbatches (reference
        ``train_batch`` pipe/engine.py:338).

        Loss aggregation contract (matches the reference's
        ``_aggregate_total_loss``): the module's ``loss_fn`` must be a
        uniform per-row-mean loss — the fused program psum-averages it over
        pp/M and pmean-averages over dp with EQUAL weights, so a sum-reduced
        or sample-weighted loss_fn returns a mis-weighted global loss."""
        self._check_params()
        if data_iter is None:
            data_iter = iter(self.training_dataloader)
        M = self.micro_batches
        xs, ys = [], []
        for _ in range(M):
            batch = next(data_iter)
            x, y = batch[0], batch[1]
            xs.append(np.asarray(x))
            ys.append(np.asarray(y))
        batch_mb = jnp.asarray(np.stack(xs))   # [M, mb*dp, ...]
        labels_mb = jnp.asarray(np.stack(ys))
        self._check_rows(batch_mb.shape[1], "train_batch microbatch")

        # shard microbatch data over dp on dim 1 (same helper as the fused
        # program's in_specs — the layouts must agree)
        batch_mb = jax.device_put(batch_mb, NamedSharding(
            self.mesh, self._dp_row_spec(batch_mb.ndim)))
        labels_mb = jax.device_put(labels_mb, NamedSharding(
            self.mesh, self._dp_row_spec(labels_mb.ndim)))

        self.tput_timer.start()
        self._ensure_state_resident()  # NVMe offload: swap state back in
        step_fn = self._get_compiled_pipe(batch_mb, labels_mb)
        (self.params, self.master, self.opt_state, self.scale_state, loss,
         overflow) = step_fn(self.params, self.master, self.opt_state,
                             self.scale_state, batch_mb, labels_mb)
        if self._nvme_swapper is not None:
            self._nvme_swap_out()
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        if self._config.fp16_enabled:
            # no per-batch host sync: accumulate on device, drained lazily
            # by the skipped_steps property / steps_per_print report
            ov = overflow.astype(jnp.int32)
            self._overflow_acc = (ov if self._overflow_acc is None
                                  else self._overflow_acc + ov)
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.step()
            self._scheduler_reclaims_lr()
        self._last_loss = loss
        self._report_step_metrics(None)
        self.tput_timer.stop(global_step=True)
        return loss

    def eval_batch(self, data_iter, return_logits=False):
        """Forward-only THROUGH the pipelined program (reference
        ``eval_batch`` pipe/engine.py:441; round 1 silently bypassed the
        pipeline — round 2 runs the same fused schedule, grad-free).
        Same loss contract as :meth:`train_batch`: uniform per-row-mean
        ``loss_fn`` (equal-weight pp/M/dp averaging)."""
        self._check_params()
        batch = next(data_iter)
        x, y = np.asarray(batch[0]), np.asarray(batch[1])
        if self.pp_world_size > 1:
            self._check_rows(x.shape[0], "eval_batch batch")
        batch_mb = jnp.asarray(x)[None]
        labels_mb = jnp.asarray(y)[None]
        key = (tuple(batch_mb.shape), str(batch_mb.dtype), bool(return_logits))
        if key not in self._compiled_eval:
            if self.pp_world_size > 1:
                fn = self._pipe_loss_fn(1, with_logits=return_logits)
            elif return_logits:
                fn = self._plain_logits_fn()
            else:
                fn = self._plain_gas_loss_fn()

            def eval_fn(params, batch_mb, labels_mb):
                cp = jax.tree_util.tree_map(
                    lambda t: t.astype(self.compute_dtype), params)
                for transform in self._param_transforms:
                    cp = transform(cp)
                return fn(cp, batch_mb, labels_mb)

            self._compiled_eval[key] = jax.jit(eval_fn)
        out = self._compiled_eval[key](self.params, batch_mb, labels_mb)
        if return_logits:
            loss, logits = out
            return loss, logits[0]
        return out

    # forward/backward/step are not the PP interface (reference raises too)
    def forward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch/eval_batch "
                           "(reference pipe/engine.py also disables forward())")

    def backward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch")

    def step(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch")