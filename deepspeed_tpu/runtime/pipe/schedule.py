"""Pipeline schedules — analog of reference ``runtime/pipe/schedule.py``
(PipeSchedule ABC ``:11``, InferenceSchedule ``:135``, TrainSchedule ``:189``
1F1B, DataParallelSchedule ``:301``; instruction taxonomy ``:327-480``).

This file is deliberately framework-agnostic data (as the reference's is): a
schedule yields lists of instructions per step; the engine decides how to
execute them (eagerly with jitted per-instruction fns, or fused into a single
scanned program for the TPU fast path)."""


class PipeSchedule:
    """Base: yields step_cmds lists; each cmd is a PipeInstruction."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    def steps(self):
        raise NotImplementedError

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Reference ``:135``: forward-only streaming."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds = []
            if 0 <= prev_micro_batch_id < self.micro_batches:
                buf = prev_micro_batch_id % self.num_pipe_buffers()
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            if 0 <= micro_batch_id < self.micro_batches:
                buf = micro_batch_id % self.num_pipe_buffers()
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
            prev_micro_batch_id = micro_batch_id
            yield cmds

    def num_pipe_buffers(self):
        return min(2, self.micro_batches)


class TrainSchedule(PipeSchedule):
    """Reference ``:189``: 1F1B — warmup fwds, steady 1F1B, drain bwds."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            if self._valid_micro_batch(prev_micro_batch_id):
                prev_buffer = self._buffer_idx(prev_micro_batch_id)
            if self._valid_micro_batch(micro_batch_id):
                curr_buffer = self._buffer_idx(micro_batch_id)

            cmds = []
            # Exchange activations
            if is_forward:
                if self._valid_micro_batch(prev_micro_batch_id) and \
                        self._valid_stage(self.prev_stage):
                    cmds.append(SendGrad(prev_buffer))
                if self._valid_micro_batch(micro_batch_id) and \
                        self._valid_stage(self.prev_stage):
                    cmds.append(RecvActivation(curr_buffer))
            else:
                if self._valid_micro_batch(micro_batch_id) and \
                        self._valid_stage(self.next_stage):
                    cmds.append(RecvGrad(curr_buffer))
                if self._valid_micro_batch(prev_micro_batch_id) and \
                        self._valid_stage(self.next_stage):
                    cmds.append(SendActivation(prev_buffer))

            # Compute
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(curr_buffer))
                    cmds.append(ForwardPass(curr_buffer))
                else:
                    cmds.append(BackwardPass(curr_buffer))

            # Model step at the end of the batch
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def _step_to_micro_batch(self, step_id):
        """Reference ``:258``: map step index → (micro_batch, is_forward)."""
        if _is_even(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._even_step_forward_id(step_id)
            is_forward = True
        elif _is_odd(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._odd_step_forward_id(step_id)
            is_forward = True
        elif _is_even(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._even_step_backward_id(step_id)
            is_forward = False
        elif _is_odd(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._odd_step_backward_id(step_id)
            is_forward = False
        else:
            assert False
        return micro_batch_id, is_forward

    def _even_step_forward_id(self, step_id):
        base = step_id // 2
        return int(base - self.stage_id // 2)

    def _odd_step_forward_id(self, step_id):
        base = (step_id - 1) // 2
        return int(base - self.stage_id // 2)

    def _even_step_backward_id(self, step_id):
        base = step_id // 2
        return int(base - self.stages + (self.stage_id + 1) // 2)

    def _odd_step_backward_id(self, step_id):
        base = ((step_id - 1) // 2) - self.stages + 1
        return int(base + self.stage_id // 2)

    def num_pipe_buffers(self):
        """Reference: stages - stage_id buffers needed, ≥2."""
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)


class DataParallelSchedule(PipeSchedule):
    """Reference ``:301``: degenerate single-stage schedule."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


class PipeInstruction:
    """Reference ``:327``."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if self.kwargs:
            kw = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
            return f"{self.name}({kw})"
        return self.name

    def __eq__(self, other):
        return (self.__class__ == other.__class__ and self.kwargs == other.kwargs)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0
