"""Pipeline schedules — instruction-stream view of pipeline execution.

Role parity with reference ``runtime/pipe/schedule.py`` (``PipeSchedule``,
``TrainSchedule``/1F1B, ``InferenceSchedule``, the ``PipeInstruction``
taxonomy), but derived differently: instead of closed-form step↔microbatch
index formulas, each stage's compute order is written down from the 1F1B
invariants and a small dependency-driven clock simulation aligns the
communication ticks across stages.  The result is a global schedule where
every Send is emitted on the producer in the same tick as the consumer's
Recv, which is what a synchronous pairwise executor needs.

On TPU the hot path does NOT interpret these streams — the fused shard_map
program in ``pipe/engine.py`` is the executor, and XLA's scheduler overlaps
the ppermutes.  The streams exist for parity tests, debugging, and as the
reference-semantics oracle for the fused program.
"""


# --------------------------------------------------------------------------
# Instruction taxonomy (names are the reference's public vocabulary)
# --------------------------------------------------------------------------
class PipeInstruction:

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})" if args else self.name

    def __eq__(self, other):
        return (self.__class__ == other.__class__
                and self.kwargs == other.kwargs)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):

    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


# --------------------------------------------------------------------------
# Per-stage compute orders
# --------------------------------------------------------------------------
def one_f1b_order(micro_batches, stages, stage_id):
    """The 1F1B compute order for one stage, from its defining invariants:

    * warmup: stage s starts with ``stages - 1 - s`` forwards so the last
      stage can begin alternating immediately (bounded in-flight work);
    * steady state: strictly alternate forward/backward;
    * drain: the backwards that warmup deferred.

    Returns a list of ("F"|"B", microbatch_id).
    """
    M = micro_batches
    warmup = min(stages - 1 - stage_id, M)
    order = [("F", m) for m in range(warmup)]
    for i in range(M - warmup):
        order.append(("F", warmup + i))
        order.append(("B", i))
    for m in range(M - warmup, M):
        order.append(("B", m))
    return order


def forward_order(micro_batches, stages, stage_id):
    """Forward-only streaming order (inference)."""
    return [("F", m) for m in range(micro_batches)]


def _simulate(orders, stages):
    """Greedy clock simulation of per-stage compute orders under the data
    dependencies F(m)@s ← F(m)@s-1 and B(m)@s ← B(m)@s+1 (+ F(m)@s).

    Returns ``done``: {(kind, m, stage): tick}, and the tick count.  Each
    stage runs at most one compute per tick, at the earliest tick whose
    dependencies completed on a *strictly earlier* tick.
    """
    cursor = [0] * stages           # next event index per stage
    done = {}
    tick = 0
    while any(cursor[s] < len(orders[s]) for s in range(stages)):
        progressed = False
        scheduled = []
        for s in range(stages):
            if cursor[s] >= len(orders[s]):
                continue
            kind, m = orders[s][cursor[s]]
            if kind == "F":
                dep = None if s == 0 else ("F", m, s - 1)
            else:
                dep = None if s == stages - 1 else ("B", m, s + 1)
            dep_ok = dep is None or done.get(dep, tick) < tick
            own_ok = kind != "B" or done.get(("F", m, s), tick) < tick
            if dep_ok and own_ok:
                scheduled.append((kind, m, s))
        for kind, m, s in scheduled:
            done[(kind, m, s)] = tick
            cursor[s] += 1
            progressed = True
        tick += 1
        if not progressed and tick > 4 * sum(map(len, orders)) + 8:
            raise RuntimeError("pipeline schedule deadlock (bug)")
    return done, tick


# --------------------------------------------------------------------------
# Schedules
# --------------------------------------------------------------------------
class PipeSchedule:
    """Iterable of per-tick instruction lists for ``stage_id``."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id

    # -- geometry helpers
    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self):
        """In-flight microbatches at this stage: a microbatch's buffer is
        live from its forward until its backward, and 1F1B keeps at most
        ``stages - stage_id`` in flight (≥2 for double-buffered comm)."""
        return max(2, min(self.stages - self.stage_id, self.micro_batches))

    def _buffer(self, m):
        return m % self.num_pipe_buffers()

    # -- stream construction
    def _orders(self):
        raise NotImplementedError

    def _tail(self):
        """Instructions appended after the final compute tick."""
        return []

    def steps(self):
        """Yield the per-tick instruction lists for this stage."""
        make_order = self._orders()
        orders = [make_order(s) for s in range(self.stages)]
        done, ticks = _simulate(orders, self.stages)
        by_tick = {}
        for (kind, m, s), t in done.items():
            by_tick.setdefault(t, []).append((kind, m, s))

        for t in range(ticks):
            cmds = []
            events = sorted(by_tick.get(t, []))
            mine = [(k, m) for (k, m, s) in events if s == self.stage_id]
            # comm first: a Recv on this stage pairs with the producer's Send
            # in the SAME tick (synchronous pairwise exchange)
            for kind, m, s in events:
                if kind == "F" and s == self.stage_id and s > 0:
                    cmds.append(RecvActivation(self._buffer(m)))
                if kind == "F" and s == self.stage_id + 1:
                    cmds.append(SendActivation(self._buffer(m)))
                if kind == "B" and s == self.stage_id and s < self.stages - 1:
                    cmds.append(RecvGrad(self._buffer(m)))
                if kind == "B" and s == self.stage_id - 1:
                    cmds.append(SendGrad(self._buffer(m)))
            for kind, m in mine:
                if kind == "F":
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(self._buffer(m)))
                    cmds.append(ForwardPass(self._buffer(m)))
                else:
                    cmds.append(BackwardPass(self._buffer(m)))
            if t == ticks - 1:
                cmds.extend(self._tail())
            yield cmds

    def __iter__(self):
        return iter(list(self.steps()))


class TrainSchedule(PipeSchedule):
    """1F1B training schedule."""

    def _orders(self):
        return lambda s: one_f1b_order(self.micro_batches, self.stages, s)

    def _tail(self):
        return [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]


class InferenceSchedule(PipeSchedule):
    """Forward-only streaming."""

    def _orders(self):
        return lambda s: forward_order(self.micro_batches, self.stages, s)

    def num_pipe_buffers(self):
        return min(2, self.micro_batches)


class DataParallelSchedule(PipeSchedule):
    """Single-stage degenerate schedule (gradient accumulation only)."""

    def steps(self):
        for m in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if m == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1
