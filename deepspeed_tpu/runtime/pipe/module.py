"""PipelineModule — analog of reference ``runtime/pipe/module.py``
(LayerSpec ``:30``, TiedLayerSpec ``:77``, PipelineModule ``:86``,
``_partition_layers`` ``:391`` with methods
uniform|parameters|profile|type:regex).

TPU-native layer contract: each layer is either
  * a flax ``nn.Module`` (init/apply), or
  * a pair of callables via ``LayerSpec(init_fn=..., apply_fn=...)``, or
  * a plain callable ``f(params, x) -> x`` plus an init.

The PipelineEngine executes stages either with the instruction schedule
(reference-parity path) or as a single jitted scan over microbatches with
``ppermute`` stage hand-off (TPU fast path) — see ``pipe/engine.py``.
"""

import re

import numpy as np

import jax

from ...utils.logging import logger
from ..utils import partition_balanced, partition_uniform


class LayerSpec:
    """Deferred layer constructor (reference ``module.py:30``): stores the
    callable + args so stages only materialize their own layers."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    @property
    def name(self):
        return getattr(self.typename, "__name__", str(self.typename))

    def __repr__(self):
        return f"LayerSpec({self.name})"


class TiedLayerSpec(LayerSpec):
    """Reference ``:77``: layers sharing parameters across stages (e.g. tied
    embeddings).  ``key`` identifies the tie group; ``forward_fn`` lets the
    reuse site run a different function over the shared params."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="weight", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    """Layer-list model for pipeline execution (reference ``:86``)."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seed_layers=False, base_seed=1234,
                 partition_method="parameters",
                 activation_checkpoint_interval=0):
        self.specs = []
        for layer in layers:
            if isinstance(layer, LayerSpec):
                self.specs.append(layer)
            elif callable(layer) and not isinstance(layer, type):
                # plain callable: stateless layer
                self.specs.append(LayerSpec(lambda f=layer: f))
            else:
                self.specs.append(LayerSpec(layer))
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.num_stages = num_stages
        self.topology = topology
        self._layer_params_cache = None
        # stage boundaries are computed when the engine knows the pp degree
        self.parts = None

    def __len__(self):
        return len(self.specs)

    # --------------------------------------------------------------- partition
    def _count_layer_params(self):
        """Parameter counts per layer (for method="parameters"), measured via
        eval_shape on built layers (no device memory)."""
        counts = []
        for spec in self.specs:
            layer = spec.build()
            n = 0
            if hasattr(layer, "param_shapes"):
                n = sum(int(np.prod(s)) for s in layer.param_shapes())
            elif hasattr(layer, "init"):
                # flax module: requires example input; fall back to 1
                n = 1
            counts.append(max(1, n))
        return counts

    def _profile_layer_latencies(self, example_input, iters=3):
        """Per-layer forward latency (for method="profile", reference
        ``module.py:391`` 'profile'): build, init, and time each layer on
        the example input, chaining each layer's output into the next so
        shapes evolve as they would in the real stack.  A layer that can't
        be timed poisons every downstream shape, so the whole profile falls
        back to parameter-count weights rather than returning skewed data.
        """
        import jax
        import jax.numpy as jnp
        from ...profiling.flops_profiler.profiler import FlopsProfiler
        prof = FlopsProfiler()
        x = jnp.asarray(example_input)
        lats = []
        for spec in self.specs:
            layer = spec.build()
            try:
                if hasattr(layer, "init"):
                    variables = layer.init(jax.random.PRNGKey(0), x)
                    fn, args = (lambda v, t, l=layer: l.apply(v, t)), \
                        (variables, x)
                else:
                    fn, args = (lambda t, l=layer: l(t)), (x, )
                lats.append(max(prof.measure_latency(fn, *args, iters=iters),
                                1e-7))
                x = jax.jit(fn)(*args)  # jit-cache hit, not an eager re-run
            except Exception as e:
                logger.warning(
                    f"profile partition: layer {spec.name} not timeable "
                    f"({type(e).__name__}: {e}); downstream shapes unknown "
                    "— falling back to parameter-count weights")
                return self._count_layer_params()
        # partition_balanced binary-searches integer limits — scale
        # latencies to integers (~3 significant digits)
        lo = min(lats)
        return [max(1, round(v / lo * 100)) for v in lats]

    def partition_layers(self, num_stages, method=None, example_input=None):
        """Reference ``_partition_layers`` ``:391``: returns stage boundary
        list ``parts`` of len num_stages+1.  ``method="profile"`` requires
        ``example_input`` (a sample layer-0 input) to time the layers."""
        method = (method or self.partition_method).lower()
        num_layers = len(self.specs)
        if method == "uniform":
            self.parts = partition_uniform(num_layers, num_stages)
        elif method == "parameters":
            weights = self._count_layer_params()
            self.parts = partition_balanced(weights, num_stages)
        elif method == "profile":
            if example_input is None:
                raise ValueError(
                    "partition_method='profile' needs example_input= "
                    "(a sample input for the first layer)")
            weights = self._profile_layer_latencies(example_input)
            self.parts = partition_balanced(weights, num_stages)
        elif method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            binary = [1 if re.search(pattern, s.name, re.IGNORECASE) else 0
                      for s in self.specs]
            self.parts = partition_balanced([b or 1 for b in binary], num_stages)
        else:
            raise NotImplementedError(f"partition method {method!r}")
        self.num_stages = num_stages
        logger.debug(f"pipeline partition ({method}): {self.parts}")
        return self.parts

    def stage_layers(self, stage_id):
        assert self.parts is not None, "call partition_layers first"
        return self.specs[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_owner(self, layer_idx):
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise ValueError(layer_idx)

    # ------------------------------------------------------------- tied layers
    def tied_groups(self):
        """Reference ``_index_tied_modules`` ``:468``: key → list of layer idx."""
        groups = {}
        for i, spec in enumerate(self.specs):
            if isinstance(spec, TiedLayerSpec):
                groups.setdefault(spec.key, []).append(i)
        return {k: v for k, v in groups.items() if len(v) > 1}
