"""Process topology — analog of reference ``runtime/pipe/topology.py``
(ProcessTopology ``:12``, PipeDataParallelTopology ``:232``,
PipeModelDataParallelTopology ``:244``, PipelineParallelGrid ``:251``).

On TPU the authoritative topology is the global Mesh (utils/groups.py); this
class provides the reference's *rank-grid calculus* — axis/coord mapping,
filtered rank queries — because PipelineModule partitioning and checkpoint
layouts are expressed in those terms."""

from collections import namedtuple
from itertools import product


class ProcessTopology:
    """Cartesian product of named axes → rank mapping (reference ``:12``)."""

    def __init__(self, axes, dims):
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        ranges = [range(d) for d in dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = dict(zip(axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs):
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"expected all axes {self.axes}, got {coord_kwargs}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", ), inner_sep="_",
                      outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis):
        """Groups of ranks that vary only along ``axis`` (reference ``:142``)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for coord in product(*ranges):
            other = dict(zip(other_axes, coord))
            ranks = [self.get_rank(**{axis: i}, **other)
                     for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        def match(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return [rank for coord, rank in self.mapping.items() if match(coord)]

    def get_axis_list(self, axis, idx):
        return self.filter_match(**{axis: idx})

    def world_size(self):
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """Reference ``:232``: (pipe, data) grid."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """Reference ``:244``: (pipe, data, model) grid."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Reference ``:251`` — axis-degree accessors over the global mesh.

    With mesh-axis groups (comm/backend.py) there are no communicator
    objects to build; this exposes the stage/dp ids and sizes the
    PipelineModule/engine need."""

    def __init__(self, topology=None, process_id=0):
        from ...utils import groups
        if topology is None:
            st = groups.get_mesh_state()
            topology = PipeDataParallelTopology(num_pp=st.pp, num_dp=st.dp *
                                                st.sp * st.tp)
        self._topo = topology
        self.global_rank = process_id
        self.world_size = topology.world_size()
        self.pipe_parallel_size = topology.get_dim("pipe")
        self.data_parallel_size = topology.get_dim("data")
        self.model_parallel_size = max(1, topology.get_dim("model"))
        coord = topology.get_coord(self.global_rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)

    def get_stage_id(self):
        return self.stage_id

    def get_data_parallel_id(self):
        return self.data_parallel_id

    def get_pipe_parallel_rank(self):
        return self.stage_id

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_rank(self):
        return self.data_parallel_id

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_rank(self):
        return 0

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def get_global_rank(self):
        return self.global_rank

    def topology(self):
        return self._topo

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id, **kwargs):
        coord = self._topo.get_coord(self.global_rank)
        d = coord._asdict()
        d.update(kwargs)
        d["pipe"] = stage_id
        return self._topo.get_rank(**d)
