"""Out-of-band point-to-point — reference ``runtime/pipe/p2p.py``.

The activation hot path is ``lax.ppermute`` INSIDE the fused pipeline
program (``engine.py``), so the reference's ``send``/``recv`` tensor calls
have no eager analog here.  What this module keeps is the *control-plane*
surface: host-side object exchange for debugging and elastic tooling
(reference ``send_obj``/``recv_obj`` at ``p2p.py:46``), riding the
coordination-service KV store via :mod:`deepspeed_tpu.comm`.
"""

from ... import comm as dist


def init_process_groups(grid=None):
    """Parity no-op: the mesh IS the process-group topology."""
    dist.ensure_runtime_initialized()


def can_send_recv():
    return dist.get_world_size() > 1


def send_obj(msg, dest, tag=0):
    """Reference ``p2p.py`` ``send_obj`` — picklable object to rank ``dest``."""
    dist.send_obj(msg, dest, tag=tag)


def recv_obj(sender, tag=0, timeout_s=300):
    """Reference ``p2p.py`` ``recv_obj`` — blocking object receive."""
    return dist.recv_obj(sender, tag=tag, timeout_s=timeout_s)


def send(tensor, dest_stage, tag=0):
    dist.send(tensor, dest_stage, tag=tag)   # raises with the design note


def recv(tensor, src_stage, tag=0):
    dist.recv(tensor, src_stage, tag=tag)    # raises with the design note
