"""Checkpoint save/load for the engine.

Analog of reference ``runtime/checkpoint_engine/`` (pluggable CheckpointEngine)
+ ``engine.py:3140 save_checkpoint`` / ``:2794 load_checkpoint`` layout:

    {save_dir}/{tag}/engine_state.json           — step counters, config hash
    {save_dir}/{tag}/model/…                     — orbax pytree (compute params)
    {save_dir}/{tag}/master/…                    — fp32 master (ZeRO "optim
                                                   states" shard analog)
    {save_dir}/{tag}/optim/…                     — optimizer moments
    {save_dir}/latest                            — tag file (reference `latest`)

Sharded arrays are written via orbax (tensorstore), which stores the *global*
array — so resume at a different dp/mesh "just works": universal-checkpoint
semantics (reference ``deepspeed/checkpoint/``) by construction.
"""

import json
import os

import jax
import numpy as np

from ..utils.logging import log_dist, logger


def _strip_lr_override(opt_state):
    """The ``lr_override`` leaf is ephemeral runtime state (a torch-API
    ``param_groups`` write), not training state — keep it OUT of the on-disk
    layout so checkpoints stay loadable across revisions that added it."""
    if hasattr(opt_state, "lr_override") and opt_state.lr_override is not None:
        return opt_state._replace(lr_override=None)
    return opt_state


def _reattach_lr_override(restored, current):
    if hasattr(restored, "lr_override") and \
            getattr(current, "lr_override", None) is not None:
        return restored._replace(lr_override=current.lr_override)
    return restored


def _pytree_save(path, tree):
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, tree, force=True)


def _pytree_save_async(path, tree):
    """Async orbax save (the reference's Nebula engine role: staging returns
    immediately, the write commits in the background).  Returns the
    checkpointer — callers must keep it alive and ``wait_until_finished``."""
    import orbax.checkpoint as ocp
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    ckptr.save(path, tree, force=True)
    return ckptr


class _AsyncSaveHandle:
    """Pending async checkpoint: ``wait()`` commits the `latest` tag only
    after every tree is durably written (Nebula's commit semantics)."""

    def __init__(self, checkpointers, latest_path=None, tag=None):
        self._ckptrs = checkpointers
        self._latest_path = latest_path
        self._tag = tag
        self._done = False

    def wait(self):
        if self._done:
            return
        errors = []
        try:
            for c in self._ckptrs:
                try:
                    c.wait_until_finished()
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(e)
                finally:
                    try:  # join orbax's commit threads even on failure
                        c.close()
                    except Exception:
                        pass
            if errors:
                # `latest` is NOT written: the checkpoint is not durable
                raise errors[0]
            if self._latest_path is not None:
                with open(self._latest_path, "w") as f:
                    f.write(str(self._tag))
        finally:
            self._done = True  # a failed commit must not wedge retries

    @property
    def done(self):
        return self._done


def _pytree_restore(path, template=None, shardings=None):
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    if template is not None:
        restore_args = jax.tree_util.tree_map(
            lambda x, s: ocp.ArrayRestoreArgs(
                sharding=s, global_shape=x.shape, dtype=x.dtype),
            template, shardings)
        return ckptr.restore(path, item=template, restore_args=restore_args)
    return ckptr.restore(path)


def collect_data_state(engine):
    """Sampler + legacy curriculum state to persist (reference
    engine.py:3329/:3401).  Shared by the monolithic and streamed save
    paths."""
    out = {}
    sampler = getattr(getattr(engine, "training_dataloader", None),
                      "data_sampler", None)
    if sampler is not None and hasattr(sampler, "state_dict"):
        out["data_sampler"] = sampler.state_dict()
    if getattr(engine, "curriculum_scheduler", None) is not None:
        out["curriculum_scheduler"] = engine.curriculum_scheduler.state_dict()
    return out


def restore_data_state(engine, state):
    """Inverse of collect_data_state (reference engine.py:2968): the
    curriculum must not restart easy and consumed samples must not be
    re-drawn.  Shared by the native, streamed, and universal load paths."""
    sampler = getattr(getattr(engine, "training_dataloader", None),
                      "data_sampler", None)
    if sampler is not None and "data_sampler" in state and \
            hasattr(sampler, "load_state_dict"):
        sampler.load_state_dict(state["data_sampler"])
    if getattr(engine, "curriculum_scheduler", None) is not None and \
            "curriculum_scheduler" in state:
        engine.curriculum_scheduler.load_state_dict(
            state["curriculum_scheduler"])


def save_engine_checkpoint(engine, save_dir, tag=None, client_state=None,
                           save_latest=True, async_save=False):
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    root = os.path.abspath(os.path.join(save_dir, str(tag)))
    os.makedirs(root, exist_ok=True)

    state = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "loss_scale": float(engine.scale_state.scale),
        "zero_stage": engine.zero_stage,
        "dp_world_size": engine.dp_world_size,
        "client_state": client_state or {},
    }
    if engine.lr_scheduler is not None and hasattr(engine.lr_scheduler,
                                                   "state_dict"):
        state["lr_scheduler"] = engine.lr_scheduler.state_dict()
    state.update(collect_data_state(engine))

    with open(os.path.join(root, "engine_state.json"), "w") as f:
        json.dump(state, f, indent=2)

    trees = [("model", engine.params)]
    if engine.master is not None:
        trees.append(("master", engine.master))
    if engine.opt_state is not None:
        trees.append(("optim", _strip_lr_override(engine.opt_state)))
    latest_path = (os.path.join(os.path.abspath(save_dir), "latest")
                   if save_latest else None)

    handle = None
    if async_save:
        handle = _AsyncSaveHandle(
            [_pytree_save_async(os.path.join(root, sub), tree)
             for sub, tree in trees],
            latest_path=latest_path, tag=tag)
    else:
        for sub, tree in trees:
            _pytree_save(os.path.join(root, sub), tree)
        if latest_path is not None:
            with open(latest_path, "w") as f:
                f.write(str(tag))

    # ship the recovery script into the checkpoint (reference engine.py:3540
    # _copy_recovery_script copies zero_to_fp32.py next to the shards)
    try:
        import shutil
        from ..checkpoint import zero_to_fp32 as _z2f
        shutil.copy2(_z2f.__file__,
                     os.path.join(os.path.abspath(save_dir), "zero_to_fp32.py"))
    except Exception:  # non-fatal: checkpoint itself is complete
        pass
    if handle is not None:
        log_dist(f"async checkpoint staged {root}", ranks=[0])
        return handle
    log_dist(f"saved checkpoint {root}", ranks=[0])
    return True


def load_engine_checkpoint(engine, load_dir, tag=None,
                           load_optimizer_states=True,
                           load_lr_scheduler_states=True,
                           load_module_only=False):
    load_dir = os.path.abspath(load_dir)
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            logger.warning(f"no 'latest' file at {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    root = os.path.join(load_dir, str(tag))
    if not os.path.isdir(root):
        logger.warning(f"checkpoint dir {root} missing; nothing loaded")
        return None, {}

    with open(os.path.join(root, "engine_state.json")) as f:
        state = json.load(f)

    engine.params = _pytree_restore(
        os.path.join(root, "model"), template=engine.params,
        shardings=engine.plan.param_shardings(engine.params))
    if load_module_only:
        # reference engine.py load_module_only path ends with
        # ``optimizer.refresh_fp32_params()``: the fp32 master must re-derive
        # from the just-loaded module weights — otherwise the next boundary
        # apply recasts params from the STALE master and silently reverts
        # the load.  NVMe-resident master first swaps back in (it would be
        # swapped in stale by the next step otherwise).
        if getattr(engine, "_state_on_nvme", False):
            engine._ensure_state_resident()
        if engine.master is not None:
            import jax.numpy as jnp
            engine.master = jax.tree_util.tree_map(
                lambda p, s: jax.device_put(p.astype(jnp.float32), s),
                engine.params, engine.plan.master_shardings(engine.master))
    if not load_module_only:
        if engine.master is not None and os.path.isdir(os.path.join(root, "master")):
            engine.master = _pytree_restore(
                os.path.join(root, "master"), template=engine.master,
                shardings=engine.plan.master_shardings(engine.master))
        if load_optimizer_states and engine.opt_state is not None and \
                os.path.isdir(os.path.join(root, "optim")):
            target = engine.master if engine.master is not None else engine.params
            restored = _pytree_restore(
                os.path.join(root, "optim"),
                template=_strip_lr_override(engine.opt_state),
                shardings=_strip_lr_override(
                    engine._opt_state_shardings(target)))
            engine.opt_state = _reattach_lr_override(restored,
                                                     engine.opt_state)
        if load_lr_scheduler_states and engine.lr_scheduler is not None and \
                "lr_scheduler" in state and hasattr(engine.lr_scheduler,
                                                    "load_state_dict"):
            engine.lr_scheduler.load_state_dict(state["lr_scheduler"])

    restore_data_state(engine, state)

    engine.global_steps = state["global_steps"]
    engine.global_samples = state["global_samples"]
    engine.micro_steps = state["micro_steps"]
    engine.skipped_steps = state["skipped_steps"]
    import jax.numpy as jnp
    from .loss_scaler import commit_scale_state
    engine.scale_state = commit_scale_state(
        engine.mesh,
        engine.scale_state._replace(
            scale=jnp.asarray(state["loss_scale"], jnp.float32)))
    log_dist(f"loaded checkpoint {root}", ranks=[0])
    return root, state.get("client_state", {})
