"""Checkpoint save/load for the engine.

Analog of reference ``runtime/checkpoint_engine/`` (pluggable CheckpointEngine)
+ ``engine.py:3140 save_checkpoint`` / ``:2794 load_checkpoint`` layout:

    {save_dir}/{tag}/engine_state.json           — step counters, config hash
    {save_dir}/{tag}/model/…                     — orbax pytree (compute params)
    {save_dir}/{tag}/master/…                    — fp32 master (ZeRO "optim
                                                   states" shard analog)
    {save_dir}/{tag}/optim/…                     — optimizer moments
    {save_dir}/latest                            — tag file (reference `latest`)

Sharded arrays are written via orbax (tensorstore), which stores the *global*
array — so resume at a different dp/mesh "just works": universal-checkpoint
semantics (reference ``deepspeed/checkpoint/``) by construction.
"""

import json
import os
import time
import zlib

import jax
import numpy as np

from .. import telemetry as _telemetry
from ..utils.fault_injection import fault_point
from ..utils.logging import log_dist, logger

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1


# --------------------------------------------------------------- integrity
def _retry(fn, attempts, backoff, what):
    """Retry ``fn`` on transient failures with exponential backoff
    (reference Nebula engine retries commit the same way).  ``attempts`` is
    the number of RE-tries; 0 = fail on the first error."""
    for i in range(attempts + 1):
        try:
            return fn()
        except (OSError, IOError) as e:
            if i >= attempts:
                raise
            delay = backoff * (2 ** i)
            logger.warning("checkpoint %s failed (%s: %s); retry %d/%d "
                           "in %.2fs", what, type(e).__name__, e, i + 1,
                           attempts, delay)
            if delay > 0:
                time.sleep(delay)


def _file_crc32(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(b, crc)


def _walk_tag_files(root):
    """Relative paths of every file in a tag dir, manifest excluded."""
    out = []
    for dirpath, _, files in os.walk(root):
        for name in files:
            rel = os.path.relpath(os.path.join(dirpath, name), root)
            if rel != MANIFEST_NAME and not rel.endswith(".tmp"):
                out.append(rel)
    return sorted(out)


def write_manifest(root, config_hash=None, tag=None):
    """Commit the tag's integrity manifest — file list + sizes + content
    checksums + config hash — written to a temp file and atomically
    renamed, AFTER every tree write finished: its presence certifies the
    tag is complete, its checksums certify the bytes."""
    files = {}
    for rel in _walk_tag_files(root):
        path = os.path.join(root, rel)
        files[rel] = {"size": os.path.getsize(path),
                      "crc32": _file_crc32(path)}
    manifest = {"version": MANIFEST_VERSION, "tag": str(tag),
                "config_hash": config_hash, "files": files}
    # pid-unique tmp: every process may commit (node-local-storage layouts
    # need a latest/manifest per host) and shared-fs ranks must not
    # interleave writes into one tmp file
    tmp = os.path.join(root, f"{MANIFEST_NAME}.{os.getpid()}.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(root, MANIFEST_NAME))
    return manifest


def verify_checkpoint_tag(root):
    """Verify a tag dir against its manifest.

    Returns ``(status, detail)`` with status one of ``"valid"`` (manifest
    present, every file matches size+checksum), ``"legacy"`` (no manifest —
    a pre-integrity checkpoint OR a partial write that died before commit;
    indistinguishable, so callers prefer any verified tag over it), or
    ``"corrupt"`` (manifest present but unreadable / files missing or
    mismatched)."""
    if _telemetry.enabled:
        t0 = time.perf_counter()
        try:
            return _verify_checkpoint_tag(root)
        finally:
            _telemetry.observe("checkpoint/verify_seconds",
                               time.perf_counter() - t0,
                               help="manifest CRC-walk duration")
    return _verify_checkpoint_tag(root)


def _verify_checkpoint_tag(root):
    if not os.path.isdir(root):
        return "corrupt", "tag directory missing"
    mpath = os.path.join(root, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return "legacy", "no manifest (pre-integrity save or partial write)"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (ValueError, KeyError, OSError) as e:
        return "corrupt", f"unreadable manifest: {e}"
    for rel, meta in files.items():
        path = os.path.join(root, rel)
        try:
            if not os.path.exists(path):
                return "corrupt", f"missing file {rel}"
            size = os.path.getsize(path)
            if size != meta["size"]:
                return "corrupt", (f"size mismatch {rel}: "
                                   f"{size} != {meta['size']}")
            if _file_crc32(path) != meta["crc32"]:
                return "corrupt", f"checksum mismatch {rel}"
        except OSError as e:
            # a file vanishing mid-check (concurrent retention on another
            # rank, fs hiccup) is a failed verification, not a crash
            return "corrupt", f"unreadable file {rel}: {e}"
    return "valid", "ok"


def _tag_sort_key(load_dir, tag):
    """Newest-first ordering: the step counter recorded in the tag's own
    engine_state.json (mtime breaks ties / stands in when unreadable)."""
    root = os.path.join(load_dir, tag)
    step = -1
    try:
        with open(os.path.join(root, "engine_state.json")) as f:
            step = int(json.load(f).get("global_steps", -1))
    except (OSError, ValueError, TypeError):
        pass
    try:
        mtime = os.path.getmtime(root)
    except OSError:
        mtime = 0.0
    return (step, mtime)


def list_checkpoint_tags(load_dir):
    """Tag subdirs (anything holding an engine_state.json or a manifest),
    newest first."""
    try:
        names = os.listdir(load_dir)
    except OSError:
        return []
    tags = [n for n in names
            if os.path.isdir(os.path.join(load_dir, n)) and
            (os.path.exists(os.path.join(load_dir, n, "engine_state.json"))
             or os.path.exists(os.path.join(load_dir, n, MANIFEST_NAME)))]
    return sorted(tags, key=lambda t: _tag_sort_key(load_dir, t),
                  reverse=True)


def find_latest_valid_tag(load_dir, exclude=(), not_newer_than=None):
    """Newest tag that passes manifest verification; falls back to the
    newest legacy (manifest-less) tag only when NO verified tag exists.
    ``not_newer_than``: a tag name — candidates newer than it (step counter,
    mtime tiebreak) are skipped, so a fallback can only roll BACK."""
    ceiling = (_tag_sort_key(load_dir, not_newer_than)
               if not_newer_than is not None else None)

    def newer_than_ceiling(key):
        if ceiling is None:
            return False
        if ceiling[0] < 0:
            # the reference tag's step counter is unreadable (that is often
            # WHY we are falling back) — compare by mtime alone, or every
            # older valid tag would count as "newer" than step -1
            return key[1] > ceiling[1]
        return key > ceiling

    legacy = None
    for tag in list_checkpoint_tags(load_dir):
        if tag in exclude:
            continue
        if newer_than_ceiling(_tag_sort_key(load_dir, tag)):
            continue
        status, _ = verify_checkpoint_tag(os.path.join(load_dir, tag))
        if status == "valid":
            return tag, "valid"
        if status == "legacy" and legacy is None:
            legacy = tag
    return (legacy, "legacy") if legacy is not None else (None, None)


def _tag_committed(root):
    """Cheap committed-ness check for retention: a readable manifest.
    Retention must not re-CRC every byte of every retained tag on each
    save — full verification is the LOADER's job; GC only needs to know
    the tag finished its commit."""
    try:
        with open(os.path.join(root, MANIFEST_NAME)) as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


def prune_checkpoint_tags(save_dir, keep_n, protect=None):
    """Bounded retention: delete *committed* tags (manifest present)
    beyond the newest ``keep_n``.  Uncommitted/corrupt tags are never
    deleted (the loader skips them anyway, and deleting data because its
    verification failed would be exactly backwards); the newest committed
    tag — plus ``protect``, the tag just written — always survives."""
    if not keep_n or keep_n < 1:
        return []
    try:
        committed = [t for t in list_checkpoint_tags(save_dir)
                     if _tag_committed(os.path.join(save_dir, t))]
        doomed = [t for t in committed[keep_n:] if t != protect]
    except OSError as e:   # retention must never fail a committed save
        logger.warning("checkpoint retention: scan failed (%s); skipped", e)
        return []
    import shutil
    removed = []
    for tag in doomed:
        try:
            shutil.rmtree(os.path.join(save_dir, tag))
            removed.append(tag)
        except OSError as e:
            logger.warning("checkpoint retention: could not remove %s (%s)",
                           tag, e)
    if removed:
        log_dist(f"checkpoint retention: pruned {removed} "
                 f"(keep_n={keep_n})", ranks=[0])
    return removed


def _strip_lr_override(opt_state):
    """The ``lr_override`` leaf is ephemeral runtime state (a torch-API
    ``param_groups`` write), not training state — keep it OUT of the on-disk
    layout so checkpoints stay loadable across revisions that added it."""
    if hasattr(opt_state, "lr_override") and opt_state.lr_override is not None:
        return opt_state._replace(lr_override=None)
    return opt_state


def _reattach_lr_override(restored, current):
    if hasattr(restored, "lr_override") and \
            getattr(current, "lr_override", None) is not None:
        return restored._replace(lr_override=current.lr_override)
    return restored


def _write_latest(latest_path, tag):
    tmp = f"{latest_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(str(tag))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, latest_path)


def _pytree_save(path, tree):
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, tree, force=True)


def _pytree_save_async(path, tree):
    """Async orbax save (the reference's Nebula engine role: staging returns
    immediately, the write commits in the background).  Returns the
    checkpointer — callers must keep it alive and ``wait_until_finished``."""
    import orbax.checkpoint as ocp
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    ckptr.save(path, tree, force=True)
    return ckptr


class _AsyncSaveHandle:
    """Pending async checkpoint: ``wait()`` commits manifest + `latest` tag
    only after every tree is durably written (Nebula's commit semantics) and
    re-raises any background-write exception — a failed async save must
    never be silently treated as durable."""

    def __init__(self, checkpointers, latest_path=None, tag=None,
                 root=None, config_hash=None, integrity=False,
                 keep_n=0, save_dir=None, retries=0, backoff=0.0):
        self._ckptrs = checkpointers
        self._latest_path = latest_path
        self._tag = tag
        self._root = root
        self._config_hash = config_hash
        self._integrity = integrity
        self._keep_n = keep_n
        self._save_dir = save_dir
        self._retries = retries
        self._backoff = backoff
        self._done = False

    def wait(self):
        if self._done:
            return
        t0 = time.perf_counter()
        errors = []
        try:
            for c in self._ckptrs:
                try:
                    c.wait_until_finished()
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(e)
                finally:
                    try:  # join orbax's commit threads even on failure
                        c.close()
                    except Exception:
                        pass
            if errors:
                # neither manifest nor `latest` is written: the checkpoint
                # is not durable and the previous valid tag stays current.
                # Background-write failures cannot be retried here — the
                # staged device buffers may have been donated away by
                # subsequent train steps — so save_retries covers staging
                # and the commit files only on the async path (the sync
                # path retries the tree writes themselves).
                logger.error(
                    "async checkpoint %s FAILED in background write (%s); "
                    "tag not committed — the previous valid tag remains "
                    "the resume target", self._tag, errors[0])
                raise errors[0]
            if self._integrity and self._root is not None:
                _retry(lambda: write_manifest(self._root, self._config_hash,
                                              self._tag),
                       self._retries, self._backoff, "manifest commit")
            if self._latest_path is not None:
                _retry(lambda: _write_latest(self._latest_path, self._tag),
                       self._retries, self._backoff, "latest commit")
            fault_point("ckpt.committed", tag=self._tag, root=self._root)
            if self._integrity and self._save_dir is not None:
                prune_checkpoint_tags(self._save_dir, self._keep_n,
                                      protect=str(self._tag))
        finally:
            self._done = True  # a failed commit must not wedge retries
            if _telemetry.enabled:
                _telemetry.observe("checkpoint/async_commit_seconds",
                                   time.perf_counter() - t0,
                                   help="async save wait-to-durable time")

    @property
    def done(self):
        return self._done


def _pytree_restore(path, template=None, shardings=None):
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    if template is not None:
        restore_args = jax.tree_util.tree_map(
            lambda x, s: ocp.ArrayRestoreArgs(
                sharding=s, global_shape=x.shape, dtype=x.dtype),
            template, shardings)
        return ckptr.restore(path, item=template, restore_args=restore_args)
    return ckptr.restore(path)


def collect_data_state(engine):
    """Sampler + legacy curriculum state to persist (reference
    engine.py:3329/:3401).  Shared by the monolithic and streamed save
    paths."""
    out = {}
    sampler = getattr(getattr(engine, "training_dataloader", None),
                      "data_sampler", None)
    if sampler is not None and hasattr(sampler, "state_dict"):
        out["data_sampler"] = sampler.state_dict()
    if getattr(engine, "curriculum_scheduler", None) is not None:
        out["curriculum_scheduler"] = engine.curriculum_scheduler.state_dict()
    return out


def restore_data_state(engine, state):
    """Inverse of collect_data_state (reference engine.py:2968): the
    curriculum must not restart easy and consumed samples must not be
    re-drawn.  Shared by the native, streamed, and universal load paths."""
    sampler = getattr(getattr(engine, "training_dataloader", None),
                      "data_sampler", None)
    if sampler is not None and "data_sampler" in state and \
            hasattr(sampler, "load_state_dict"):
        sampler.load_state_dict(state["data_sampler"])
    if getattr(engine, "curriculum_scheduler", None) is not None and \
            "curriculum_scheduler" in state:
        engine.curriculum_scheduler.load_state_dict(
            state["curriculum_scheduler"])


def save_engine_checkpoint(engine, save_dir, tag=None, client_state=None,
                           save_latest=True, async_save=False):
    if _telemetry.enabled:
        t0 = time.perf_counter()
        with _telemetry.span("checkpoint_save", cat="checkpoint",
                             tag=str(tag), async_save=bool(async_save)):
            out = _save_engine_checkpoint(engine, save_dir, tag,
                                          client_state, save_latest,
                                          async_save)
        # async: this times the staging (device_get + dispatch), the commit
        # is timed by _AsyncSaveHandle.wait
        _telemetry.observe("checkpoint/save_seconds",
                           time.perf_counter() - t0,
                           help="checkpoint save (sync) / staging (async)")
        _telemetry.counter("checkpoint/saves").inc()
        return out
    return _save_engine_checkpoint(engine, save_dir, tag, client_state,
                                   save_latest, async_save)


def _save_engine_checkpoint(engine, save_dir, tag, client_state,
                            save_latest, async_save):
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    root = os.path.abspath(os.path.join(save_dir, str(tag)))
    os.makedirs(root, exist_ok=True)

    state = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "loss_scale": float(engine.scale_state.scale),
        "zero_stage": engine.zero_stage,
        "dp_world_size": engine.dp_world_size,
        "client_state": client_state or {},
    }
    if engine.lr_scheduler is not None and hasattr(engine.lr_scheduler,
                                                   "state_dict"):
        state["lr_scheduler"] = engine.lr_scheduler.state_dict()
    state.update(collect_data_state(engine))

    with open(os.path.join(root, "engine_state.json"), "w") as f:
        json.dump(state, f, indent=2)

    trees = [("model", engine.params)]
    if engine.master is not None:
        trees.append(("master", engine.master))
    if engine.opt_state is not None:
        trees.append(("optim", _strip_lr_override(engine.opt_state)))
    latest_path = (os.path.join(os.path.abspath(save_dir), "latest")
                   if save_latest else None)

    ic = engine._config.resilience_config.checkpoint_integrity
    config_hash = engine._config.config_hash()
    # one manifest/prune per checkpoint, not per rank: on a shared fs the
    # CRC walk re-reads every byte of the tag, so world_size× of it is pure
    # redundant I/O (node-local layouts need every host to commit its own)
    commits_integrity = (ic.enabled and
                         (jax.process_index() == 0
                          or engine._config.use_node_local_storage))

    def saved_tree(sub, tree, async_):
        def once():
            fault_point("ckpt.save_tree", tag=tag, sub=sub)
            if async_:
                return _pytree_save_async(os.path.join(root, sub), tree)
            return _pytree_save(os.path.join(root, sub), tree)
        return _retry(once, ic.save_retries, ic.retry_backoff,
                      f"write of {tag}/{sub}")

    handle = None
    if async_save:
        ckptrs = []
        for sub, tree in trees:
            ckptrs.append(saved_tree(sub, tree, async_=True))
            fault_point("ckpt.mid_write", tag=tag, root=root, sub=sub)
        handle = _AsyncSaveHandle(
            ckptrs, latest_path=latest_path, tag=tag, root=root,
            config_hash=config_hash, integrity=commits_integrity,
            keep_n=ic.keep_n, save_dir=os.path.abspath(save_dir),
            retries=ic.save_retries, backoff=ic.retry_backoff)
    else:
        for sub, tree in trees:
            saved_tree(sub, tree, async_=False)
            fault_point("ckpt.mid_write", tag=tag, root=root, sub=sub)
        # commit order matters: manifest BEFORE `latest` — `latest` must
        # never name a tag whose completeness certificate does not exist
        if commits_integrity:
            _retry(lambda: write_manifest(root, config_hash, tag),
                   ic.save_retries, ic.retry_backoff, "manifest commit")
        if latest_path is not None:
            _retry(lambda: _write_latest(latest_path, tag),
                   ic.save_retries, ic.retry_backoff, "latest commit")
        fault_point("ckpt.committed", tag=tag, root=root)
        if commits_integrity:
            prune_checkpoint_tags(os.path.abspath(save_dir), ic.keep_n,
                                  protect=str(tag))

    # ship the recovery script into the checkpoint (reference engine.py:3540
    # _copy_recovery_script copies zero_to_fp32.py next to the shards)
    try:
        import shutil
        from ..checkpoint import zero_to_fp32 as _z2f
        shutil.copy2(_z2f.__file__,
                     os.path.join(os.path.abspath(save_dir), "zero_to_fp32.py"))
    except Exception:  # non-fatal: checkpoint itself is complete
        pass
    if handle is not None:
        log_dist(f"async checkpoint staged {root}", ranks=[0])
        return handle
    log_dist(f"saved checkpoint {root}", ranks=[0])
    return True


def _resolve_load_tag(engine, load_dir, tag):
    """Resolve + verify the tag to load.  A corrupt or partial tag (failed
    manifest verification) logs LOUDLY and falls back to the newest valid
    tag instead of crashing — or worse, silently loading garbage weights
    into a healthy optimizer state.  Returns the tag or None."""
    requested = tag
    integrity = engine._config.resilience_config.checkpoint_integrity.enabled
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip() or None
        if tag is None:
            # no auto-recovery here: `save_latest=False` snapshots are
            # SUPPOSED to be invisible to auto-resume, and a dir whose only
            # tags are partial first saves must mean a clean fresh start —
            # but tell the operator what IS recoverable
            hint, status = (find_latest_valid_tag(load_dir) if integrity
                            else (None, None))
            logger.warning(
                f"no 'latest' file at {load_dir}; nothing loaded"
                + (f" (a {status} tag '{hint}' exists — pass "
                   f"tag={hint!r} to resume from it)"
                   if hint is not None else ""))
            return None

    if not integrity:
        if not os.path.isdir(os.path.join(load_dir, str(tag))):
            logger.warning(f"checkpoint dir {load_dir}/{tag} missing; "
                           "nothing loaded")
            return None
        return tag

    # an EXPLICITLY requested tag may only ever fall back to an OLDER tag:
    # the user naming 'step1000' is often a deliberate rollback away from a
    # newer state — silently rolling FORWARD to the newest valid tag would
    # hand back exactly the state they were escaping
    ceiling = str(tag) if requested is not None else None

    status, detail = verify_checkpoint_tag(os.path.join(load_dir, str(tag)))
    if status == "valid":
        return tag
    if status == "legacy":
        # no manifest: either a pre-integrity checkpoint (fine) or a save
        # that died before commit (poison).  Prefer a VERIFIED tag (never
        # newer than an explicit request); load the legacy one best-effort
        # only when none exists.
        fallback, fstatus = find_latest_valid_tag(load_dir,
                                                  exclude=(str(tag),),
                                                  not_newer_than=ceiling)
        if fstatus == "valid":
            logger.error(
                f"CHECKPOINT INTEGRITY: tag '{tag}' at {load_dir} has no "
                f"manifest ({detail}) — treating as partial; falling back "
                f"to newest verified tag '{fallback}'")
            _fallback_event(engine, load_dir, str(tag), fallback)
            return fallback
        if os.path.isdir(os.path.join(load_dir, str(tag))):
            logger.warning(
                f"checkpoint tag '{tag}' has no integrity manifest "
                f"({detail}); loading best-effort (legacy layout)")
            return tag
        logger.warning(f"checkpoint dir {load_dir}/{tag} missing; "
                       "nothing loaded")
        return None
    # corrupt: manifest says the bytes are wrong
    logger.error(
        f"CHECKPOINT INTEGRITY: tag '{tag}' at {load_dir} FAILED "
        f"verification ({detail}); refusing to load it")
    fallback, fstatus = find_latest_valid_tag(load_dir, exclude=(str(tag),),
                                              not_newer_than=ceiling)
    if fallback is None:
        logger.error(f"no other usable tag under {load_dir}; nothing loaded"
                     + ("" if requested is None else
                        f" (requested tag was '{requested}')"))
        return None
    logger.error(f"RECOVERY: falling back to newest {fstatus} tag "
                 f"'{fallback}'")
    _fallback_event(engine, load_dir, str(tag), fallback)
    return fallback


def _fallback_event(engine, load_dir, bad_tag, good_tag):
    """Surface a rollback through the monitor so dashboards see silent
    corruption events (reference monitor event stream role)."""
    monitor = getattr(engine, "monitor", None)
    if monitor is not None and getattr(monitor, "enabled", False):
        monitor.write_resilience_events(
            [("ckpt_fallback", 1.0)], step=engine.global_samples)
    if _telemetry.enabled:
        _telemetry.counter("checkpoint/rollbacks",
                           help="loads that fell back to an older valid "
                           "tag").inc()
    logger.error("checkpoint rollback: %s/%s → %s", load_dir, bad_tag,
                 good_tag)


def load_engine_checkpoint(engine, load_dir, tag=None,
                           load_optimizer_states=True,
                           load_lr_scheduler_states=True,
                           load_module_only=False):
    if _telemetry.enabled:
        t0 = time.perf_counter()
        with _telemetry.span("checkpoint_load", cat="checkpoint",
                             tag=str(tag)):
            out = _load_engine_checkpoint(engine, load_dir, tag,
                                          load_optimizer_states,
                                          load_lr_scheduler_states,
                                          load_module_only)
        _telemetry.observe("checkpoint/load_seconds",
                           time.perf_counter() - t0,
                           help="checkpoint load incl. tag verification")
        return out
    return _load_engine_checkpoint(engine, load_dir, tag,
                                   load_optimizer_states,
                                   load_lr_scheduler_states,
                                   load_module_only)


def _load_engine_checkpoint(engine, load_dir, tag,
                            load_optimizer_states,
                            load_lr_scheduler_states,
                            load_module_only):
    load_dir = os.path.abspath(load_dir)
    tag = _resolve_load_tag(engine, load_dir, tag)
    if tag is None:
        return None, {}
    root = os.path.join(load_dir, str(tag))
    if not os.path.isdir(root):
        logger.warning(f"checkpoint dir {root} missing; nothing loaded")
        return None, {}

    with open(os.path.join(root, "engine_state.json")) as f:
        state = json.load(f)

    mpath = os.path.join(root, MANIFEST_NAME)
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                saved_hash = json.load(f).get("config_hash")
        except (OSError, ValueError):
            saved_hash = None
        if saved_hash and saved_hash != engine._config.config_hash():
            logger.warning(
                f"checkpoint {root} was saved under a different config "
                f"(hash {saved_hash} != {engine._config.config_hash()}); "
                "resuming anyway — expected after an elastic rescale, "
                "suspicious otherwise")

    engine.params = _pytree_restore(
        os.path.join(root, "model"), template=engine.params,
        shardings=engine.plan.param_shardings(engine.params))
    if load_module_only:
        # reference engine.py load_module_only path ends with
        # ``optimizer.refresh_fp32_params()``: the fp32 master must re-derive
        # from the just-loaded module weights — otherwise the next boundary
        # apply recasts params from the STALE master and silently reverts
        # the load.  NVMe-resident master first swaps back in (it would be
        # swapped in stale by the next step otherwise).
        if getattr(engine, "_state_on_nvme", False):
            engine._ensure_state_resident()
        if engine.master is not None:
            import jax.numpy as jnp
            engine.master = jax.tree_util.tree_map(
                lambda p, s: jax.device_put(p.astype(jnp.float32), s),
                engine.params, engine.plan.master_shardings(engine.master))
    if not load_module_only:
        if engine.master is not None and os.path.isdir(os.path.join(root, "master")):
            engine.master = _pytree_restore(
                os.path.join(root, "master"), template=engine.master,
                shardings=engine.plan.master_shardings(engine.master))
        if load_optimizer_states and engine.opt_state is not None and \
                os.path.isdir(os.path.join(root, "optim")):
            target = engine.master if engine.master is not None else engine.params
            restored = _pytree_restore(
                os.path.join(root, "optim"),
                template=_strip_lr_override(engine.opt_state),
                shardings=_strip_lr_override(
                    engine._opt_state_shardings(target)))
            engine.opt_state = _reattach_lr_override(restored,
                                                     engine.opt_state)
        if load_lr_scheduler_states and engine.lr_scheduler is not None and \
                "lr_scheduler" in state and hasattr(engine.lr_scheduler,
                                                    "load_state_dict"):
            engine.lr_scheduler.load_state_dict(state["lr_scheduler"])

    restore_data_state(engine, state)

    engine.global_steps = state["global_steps"]
    engine.global_samples = state["global_samples"]
    engine.micro_steps = state["micro_steps"]
    engine.skipped_steps = state["skipped_steps"]
    import jax.numpy as jnp
    from .loss_scaler import commit_scale_state
    engine.scale_state = commit_scale_state(
        engine.mesh,
        engine.scale_state._replace(
            scale=jnp.asarray(state["loss_scale"], jnp.float32)))
    log_dist(f"loaded checkpoint {root}", ranks=[0])
    return root, state.get("client_state", {})
