"""Error-feedback 1-bit compressed allreduce (reference
``runtime/comm/nccl.py:51 compressed_allreduce`` / ``compressed.py:13
CompressedBackend`` + the packbits native op ``csrc/xpu/packbits``).

The 1-bit optimizers communicate the *sign* of the (error-compensated)
momentum plus one fp32 scale per tensor — 1/32 the allreduce volume — with
local error feedback so the quantization noise is re-injected next step
(Bernstein et al. signSGD-with-majority / 1-bit Adam).

Wire scheme (2-stage, like the reference):
  stage 1: each worker packs sign bits (8/byte) and all-to-alls chunk j to
           worker j with its scale; worker j decodes and averages its chunk
           ("server" role), carrying a server-side error term.
  stage 2: each worker re-compresses its averaged chunk and all-gathers —
           every worker ends with the identical averaged tensor.

Everything is axis-name collectives, so it runs inside ``shard_map`` over the
dp mesh axes (SPMD) — no NCCL/MPI backend objects needed; ``CompressedBackend``
is a thin parity shim exposing the reference's class API.
"""

import numpy as np

import jax
import jax.numpy as jnp

_POW2 = (1 << np.arange(8)).astype(np.uint8)  # bit i → 2^i


def pack_signs(bits):
    """bool[k*8] → uint8[k] (packbits; bit i of byte j = bits[8j+i])."""
    b = bits.reshape(-1, 8).astype(jnp.uint8)
    return (b * _POW2).sum(axis=1).astype(jnp.uint8)


def unpack_signs(packed):
    """uint8[k] → float[k*8] of ±1."""
    bits = (packed[:, None] >> np.arange(8).astype(np.uint8)) & 1
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def _l2(x):
    return jnp.sqrt(jnp.sum(x.astype(jnp.float32)**2))


def compressed_allreduce(x, worker_error, server_error, ax_names, n):
    """Inside-shard_map 1-bit averaged allreduce with error feedback.

    Args:
      x: local tensor (any shape); all workers contribute, result is the
         (approximate) mean across the ``ax_names`` mesh axes.
      worker_error: f32[padded_size] per-worker compression residual.
      server_error: f32[padded_size // n] per-worker chunk residual.
      ax_names: dp mesh axis names; n: their total size.

    Returns ``(avg, new_worker_error, new_server_error)``; avg has x's
    shape/dtype, identical on every worker.  State sizes come from
    :func:`error_shapes`.
    """
    if not ax_names or n <= 1:
        # single worker: the mean is the input; nothing to compress
        return x, worker_error, server_error
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    padded = worker_error.shape[0]
    flat = jnp.pad(flat, (0, padded - flat.shape[0]))

    # ---- worker compression
    corrected = flat + worker_error
    scale = _l2(corrected) / jnp.sqrt(jnp.float32(padded))
    signs = corrected >= 0
    new_worker_error = corrected - scale * (signs.astype(jnp.float32) * 2 - 1)
    packed = pack_signs(signs).reshape(n, -1)  # [n, chunk/8]

    # ---- exchange: chunk j → worker j; scales to everyone
    recv = jax.lax.all_to_all(packed, ax_names, split_axis=0, concat_axis=0)
    scales = jax.lax.all_gather(scale, ax_names)  # [n]
    decoded = jax.vmap(unpack_signs)(recv) * scales[:, None]  # [n, chunk]
    chunk_avg = jnp.mean(decoded, axis=0)

    # ---- server compression of my averaged chunk
    corrected2 = chunk_avg + server_error
    scale2 = _l2(corrected2) / jnp.sqrt(jnp.float32(corrected2.shape[0]))
    signs2 = corrected2 >= 0
    new_server_error = corrected2 - scale2 * (
        signs2.astype(jnp.float32) * 2 - 1)
    packed2 = pack_signs(signs2)

    # ---- gather: every worker reconstructs the full averaged tensor
    g_p = jax.lax.all_gather(packed2, ax_names)     # [n, chunk/8]
    g_s = jax.lax.all_gather(scale2, ax_names)      # [n]
    full = (jax.vmap(unpack_signs)(g_p) * g_s[:, None]).reshape(-1)
    out = full[:int(np.prod(shape, dtype=np.int64))].reshape(shape)
    return out.astype(dtype), new_worker_error, new_server_error


def error_shapes(numel, n):
    """(worker_error_size, server_error_size): numel padded so each of the n
    chunks holds a whole number of bytes of sign bits."""
    chunk = -(-numel // n)
    chunk += (-chunk) % 8
    return chunk * n, chunk


class CompressedBackend:
    """Parity shim for reference ``runtime/comm/compressed.py:13`` — the
    functional collective above is the real implementation; this class holds
    per-tensor error state for library users driving it from the host.

    ``compressed_allreduce(x)`` takes the per-worker contributions as one
    global array with a leading worker axis ``[n, *shape]`` (sharded or not)
    and returns the error-compensated mean — the SPMD analog of every rank
    passing its local tensor."""

    def __init__(self, ax_names=None, mesh=None):
        from jax.sharding import Mesh, PartitionSpec as P
        if mesh is None:
            from ...utils import groups
            mesh = groups.get_global_mesh()
        if ax_names is None:
            ax_names = tuple(a for a in ("dp", "ep")
                             if mesh.shape.get(a, 1) > 1)
        self.mesh = mesh
        self.ax_names = tuple(ax_names)
        self.n = 1
        for a in self.ax_names:
            self.n *= mesh.shape[a]
        self._errors = {}
        self._P = P

    def compressed_allreduce(self, x_stacked, key=0):
        from jax import shard_map
        P = self._P
        n = self.n
        numel = int(np.prod(x_stacked.shape[1:], dtype=np.int64))
        we_size, se_size = error_shapes(numel, n)
        # error state is per (key, size) — mixing residuals across tensors of
        # different sizes would crash the pad or corrupt the feedback
        key = (key, numel)
        we, se = self._errors.get(
            key, (jnp.zeros((n, we_size), jnp.float32),
                  jnp.zeros((n, se_size), jnp.float32)))

        def body(xl, wel, sel):
            out, w2, s2 = compressed_allreduce(xl[0], wel[0], sel[0],
                                               self.ax_names, n)
            return out[None], w2[None], s2[None]

        nd = x_stacked.ndim - 1
        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(P(self.ax_names, *([None] * nd)),
                      P(self.ax_names, None), P(self.ax_names, None)),
            out_specs=(P(self.ax_names, *([None] * nd)),
                       P(self.ax_names, None), P(self.ax_names, None)),
            check_vma=False)
        out, we, se = fn(x_stacked, we, se)
        self._errors[key] = (we, se)
        return out[0]
