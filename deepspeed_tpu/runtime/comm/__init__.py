"""Compressed communication backends (reference ``deepspeed/runtime/comm/``:
``nccl.py``/``mpi.py``/``compressed.py`` 1-bit backends + ``coalesced_
collectives.py`` quantized collectives — the quantized ZeRO++ collectives
live in ``runtime/zero/zeropp.py``)."""

from .compressed import (CompressedBackend, compressed_allreduce, pack_signs,
                         unpack_signs)
